"""Pipeline wire types: PreprocessedRequest and engine outputs.

The worker protocol is tokens-in/tokens-out (ref lib/llm/src/protocols/
common/preprocessor.rs:14 PreprocessedRequest): the frontend owns
tokenization and detokenization; workers see only token ids. Plain dicts on
the wire; this module documents + constructs them.
"""

from __future__ import annotations

import time
import uuid
from typing import Any

# PreprocessedRequest fields (dict keys):
#   token_ids: list[int]            - the tokenized prompt
#   sampling: {temperature, top_p, top_k, seed, frequency_penalty, ...}
#   stop_conditions: {max_tokens, stop: [str], stop_token_ids: [int],
#                     ignore_eos: bool, min_tokens: int}
#   eos_token_ids: list[int]
#   backend_instance_id: int | None - router override (direct pinning)
#   estimated_prefix_hit_num_blocks: int | None  - set by KV router
#   annotations: list[str]
#   disagg: {mode: "prefill"|"decode", kv_transfer: {...}} | None


def make_preprocessed_request(
    token_ids: list[int],
    *,
    max_tokens: int = 256,
    temperature: float | None = None,
    top_p: float | None = None,
    top_k: int | None = None,
    seed: int | None = None,
    stop: list[str] | None = None,
    stop_token_ids: list[int] | None = None,
    ignore_eos: bool = False,
    min_tokens: int = 0,
    eos_token_ids: list[int] | None = None,
    annotations: list[str] | None = None,
    logprobs: int | None = None,  # None=off, N=top-N alternatives
    guided: dict[str, Any] | None = None,  # grammar spec (guided/schema.py)
) -> dict[str, Any]:
    return {
        "token_ids": token_ids,
        "sampling": {
            k: v
            for k, v in {
                "temperature": temperature,
                "top_p": top_p,
                "top_k": top_k,
                "seed": seed,
            }.items()
            if v is not None
        },
        "stop_conditions": {
            "max_tokens": max_tokens,
            "stop": stop or [],
            "stop_token_ids": stop_token_ids or [],
            "ignore_eos": ignore_eos,
            "min_tokens": min_tokens,
        },
        "eos_token_ids": eos_token_ids or [],
        "output_options": {"logprobs": logprobs},
        "backend_instance_id": None,
        "estimated_prefix_hit_num_blocks": None,
        "annotations": annotations or [],
        "disagg": None,
        # guided decoding: {"kind", "regex", "key", "prompt_len"} — the
        # grammar the engine compiles to token masks; prompt_len marks
        # the original prompt end so resume paths can advance the
        # automaton over already-generated tokens
        "guided": guided,
    }


# Engine output (dict keys), per stream item (ref LLMEngineOutput):
#   token_ids: list[int]      - newly generated tokens (usually 1)
#   finish_reason: None | "stop" | "length" | "cancelled" | "error"
#   cum_log_probs / log_probs - optional
#   error: str                - when finish_reason == "error"


def new_request_id() -> str:
    return f"chatcmpl-{uuid.uuid4().hex[:24]}"


def now_unix() -> int:
    return int(time.time())
