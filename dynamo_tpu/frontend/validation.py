"""Typed request validation for the OpenAI surface.

Role of the reference's typed request layer (lib/async-openai/ forked
types + the 4xx paths of http/service/openai.rs): malformed bodies fail
at the EDGE with an OpenAI-style ``invalid_request_error`` naming the
offending param — not as a 500 from deep inside template rendering or
the engine. Kept as explicit checks over dicts rather than a schema
library: the checks ARE the documentation of what the surface accepts,
and the hot path stays allocation-light.
"""

from __future__ import annotations

import hashlib
import re
from typing import Any

__all__ = [
    "RequestValidationError",
    "validate_request",
    "validate_tenancy",
]

_ROLES = {"system", "developer", "user", "assistant", "tool"}
_CONTENT_PART_TYPES = {"text", "image_url", "video_url"}

# tenancy edge validation (overload-control plane): the tenant id rides
# wire headers, metric labels, and log lines — constrain it to a safe
# charset/length HERE so a hostile header can't smuggle label injection
# or unbounded cardinality into every downstream surface
_TENANT_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")
_PRIORITIES = ("interactive", "batch")


class RequestValidationError(ValueError):
    def __init__(self, message: str, param: str | None = None):
        super().__init__(message)
        self.param = param


def _fail(message: str, param: str | None = None) -> None:
    raise RequestValidationError(message, param)


def _check_number(
    body: dict, name: str, lo: float | None, hi: float | None,
    *, integer: bool = False,
) -> None:
    v = body.get(name)
    if v is None:
        return
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        _fail(f"'{name}' must be a number", name)
    if integer and not isinstance(v, int):
        _fail(f"'{name}' must be an integer", name)
    if lo is not None and v < lo:
        _fail(f"'{name}' must be >= {lo}", name)
    if hi is not None and v > hi:
        _fail(f"'{name}' must be <= {hi}", name)


def _check_common(body: dict) -> None:
    _check_number(body, "temperature", 0.0, 2.0)
    _check_number(body, "top_p", 0.0, 1.0)
    _check_number(body, "top_k", 0, None, integer=True)
    _check_number(body, "max_tokens", 1, None, integer=True)
    _check_number(body, "max_completion_tokens", 1, None, integer=True)
    _check_number(body, "min_tokens", 0, None, integer=True)
    _check_number(body, "seed", None, None, integer=True)
    _check_number(body, "top_logprobs", 0, 20, integer=True)
    if not isinstance(body.get("stream", False), bool):
        _fail("'stream' must be a boolean", "stream")
    stop = body.get("stop")
    if stop is not None:
        if isinstance(stop, str):
            pass
        elif isinstance(stop, list):
            if len(stop) > 4:
                _fail("'stop' accepts at most 4 sequences", "stop")
            if not all(isinstance(s, str) for s in stop):
                _fail("'stop' entries must be strings", "stop")
        else:
            _fail("'stop' must be a string or list of strings", "stop")


def _check_messages(body: dict) -> None:
    messages = body.get("messages")
    if not isinstance(messages, list) or not messages:
        _fail("'messages' must be a non-empty array", "messages")
    for i, m in enumerate(messages):
        where = f"messages[{i}]"
        if not isinstance(m, dict):
            _fail(f"'{where}' must be an object", where)
        role = m.get("role")
        if not isinstance(role, str) or role not in _ROLES:
            _fail(
                f"'{where}.role' must be one of {sorted(_ROLES)}",
                f"{where}.role",
            )
        content = m.get("content")
        if content is None:
            if role != "assistant" or not m.get("tool_calls"):
                _fail(f"'{where}.content' is required", f"{where}.content")
            continue
        if isinstance(content, str):
            continue
        if isinstance(content, list):
            for j, part in enumerate(content):
                pw = f"{where}.content[{j}]"
                if not isinstance(part, dict):
                    _fail(f"'{pw}' must be an object", pw)
                ptype = part.get("type")
                if ptype not in _CONTENT_PART_TYPES:
                    _fail(
                        f"'{pw}.type' must be one of "
                        f"{sorted(_CONTENT_PART_TYPES)}",
                        f"{pw}.type",
                    )
                if ptype == "text" and not isinstance(part.get("text"), str):
                    _fail(f"'{pw}.text' must be a string", f"{pw}.text")
                if ptype in ("image_url", "video_url"):
                    iu = part.get(ptype)
                    url = iu.get("url") if isinstance(iu, dict) else iu
                    if not isinstance(url, str) or not url:
                        _fail(
                            f"'{pw}.{ptype}.url' must be a non-empty "
                            "string", f"{pw}.{ptype}",
                        )
            continue
        _fail(
            f"'{where}.content' must be a string or array of parts",
            f"{where}.content",
        )


_RESPONSE_FORMAT_TYPES = {"text", "json_object", "json_schema"}


def _check_response_format(body: dict) -> None:
    """Structural checks for the guided-decoding surface: a malformed
    ``response_format`` must 400 at the edge, not surface as a 500 (or
    worse, be silently dropped) once the stream is running."""
    rf = body.get("response_format")
    if rf is None:
        return
    if not isinstance(rf, dict):
        _fail("'response_format' must be an object", "response_format")
    t = rf.get("type")
    if t not in _RESPONSE_FORMAT_TYPES:
        _fail(
            f"'response_format.type' must be one of "
            f"{sorted(_RESPONSE_FORMAT_TYPES)}",
            "response_format.type",
        )
    if t == "json_schema":
        js = rf.get("json_schema")
        if not isinstance(js, dict):
            _fail(
                "'response_format.json_schema' must be an object",
                "response_format.json_schema",
            )
        if not isinstance(js.get("schema"), dict):
            _fail(
                "'response_format.json_schema.schema' must be an object",
                "response_format.json_schema.schema",
            )


def _check_tool_choice(body: dict) -> None:
    tc = body.get("tool_choice")
    if tc is None:
        return
    if isinstance(tc, str):
        if tc not in ("none", "auto", "required"):
            _fail(
                "'tool_choice' must be 'none', 'auto', 'required' or a "
                "named function object",
                "tool_choice",
            )
        if tc == "required" and not body.get("tools"):
            _fail("'tool_choice: required' needs 'tools'", "tool_choice")
        return
    if not isinstance(tc, dict):
        _fail("'tool_choice' must be a string or object", "tool_choice")
    fn = tc.get("function")
    name = fn.get("name") if isinstance(fn, dict) else None
    if tc.get("type") != "function" or not isinstance(name, str) or not name:
        _fail(
            "'tool_choice' object must be "
            "{'type': 'function', 'function': {'name': ...}}",
            "tool_choice",
        )
    declared = {
        (t.get("function") or {}).get("name")
        for t in body.get("tools") or ()
        if isinstance(t, dict)
    }
    if name not in declared:
        _fail(
            f"'tool_choice' names unknown tool {name!r}",
            "tool_choice.function.name",
        )


def _check_tools(body: dict) -> None:
    tools = body.get("tools")
    if tools is None:
        return
    if not isinstance(tools, list):
        _fail("'tools' must be an array", "tools")
    for i, t in enumerate(tools):
        where = f"tools[{i}]"
        if not isinstance(t, dict):
            _fail(f"'{where}' must be an object", where)
        if t.get("type") != "function":
            _fail(f"'{where}.type' must be 'function'", f"{where}.type")
        fn = t.get("function")
        if not isinstance(fn, dict) or not isinstance(fn.get("name"), str):
            _fail(
                f"'{where}.function.name' is required",
                f"{where}.function",
            )


def validate_tenancy(headers: Any) -> tuple[str, str]:
    """Validate + resolve the request's (tenant, priority) at the edge.

    Sources, in precedence order: the explicit ``x-dyn-tenant`` header;
    an ``Authorization`` bearer credential (hashed to a stable opaque
    ``key-<digest>`` id so API-key traffic gets per-key fairness without
    the key itself ever reaching headers/labels/logs); else the shared
    ``default`` tenant. Priority comes from ``x-dyn-priority``
    (``interactive`` | ``batch``; default interactive).

    Raises RequestValidationError (-> HTTP 400 naming the header) on a
    malformed tenant id or unknown priority class — a typo'd priority
    must not silently demote (or promote) the request."""
    tenant = (headers.get("x-dyn-tenant") or "").strip()
    if tenant:
        if not _TENANT_RE.match(tenant):
            _fail(
                "'x-dyn-tenant' must be 1-64 chars of [A-Za-z0-9._-]",
                "x-dyn-tenant",
            )
    else:
        auth = (headers.get("Authorization")
                or headers.get("authorization") or "").strip()
        if auth:
            cred = auth.split(None, 1)[-1].encode()
            tenant = "key-" + hashlib.sha256(cred).hexdigest()[:12]
        else:
            tenant = "default"
    priority = (headers.get("x-dyn-priority") or "interactive").strip().lower()
    if priority not in _PRIORITIES:
        _fail(
            f"'x-dyn-priority' must be one of {list(_PRIORITIES)}",
            "x-dyn-priority",
        )
    return tenant, priority


def validate_request(body: Any, kind: str) -> None:
    """Validate one request body for ``kind`` in {chat, completions,
    embeddings, responses}. Raises RequestValidationError (a ValueError)
    naming the offending param."""
    if not isinstance(body, dict):
        _fail("request body must be a JSON object")
    if kind == "chat":
        _check_messages(body)
        _check_tools(body)
        _check_tool_choice(body)
        _check_response_format(body)
        _check_common(body)
        lp = body.get("logprobs")
        if lp is not None and not isinstance(lp, bool):
            _fail("'logprobs' must be a boolean for chat", "logprobs")
    elif kind == "completions":
        prompt = body.get("prompt")
        if prompt is None:
            _fail("'prompt' is required", "prompt")
        if not isinstance(prompt, str):
            if not isinstance(prompt, list) or not all(
                isinstance(p, str) for p in prompt
            ):
                _fail(
                    "'prompt' must be a string or list of strings", "prompt"
                )
        _check_common(body)
        lp = body.get("logprobs")
        if lp is not None and (isinstance(lp, bool) or not isinstance(lp, int)):
            _fail("'logprobs' must be an integer for completions", "logprobs")
    elif kind == "embeddings":
        inp = body.get("input")
        if inp is None:
            _fail("'input' is required", "input")
        if not isinstance(inp, str):
            if not isinstance(inp, list) or not all(
                isinstance(p, str) for p in inp
            ):
                _fail("'input' must be a string or list of strings", "input")
    elif kind == "responses":
        inp = body.get("input")
        if inp is None:
            _fail("'input' is required", "input")
        _check_common(body)
