"""OpenAI-compatible frontend + request pipeline.

The serving pipeline (ref lib/llm/src/entrypoint/input/common.rs:196
build_pipeline / :228 build_routed_pipeline):

    HTTP (SSE) -> OpenAIPreprocessor -> Backend (detokenize/stops)
               -> Migration (retry on worker death) -> PushRouter | KvPushRouter
               -> worker instances (tokens in / tokens out)

Workers self-register ModelDeploymentCards in the hub (v1/mdc/...); the
frontend's ModelWatcher builds a pipeline per model as cards appear and
tears them down as leases expire.
"""

from dynamo_tpu.frontend.tokenizer import MockTokenizer, load_tokenizer
from dynamo_tpu.frontend.model_card import ModelDeploymentCard, register_llm
from dynamo_tpu.frontend.preprocessor import OpenAIPreprocessor
from dynamo_tpu.frontend.backend_op import Backend
from dynamo_tpu.frontend.migration import Migration
from dynamo_tpu.frontend.watcher import ModelManager, ModelWatcher
from dynamo_tpu.frontend.http import HttpFrontend

__all__ = [
    "MockTokenizer",
    "load_tokenizer",
    "ModelDeploymentCard",
    "register_llm",
    "OpenAIPreprocessor",
    "Backend",
    "Migration",
    "ModelManager",
    "ModelWatcher",
    "HttpFrontend",
]
