"""Backend operator: incremental detokenization + stop-sequence scanning.

Sits between the router (token deltas from workers) and the preprocessor's
postprocessing (OpenAI deltas). Ref: lib/llm/src/backend.rs:55 ``Backend`` -
incremental Decoder, stop-sequence scan over a sliding text window, token
accumulation.
"""

from __future__ import annotations

from contextlib import aclosing
from typing import Any, AsyncIterator

from dynamo_tpu.frontend.tokenizer import IncrementalDecoder, Tokenizer
from dynamo_tpu.runtime.context import Context


class Backend:
    """Wraps a downstream token engine; yields deltas with ``text`` attached."""

    def __init__(self, tokenizer: Tokenizer, downstream):
        self.tokenizer = tokenizer
        self.downstream = downstream

    async def generate(
        self, request: dict[str, Any], context: Context
    ) -> AsyncIterator[dict[str, Any]]:
        stops: list[str] = list(
            (request.get("stop_conditions") or {}).get("stop") or []
        )
        stop_token_ids = set(
            (request.get("stop_conditions") or {}).get("stop_token_ids") or []
        )
        eos_ids = set(request.get("eos_token_ids") or [])
        ignore_eos = bool(
            (request.get("stop_conditions") or {}).get("ignore_eos", False)
        )
        decoder = IncrementalDecoder(self.tokenizer)
        emitted_text_len = 0
        # longest stop string bounds how much text we must hold back
        holdback = max((len(s) for s in stops), default=0)

        # deterministic close: this operator returns as soon as it sees a
        # terminal item, and an abandoned downstream chain would otherwise
        # be torn down by GC finalizer tasks — one per layer, per request
        downstream = self.downstream.generate(request, context)
        async with aclosing(downstream):
            async for item in downstream:
                out = dict(item)
                tokens = out.get("token_ids") or []
                finish = out.get("finish_reason")

                # token-level stops: explicit stop_token_ids always apply;
                # ignore_eos disables only the EOS check
                if tokens:
                    for pos, t in enumerate(tokens):
                        if t in stop_token_ids or (t in eos_ids and not ignore_eos):
                            out["token_ids"] = tokens[: pos + 1]
                            tokens = out["token_ids"]
                            finish = out["finish_reason"] = "stop"
                            break

                if out.get("logprobs"):
                    # align with any token truncation above; attach token text
                    entries = list(out["logprobs"])[: len(tokens)]
                    for e in entries:
                        e["token"] = self.tokenizer.decode([e["id"]])
                        for t in e.get("top", ()):
                            t["token"] = self.tokenizer.decode([t["id"]])
                    out["logprobs"] = entries

                delta_text = decoder.push(tokens) if tokens else ""
                if finish is not None:
                    delta_text += decoder.flush()

                if stops:
                    # scan the full text for stop strings (sliding window)
                    full = decoder.text
                    hit = -1
                    for s in stops:
                        idx = full.find(s, max(emitted_text_len - len(s), 0))
                        if idx != -1:
                            hit = idx if hit == -1 else min(hit, idx)
                    if hit != -1:
                        # truncate at the stop string and finish
                        out["text"] = full[emitted_text_len:hit]
                        out["finish_reason"] = "stop"
                        emitted_text_len = hit
                        if out.get("logprobs"):
                            # drop entries for tokens past the stop string
                            # (OpenAI truncates logprobs with the text)
                            kept, seen = [], 0
                            for e in out["logprobs"]:
                                if seen >= len(out["text"]):
                                    break
                                kept.append(e)
                                seen += len(e.get("token", ""))
                            out["logprobs"] = kept
                        yield out
                        context.stop_generating()
                        return
                    # hold back enough text to catch a stop string spanning deltas
                    if finish is None and holdback:
                        safe = max(len(full) - holdback, emitted_text_len)
                        delta_text = full[emitted_text_len:safe]
                        out["text"] = delta_text
                        emitted_text_len = safe
                    else:
                        out["text"] = full[emitted_text_len:]
                        emitted_text_len = len(full)
                else:
                    out["text"] = delta_text
                    emitted_text_len += len(delta_text)

                yield out
                if out.get("finish_reason") is not None:
                    return


def make_operator(sink, *, tokenizer) -> "Backend":
    """Operator-registry factory (runtime/pipeline.py): sink-first form."""
    return Backend(tokenizer, sink)
