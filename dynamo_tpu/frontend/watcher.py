"""Model discovery: watch cards, build per-model pipelines.

ModelWatcher watches the hub ``v1/mdc/`` prefix; for each card it assembles
the serving chain Preprocessor -> Backend -> Migration -> (Kv)PushRouter ->
instances and registers it in ModelManager under the served model name.
Cards disappearing (lease expiry / deregistration) tear the pipeline down.
Ref: lib/llm/src/discovery/ (ModelWatcher watcher.rs:49, ModelManager
model_manager.rs:38) and entrypoint/input/common.rs:228
build_routed_pipeline.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import Any, AsyncIterator

from dynamo_tpu.frontend.model_card import MDC_ROOT, ModelDeploymentCard
from dynamo_tpu.frontend.preprocessor import OpenAIPreprocessor
from dynamo_tpu.frontend.tokenizer import load_tokenizer
from dynamo_tpu.kv_router.protocols import RouterConfig
from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.push import PushRouter, RouterMode

log = logging.getLogger("dynamo.discovery")


@dataclass
class ModelPipeline:
    card: ModelDeploymentCard
    preprocessor: OpenAIPreprocessor
    engine: Any  # Backend chain: Backend(MmEncode?(Migration(router)))
    push_router: PushRouter
    kv_router: KvRouter | None
    encode_router: PushRouter | None = None  # multimodal encode hop

    async def close(self) -> None:
        if self.kv_router is not None:
            await self.kv_router.close()
        if self.encode_router is not None:
            await self.encode_router.client.close()
        await self.push_router.client.close()

    def generate(self, preprocessed: dict, context: Context) -> AsyncIterator[dict]:
        return self.engine.generate(preprocessed, context)


class ModelManager:
    def __init__(self) -> None:
        self._models: dict[str, ModelPipeline] = {}

    def get(self, name: str) -> ModelPipeline | None:
        return self._models.get(name)

    def add(self, pipeline: ModelPipeline) -> None:
        self._models[pipeline.card.name] = pipeline

    async def remove(self, name: str) -> None:
        pipe = self._models.pop(name, None)
        if pipe is not None:
            await pipe.close()

    def names(self) -> list[str]:
        return sorted(self._models)

    def cards(self) -> list[ModelDeploymentCard]:
        return [p.card for p in self._models.values()]


async def build_pipeline(
    drt: DistributedRuntime, card: ModelDeploymentCard
) -> ModelPipeline:
    tokenizer = load_tokenizer(card.tokenizer)
    endpoint = (
        drt.namespace(card.namespace)
        .component(card.component)
        .endpoint(card.endpoint)
    )
    mode = {
        "kv": RouterMode.KV,
        "round_robin": RouterMode.ROUND_ROBIN,
        "random": RouterMode.RANDOM,
    }.get(card.router_mode, RouterMode.ROUND_ROBIN)

    push = await PushRouter.from_endpoint(
        endpoint,
        RouterMode.DIRECT if mode is RouterMode.KV else mode,
    )
    kv_router: KvRouter | None = None
    router_engine: Any = push
    if mode is RouterMode.KV:
        kv_router = await KvRouter(
            drt.hub,
            card.component_path,
            RouterConfig(block_size=card.kv_block_size),
        ).start()
        # The hash salt MUST match what workers use when hashing blocks for
        # their KV events (engines hash unsalted unless the card says
        # otherwise) - a mismatched salt silently zeroes all prefix overlap.
        router_engine = KvPushRouter(
            push, kv_router, salt=card.runtime_config.get("kv_salt")
        )

    # chains are data through the generic operator registry (ref
    # pipeline/nodes.rs + registry.rs): cards may splice extra operators
    # via runtime_config["operators"] (name or [name, kwargs] entries)
    # between the backend and the router
    from dynamo_tpu.runtime.pipeline import build_chain

    extra = list(card.runtime_config.get("operators") or [])
    # multimodal cards get the encode hop: image refs resolve to
    # embeddings via the encoder component BEFORE migration/routing
    encode_router: PushRouter | None = None
    mm_ops: list = []
    if card.mm_tokens_per_image:
        from dynamo_tpu.multimodal.worker import (
            ENCODER_COMPONENT,
            ENCODER_ENDPOINT,
        )

        enc_ep = (
            drt.namespace(card.namespace)
            .component(ENCODER_COMPONENT)
            .endpoint(ENCODER_ENDPOINT)
        )
        encode_router = await PushRouter.from_endpoint(
            enc_ep, RouterMode.ROUND_ROBIN
        )
        mm_ops = [("mm_encode", {"encode_router": encode_router})]
    backend = build_chain(
        [
            ("backend", {"tokenizer": tokenizer}),
            *mm_ops,
            *extra,
            ("migration", {"migration_limit": card.migration_limit}),
        ],
        router_engine,
    )
    preprocessor = OpenAIPreprocessor(
        tokenizer,
        model_name=card.name,
        context_length=card.context_length,
        chat_template=card.chat_template,
        tool_call_parser=card.tool_call_parser,
        reasoning_parser=card.reasoning_parser,
        mm_tokens_per_image=card.mm_tokens_per_image,
        image_token_id=card.image_token_id,
        mm_video_frames=card.mm_video_frames,
    )
    return ModelPipeline(
        card=card,
        preprocessor=preprocessor,
        engine=backend,
        push_router=push,
        kv_router=kv_router,
        encode_router=encode_router,
    )


class ModelWatcher:
    def __init__(self, drt: DistributedRuntime, manager: ModelManager):
        self.drt = drt
        self.manager = manager
        self._task: asyncio.Task | None = None
        self._ready = asyncio.Event()
        self._known_keys: dict[str, str] = {}  # card key -> model name
        self._model_refs: dict[str, set[str]] = {}  # model name -> card keys

    async def start(self) -> "ModelWatcher":
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._watch())
        return self

    async def wait_for_model(self, name: str | None = None, timeout: float = 30.0) -> None:
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            if name is None and self.manager.names():
                return
            if name is not None and self.manager.get(name) is not None:
                return
            if asyncio.get_running_loop().time() > deadline:
                raise TimeoutError(f"model {name!r} not discovered in {timeout}s")
            await asyncio.sleep(0.05)

    async def _watch(self) -> None:
        try:
            async for ev in self.drt.hub.watch_prefix(MDC_ROOT + "/"):
                try:
                    if ev.kind == "put" and ev.value:
                        card = ModelDeploymentCard.from_dict(ev.value)
                        self._known_keys[ev.key] = card.name
                        refs = self._model_refs.setdefault(card.name, set())
                        refs.add(ev.key)
                        if self.manager.get(card.name) is None:
                            pipe = await build_pipeline(self.drt, card)
                            self.manager.add(pipe)
                            log.info("model %r discovered (%s)", card.name, ev.key)
                    elif ev.kind == "delete":
                        name = self._known_keys.pop(ev.key, None)
                        if name is not None:
                            refs = self._model_refs.get(name, set())
                            refs.discard(ev.key)
                            if not refs:  # last worker gone
                                self._model_refs.pop(name, None)
                                await self.manager.remove(name)
                                log.info("model %r removed", name)
                except Exception:  # noqa: BLE001 - keep watching
                    log.exception("failed handling model card event %s", ev.key)
        except asyncio.CancelledError:
            pass
        except ConnectionError:
            log.error("hub watch lost; model discovery stopped")

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
        # tear down every pipeline (kv-router consumer tasks, push clients)
        for name in list(self.manager.names()):
            await self.manager.remove(name)
        self._known_keys.clear()
        self._model_refs.clear()
