"""Tokenizer abstraction + incremental detokenization.

Two implementations:
  - ``HFTokenizer``: wraps a local HuggingFace tokenizer (transformers) -
    the production path (ref: the preprocessor's HF tokenizers usage,
    lib/llm/src/preprocessor.rs + tokenizers crate).
  - ``MockTokenizer``: deterministic byte-level tokenizer for hermetic tests
    and the mock engine (no downloads; this environment has no egress).

``IncrementalDecoder`` converts a stream of token ids into clean UTF-8 text
deltas (the reference's Decoder in backend.rs): it withholds bytes until
they form complete codepoints, so multi-byte characters split across tokens
never emit mojibake.
"""

from __future__ import annotations

from typing import Protocol, Sequence

__all__ = ["Tokenizer", "MockTokenizer", "HFTokenizer", "IncrementalDecoder", "load_tokenizer"]


class Tokenizer(Protocol):
    eos_token_id: int
    vocab_size: int

    def encode(self, text: str) -> list[int]: ...  # pragma: no cover
    def decode(self, ids: Sequence[int]) -> str: ...  # pragma: no cover
    def apply_chat_template(self, messages: list[dict], add_generation_prompt: bool = True) -> str: ...  # pragma: no cover


_DEFAULT_CHAT_TEMPLATE = (
    "{% for m in messages %}"
    "<|{{ m['role'] }}|>{{ m['content'] }}<|end|>\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|assistant|>{% endif %}"
)


class MockTokenizer:
    """Byte-level tokenizer: token id = byte value + 16 (0..15 reserved).

    Deterministic, reversible, and needs no model files. Special ids:
    0=pad, 1=bos, 2=eos.
    """

    PAD, BOS, EOS = 0, 1, 2
    OFFSET = 16

    def __init__(self) -> None:
        self.eos_token_id = self.EOS
        self.vocab_size = 256 + self.OFFSET
        import jinja2

        self._template = jinja2.Template(_DEFAULT_CHAT_TEMPLATE)

    def encode(self, text: str) -> list[int]:
        return [b + self.OFFSET for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(
            i - self.OFFSET for i in ids if self.OFFSET <= i < self.OFFSET + 256
        )
        return data.decode("utf-8", errors="replace")

    def decode_bytes(self, ids: Sequence[int]) -> bytes:
        return bytes(
            i - self.OFFSET for i in ids if self.OFFSET <= i < self.OFFSET + 256
        )

    def apply_chat_template(
        self, messages: list[dict], add_generation_prompt: bool = True,
        tools: list[dict] | None = None,
    ) -> str:
        return self._template.render(
            messages=messages, add_generation_prompt=add_generation_prompt,
            tools=tools,
        )


class HFTokenizer:
    """HuggingFace tokenizer wrapper (local files only; no egress)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer  # deferred: heavy import

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.eos_token_id = self._tok.eos_token_id or 2
        self.vocab_size = getattr(self._tok, "vocab_size", 32000)

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

    def apply_chat_template(
        self, messages: list[dict], add_generation_prompt: bool = True,
        tools: list[dict] | None = None,
    ) -> str:
        return self._tok.apply_chat_template(
            messages, tokenize=False,
            add_generation_prompt=add_generation_prompt, tools=tools,
        )


def load_tokenizer(spec: str | None) -> Tokenizer:
    """Resolve a tokenizer spec from a model card: "mock" or a local path."""
    if not spec or spec == "mock":
        return MockTokenizer()
    return HFTokenizer(spec)


def _utf8_incomplete_tail(data: bytes) -> int:
    """Length of a trailing incomplete UTF-8 sequence (0 if none).

    Scans back at most 3 bytes for a lead byte whose declared sequence
    length exceeds the bytes present; invalid sequences count as
    complete (the errors="replace" decode handles them)."""
    for i in range(1, min(3, len(data)) + 1):
        b = data[-i]
        if b < 0x80:
            return 0  # ASCII: nothing held back
        if b >= 0xC0:  # lead byte
            need = 2 if b < 0xE0 else 3 if b < 0xF0 else 4
            return i if need > i else 0
        # else: continuation byte, keep scanning
    return 0


class IncrementalDecoder:
    """Streaming token-ids -> text deltas without broken codepoints.

    Sliding-window algorithm (the standard HF/vLLM incremental detokenizer,
    and the reference Decoder's approach in backend.rs): decode only a
    bounded window ``ids[prefix_offset:]`` each step - O(1) amortized per
    token, not O(n) - and hold the delta back while it ends in U+FFFD
    (a token boundary split a multi-byte character).
    """

    def __init__(self, tokenizer: Tokenizer):
        self.tokenizer = tokenizer
        self._ids: list[int] = []
        self._prefix_offset = 0  # window start (last fully-emitted boundary)
        self._read_offset = 0  # ids already attributed to emitted text
        self._text_parts: list[str] = []  # all emitted deltas
        self._text_len = 0
        # byte-level tokenizers (MockTokenizer) expose decode_bytes:
        # their decode is compositional, so instead of re-decoding the
        # sliding window twice per push we track raw bytes and hold back
        # only an incomplete UTF-8 tail
        self._byte_mode = hasattr(tokenizer, "decode_bytes")
        self._pending_bytes = b""

    def push(self, ids: Sequence[int]) -> str:
        if self._byte_mode:
            data = self._pending_bytes + self.tokenizer.decode_bytes(ids)
            cut = len(data) - _utf8_incomplete_tail(data)
            self._pending_bytes = data[cut:]
            delta = data[:cut].decode("utf-8", errors="replace")
            if delta:
                self._text_parts.append(delta)
                self._text_len += len(delta)
            return delta
        self._ids.extend(ids)
        prefix_text = self.tokenizer.decode(
            self._ids[self._prefix_offset : self._read_offset]
        )
        window_text = self.tokenizer.decode(self._ids[self._prefix_offset :])
        if window_text.endswith("�"):
            return ""  # incomplete codepoint: wait for more tokens
        delta = window_text[len(prefix_text) :]
        self._prefix_offset = self._read_offset
        self._read_offset = len(self._ids)
        if delta:
            self._text_parts.append(delta)
            self._text_len += len(delta)
        return delta

    def flush(self) -> str:
        if self._byte_mode:
            delta = self._pending_bytes.decode("utf-8", errors="replace")
            self._pending_bytes = b""
            if delta:
                self._text_parts.append(delta)
                self._text_len += len(delta)
            return delta
        window_text = self.tokenizer.decode(self._ids[self._prefix_offset :])
        prefix_text = self.tokenizer.decode(
            self._ids[self._prefix_offset : self._read_offset]
        )
        delta = window_text[len(prefix_text) :]
        self._prefix_offset = self._read_offset = len(self._ids)
        if delta:
            self._text_parts.append(delta)
            self._text_len += len(delta)
        return delta

    @property
    def text(self) -> str:
        """All text emitted so far (O(1) amortized; no re-decode)."""
        if len(self._text_parts) > 1:
            self._text_parts = ["".join(self._text_parts)]
        return self._text_parts[0] if self._text_parts else ""
