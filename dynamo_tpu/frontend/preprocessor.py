"""OpenAIPreprocessor: OpenAI API request <-> token-level pipeline.

Forward: render the chat template (jinja2 / tokenizer-native), tokenize,
extract sampling + stop conditions -> PreprocessedRequest (ref
lib/llm/src/preprocessor.rs:159 preprocess_request, prompt/template/oai.rs).

Backward: wrap the Backend's detokenized deltas as OpenAI
chat.completion.chunk / text_completion objects and aggregate non-streaming
responses (ref preprocessor.rs:430 transform_postprocessor_stream,
protocols/openai/chat_completions/aggregator.rs).
"""

from __future__ import annotations

from typing import Any, AsyncIterator

import jinja2

from dynamo_tpu.frontend.protocols import (
    make_preprocessed_request,
    new_request_id,
    now_unix,
)
from dynamo_tpu.frontend.tokenizer import Tokenizer


class OpenAIPreprocessor:
    def __init__(
        self,
        tokenizer: Tokenizer,
        *,
        model_name: str,
        context_length: int = 8192,
        chat_template: str | None = None,
        default_max_tokens: int = 256,
    ):
        self.tokenizer = tokenizer
        self.model_name = model_name
        self.context_length = context_length
        self.default_max_tokens = default_max_tokens
        self._template = (
            jinja2.Template(chat_template) if chat_template else None
        )

    # -- forward: OpenAI request -> PreprocessedRequest --------------------

    def render_prompt(self, request: dict[str, Any]) -> str:
        if "messages" in request:
            messages = request["messages"]
            if self._template is not None:
                return self._template.render(
                    messages=messages, add_generation_prompt=True
                )
            return self.tokenizer.apply_chat_template(
                messages, add_generation_prompt=True
            )
        prompt = request.get("prompt", "")
        if isinstance(prompt, list):
            prompt = "".join(prompt)
        return prompt

    def preprocess(self, request: dict[str, Any]) -> dict[str, Any]:
        """OpenAI chat/completions request (dict) -> PreprocessedRequest."""
        prompt = self.render_prompt(request)
        token_ids = self.tokenizer.encode(prompt)
        if len(token_ids) >= self.context_length:
            raise ValueError(
                f"prompt ({len(token_ids)} tokens) exceeds context length "
                f"{self.context_length}"
            )
        max_tokens = request.get("max_completion_tokens") or request.get(
            "max_tokens"
        )
        if max_tokens is None:
            max_tokens = min(
                self.default_max_tokens, self.context_length - len(token_ids)
            )
        max_tokens = min(max_tokens, self.context_length - len(token_ids))
        stop = request.get("stop")
        if isinstance(stop, str):
            stop = [stop]
        return make_preprocessed_request(
            token_ids,
            max_tokens=max_tokens,
            temperature=request.get("temperature"),
            top_p=request.get("top_p"),
            top_k=request.get("top_k"),
            seed=request.get("seed"),
            stop=stop,
            ignore_eos=bool(request.get("ignore_eos", False)),
            min_tokens=int(request.get("min_tokens") or 0),
            eos_token_ids=[self.tokenizer.eos_token_id],
            annotations=list(request.get("nvext", {}).get("annotations", []))
            if isinstance(request.get("nvext"), dict)
            else [],
        )

    # -- backward: backend deltas -> OpenAI objects ------------------------

    async def postprocess_chat_stream(
        self,
        deltas: AsyncIterator[dict[str, Any]],
        *,
        request_id: str | None = None,
        include_usage: bool = False,
        prompt_tokens: int = 0,
    ) -> AsyncIterator[dict[str, Any]]:
        """Backend deltas -> chat.completion.chunk dicts (SSE payloads)."""
        rid = request_id or new_request_id()
        created = now_unix()
        first = True
        completion_tokens = 0
        finish = None
        async for d in deltas:
            completion_tokens += len(d.get("token_ids", ()))
            finish = d.get("finish_reason")
            delta: dict[str, Any] = {}
            if first:
                delta["role"] = "assistant"
                first = False
            if d.get("text"):
                delta["content"] = d["text"]
            chunk = {
                "id": rid,
                "object": "chat.completion.chunk",
                "created": created,
                "model": self.model_name,
                "choices": [
                    {"index": 0, "delta": delta, "finish_reason": finish}
                ],
            }
            yield chunk
        if include_usage:
            yield {
                "id": rid,
                "object": "chat.completion.chunk",
                "created": created,
                "model": self.model_name,
                "choices": [],
                "usage": {
                    "prompt_tokens": prompt_tokens,
                    "completion_tokens": completion_tokens,
                    "total_tokens": prompt_tokens + completion_tokens,
                },
            }

    async def aggregate_chat(
        self,
        deltas: AsyncIterator[dict[str, Any]],
        *,
        request_id: str | None = None,
        prompt_tokens: int = 0,
    ) -> dict[str, Any]:
        """Backend deltas -> one chat.completion response (non-streaming)."""
        rid = request_id or new_request_id()
        text_parts: list[str] = []
        completion_tokens = 0
        finish = "stop"
        async for d in deltas:
            if d.get("text"):
                text_parts.append(d["text"])
            completion_tokens += len(d.get("token_ids", ()))
            if d.get("finish_reason"):
                finish = d["finish_reason"]
        return {
            "id": rid,
            "object": "chat.completion",
            "created": now_unix(),
            "model": self.model_name,
            "choices": [
                {
                    "index": 0,
                    "message": {
                        "role": "assistant",
                        "content": "".join(text_parts),
                    },
                    "finish_reason": finish,
                }
            ],
            "usage": {
                "prompt_tokens": prompt_tokens,
                "completion_tokens": completion_tokens,
                "total_tokens": prompt_tokens + completion_tokens,
            },
        }

    async def postprocess_completions_stream(
        self,
        deltas: AsyncIterator[dict[str, Any]],
        *,
        request_id: str | None = None,
    ) -> AsyncIterator[dict[str, Any]]:
        rid = request_id or new_request_id()
        created = now_unix()
        async for d in deltas:
            yield {
                "id": rid,
                "object": "text_completion",
                "created": created,
                "model": self.model_name,
                "choices": [
                    {
                        "index": 0,
                        "text": d.get("text", ""),
                        "finish_reason": d.get("finish_reason"),
                    }
                ],
            }

    async def aggregate_completions(
        self,
        deltas: AsyncIterator[dict[str, Any]],
        *,
        request_id: str | None = None,
        prompt_tokens: int = 0,
    ) -> dict[str, Any]:
        rid = request_id or new_request_id()
        text_parts: list[str] = []
        completion_tokens = 0
        finish = "stop"
        async for d in deltas:
            if d.get("text"):
                text_parts.append(d["text"])
            completion_tokens += len(d.get("token_ids", ()))
            if d.get("finish_reason"):
                finish = d["finish_reason"]
        return {
            "id": rid,
            "object": "text_completion",
            "created": now_unix(),
            "model": self.model_name,
            "choices": [
                {"index": 0, "text": "".join(text_parts), "finish_reason": finish}
            ],
            "usage": {
                "prompt_tokens": prompt_tokens,
                "completion_tokens": completion_tokens,
                "total_tokens": prompt_tokens + completion_tokens,
            },
        }
