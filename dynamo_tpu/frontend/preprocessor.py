"""OpenAIPreprocessor: OpenAI API request <-> token-level pipeline.

Forward: render the chat template (jinja2 / tokenizer-native), tokenize,
extract sampling + stop conditions -> PreprocessedRequest (ref
lib/llm/src/preprocessor.rs:159 preprocess_request, prompt/template/oai.rs).

Backward: wrap the Backend's detokenized deltas as OpenAI
chat.completion.chunk / text_completion objects and aggregate non-streaming
responses (ref preprocessor.rs:430 transform_postprocessor_stream,
protocols/openai/chat_completions/aggregator.rs).
"""

from __future__ import annotations

from typing import Any, AsyncIterator

import jinja2

from dynamo_tpu.frontend.protocols import (
    make_preprocessed_request,
    new_request_id,
    now_unix,
)
from dynamo_tpu.frontend.tokenizer import Tokenizer


class OpenAIPreprocessor:
    def __init__(
        self,
        tokenizer: Tokenizer,
        *,
        model_name: str,
        context_length: int = 8192,
        chat_template: str | None = None,
        default_max_tokens: int = 256,
        tool_call_parser: str | None = None,
        reasoning_parser: str | None = None,
        mm_tokens_per_image: int = 0,
        image_token_id: int = 0,
        mm_video_frames: int = 0,
    ):
        self.tokenizer = tokenizer
        self.model_name = model_name
        self.context_length = context_length
        self.default_max_tokens = default_max_tokens
        self.tool_call_parser = tool_call_parser
        self.reasoning_parser = reasoning_parser
        # multimodal: 0 = text-only model (image content parts rejected)
        self.mm_tokens_per_image = mm_tokens_per_image
        self.image_token_id = image_token_id
        # frames sampled per video_url part (0 = video rejected); each
        # frame occupies mm_tokens_per_image placeholder rows
        self.mm_video_frames = mm_video_frames
        # fail fast on unknown parser names: a typo must break worker
        # startup, not every subsequent chat request
        from dynamo_tpu.parsers import make_reasoning_parser, make_tool_config

        self._tool_cfg = make_tool_config(tool_call_parser)
        make_reasoning_parser(reasoning_parser)
        self._template = (
            jinja2.Template(chat_template) if chat_template else None
        )

    def _tool_config(self, request: dict[str, Any] | None):
        """Jail only when the model has a parser AND the request brought
        tools (ref preprocessor.rs:629 jail application). Every
        tool_choice shape except "none" flows through: "auto" (parse if
        the model calls), "required" and named functions (generation is
        grammar-FORCED into a call — _guided_spec — and the jail/parser
        consume the guaranteed output)."""
        if self._tool_cfg is None or not request or not request.get("tools"):
            return None
        if request.get("tool_choice") == "none":
            return None
        return self._tool_cfg

    def _guided_spec(self, request: dict[str, Any]) -> dict[str, Any] | None:
        """Grammar selection for guided decoding (guided/schema.py):
        forced tool calls win over response_format over
        nvext.guided_regex; None when nothing constrains generation.
        Raises ValueError (GrammarError) -> a typed 400 at the edge —
        an unsupported schema must never become a mid-stream 500."""
        from dynamo_tpu.guided.schema import grammar_from_request

        return grammar_from_request(request, tool_cfg=self._tool_cfg)

    def _reasoning(self):
        from dynamo_tpu.parsers import make_reasoning_parser

        return make_reasoning_parser(self.reasoning_parser)

    # -- forward: OpenAI request -> PreprocessedRequest --------------------

    IMAGE_MARKER = "<|mm_image|>"

    def _flatten_content(
        self, request: dict[str, Any]
    ) -> tuple[dict[str, Any], list["str | dict[str, Any]"]]:
        """OpenAI content-part lists -> string contents + media refs.

        Text parts concatenate; each image_url/video_url part becomes an
        inline marker (spliced into placeholder tokens after rendering)
        and its ref collects in order — plain URL strings for images,
        ``{"url":…, "kind":"video"}`` dicts for videos (the encode
        worker expands those into sampled frames). Ref: the template-level multimodal prompt
        handling of lib/llm/src/preprocessor/prompt/template/oai.rs."""
        if "messages" not in request:
            return request, []
        has_images = any(
            isinstance(m.get("content"), list)
            and any(
                isinstance(p, dict)
                and p.get("type") in ("image_url", "video_url")
                for p in m["content"]
            )
            for m in request["messages"]
        )

        def clean(text: str) -> str:
            # the marker is RESERVED while images are present: a literal
            # occurrence in user text would desync the marker/image count
            # when positions are recovered from the rendered prompt
            return (
                text.replace(self.IMAGE_MARKER, "") if has_images else text
            )

        images: list[str | dict[str, Any]] = []
        msgs = []
        changed = False
        for m in request["messages"]:
            c = m.get("content")
            if isinstance(c, list):
                parts: list[str] = []
                for part in c:
                    ptype = part.get("type") if isinstance(part, dict) else None
                    if ptype == "text":
                        parts.append(clean(str(part.get("text") or "")))
                    elif ptype in ("image_url", "video_url"):
                        iu = part.get(ptype)
                        url = iu.get("url") if isinstance(iu, dict) else iu
                        if not url:
                            raise ValueError(f"{ptype} part without url")
                        images.append(
                            url if ptype == "image_url"
                            else {"url": url, "kind": "video"}
                        )
                        parts.append(self.IMAGE_MARKER)
                    else:
                        raise ValueError(
                            f"unsupported content part type {ptype!r}"
                        )
                m = {**m, "content": "".join(parts)}
                changed = True
            elif has_images and isinstance(c, str) and self.IMAGE_MARKER in c:
                m = {**m, "content": clean(c)}
                changed = True
            msgs.append(m)
        if not changed:
            return request, images
        return {**request, "messages": msgs}, images

    def render_prompt(self, request: dict[str, Any]) -> str:
        if "messages" in request:
            messages = request["messages"]
            tools = request.get("tools")
            if self._template is not None:
                return self._template.render(
                    messages=messages, add_generation_prompt=True, tools=tools
                )
            try:
                return self.tokenizer.apply_chat_template(
                    messages, add_generation_prompt=True, tools=tools
                )
            except TypeError:
                # tokenizer template without tools support
                return self.tokenizer.apply_chat_template(
                    messages, add_generation_prompt=True
                )
        prompt = request.get("prompt", "")
        if isinstance(prompt, list):
            prompt = "".join(prompt)
        return prompt

    def _attachment_tokens(self, att) -> int:
        """Placeholder rows one attachment occupies: an image is
        mm_tokens_per_image; a video is that per sampled frame."""
        if isinstance(att, dict) and att.get("kind") == "video":
            return self.mm_tokens_per_image * self.mm_video_frames
        return self.mm_tokens_per_image

    def _tokenize_with_images(
        self, prompt: str, attachments: list
    ) -> tuple[list[int], list[int]]:
        """Tokenize around media markers, splicing each attachment's
        placeholder ids (_attachment_tokens — images and videos differ).
        Returns (token_ids, placeholder positions — absolute prompt
        positions the engine overwrites with the encoder's embedding
        rows)."""
        segs = prompt.split(self.IMAGE_MARKER)
        if len(segs) - 1 != len(attachments):
            raise ValueError(
                "media markers and media parts diverged (chat template "
                "dropped message content?)"
            )
        token_ids: list[int] = []
        positions: list[int] = []
        for i, seg in enumerate(segs):
            if seg:
                token_ids.extend(self.tokenizer.encode(seg))
            if i < len(attachments):
                n = self._attachment_tokens(attachments[i])
                start = len(token_ids)
                positions.extend(range(start, start + n))
                token_ids.extend([self.image_token_id] * n)
        return token_ids, positions

    def preprocess(self, request: dict[str, Any]) -> dict[str, Any]:
        """OpenAI chat/completions request (dict) -> PreprocessedRequest."""
        guided = self._guided_spec(request)
        request, images = self._flatten_content(request)
        if images and not self.mm_tokens_per_image:
            raise ValueError(
                f"model {self.model_name!r} does not accept image input"
            )
        if any(
            isinstance(a, dict) and a.get("kind") == "video"
            for a in images
        ) and not self.mm_video_frames:
            raise ValueError(
                f"model {self.model_name!r} does not accept video input"
            )
        prompt = self.render_prompt(request)
        if images:
            token_ids, mm_positions = self._tokenize_with_images(
                prompt, images
            )
        else:
            token_ids = self.tokenizer.encode(prompt)
            mm_positions = []
        if len(token_ids) >= self.context_length:
            raise ValueError(
                f"prompt ({len(token_ids)} tokens) exceeds context length "
                f"{self.context_length}"
            )
        max_tokens = request.get("max_completion_tokens") or request.get(
            "max_tokens"
        )
        if max_tokens is None:
            max_tokens = min(
                self.default_max_tokens, self.context_length - len(token_ids)
            )
        max_tokens = min(max_tokens, self.context_length - len(token_ids))
        stop = request.get("stop")
        if isinstance(stop, str):
            stop = [stop]
        # OpenAI logprob knobs: chat uses logprobs=true + top_logprobs=N,
        # completions uses logprobs=N
        lp = request.get("logprobs")
        if lp is True:
            logprobs = int(request.get("top_logprobs") or 0)
        elif isinstance(lp, int) and not isinstance(lp, bool):
            logprobs = lp
        else:
            logprobs = None
        if logprobs is not None and not (0 <= logprobs <= 20):
            # OpenAI caps top_logprobs at 20; unbounded N would also feed a
            # static top-k size into the shared decode step (recompiles /
            # k > vocab crashes affecting co-batched requests)
            raise ValueError("logprobs/top_logprobs must be between 0 and 20")
        pre = make_preprocessed_request(
            token_ids,
            max_tokens=max_tokens,
            temperature=request.get("temperature"),
            top_p=request.get("top_p"),
            top_k=request.get("top_k"),
            seed=request.get("seed"),
            stop=stop,
            ignore_eos=bool(request.get("ignore_eos", False)),
            min_tokens=int(request.get("min_tokens") or 0),
            eos_token_ids=[self.tokenizer.eos_token_id],
            annotations=list(request.get("nvext", {}).get("annotations", []))
            if isinstance(request.get("nvext"), dict)
            else [],
            logprobs=logprobs,
            guided=(
                {**guided, "prompt_len": len(token_ids)}
                if guided is not None else None
            ),
        )
        if images:
            # image refs ride to the MultimodalEncode operator, which
            # swaps them for embeddings before routing (EPD encode hop)
            pre["multimodal"] = {"images": images, "positions": mm_positions}
        return pre

    @staticmethod
    def _chat_logprob_content(entries: list[dict]) -> list[dict]:
        """Engine logprob entries -> OpenAI chat logprobs.content items."""
        return [
            {
                "token": e.get("token", ""),
                "logprob": e["logprob"],
                "bytes": list(e.get("token", "").encode("utf-8")),
                "top_logprobs": [
                    {"token": t.get("token", ""), "logprob": t["logprob"],
                     "bytes": list(t.get("token", "").encode("utf-8"))}
                    for t in e.get("top", ())
                ],
            }
            for e in entries
        ]

    # -- backward: backend deltas -> OpenAI objects ------------------------

    async def postprocess_chat_stream(
        self,
        deltas: AsyncIterator[dict[str, Any]],
        *,
        request_id: str | None = None,
        include_usage: bool = False,
        prompt_tokens: int = 0,
        request: dict[str, Any] | None = None,
    ) -> AsyncIterator[dict[str, Any]]:
        """Backend deltas -> chat.completion.chunk dicts (SSE payloads).

        When the model card configures a tool parser and the request
        carries ``tools``, text runs through the jail (parsers/jail.py):
        marker-delimited call regions leave the stream as ``tool_calls``
        deltas. A configured reasoning parser independently splits think
        segments into ``reasoning_content`` (ref preprocessor.rs:629-694).
        """
        rid = request_id or new_request_id()
        created = now_unix()
        first = True
        completion_tokens = 0
        tool_cfg = self._tool_config(request)
        jail = None
        if tool_cfg is not None:
            from dynamo_tpu.parsers import JailedStream

            jail = JailedStream(tool_cfg)
        reasoning = self._reasoning()
        tool_index = 0
        saw_tool_calls = False
        held_lp: list[dict] = []  # logprob entries from jailed deltas

        def chunk_for(delta: dict[str, Any], finish: str | None,
                      logprobs: list[dict] | None = None):
            nonlocal first
            if first:
                delta = {"role": "assistant", **delta}
                first = False
            choice: dict[str, Any] = {
                "index": 0, "delta": delta, "finish_reason": finish
            }
            if logprobs:
                choice["logprobs"] = {
                    "content": self._chat_logprob_content(logprobs)
                }
            return {
                "id": rid,
                "object": "chat.completion.chunk",
                "created": created,
                "model": self.model_name,
                "choices": [choice],
            }

        async for d in deltas:
            completion_tokens += len(d.get("token_ids", ()))
            finish = d.get("finish_reason")
            text = d.get("text") or ""

            r_delta, content = reasoning.feed(text) if reasoning else ("", text)
            events = []
            if content:
                events = (
                    jail.feed(content) if jail else [("content", content)]
                )
            if finish is not None:
                if reasoning is not None:
                    r_tail, c_tail = reasoning.finish()
                    r_delta += r_tail
                    if c_tail:
                        events += (
                            jail.feed(c_tail) if jail
                            else [("content", c_tail)]
                        )
                if jail is not None:
                    events += jail.finish()

            pending: list[dict[str, Any]] = []
            if r_delta:
                pending.append({"reasoning_content": r_delta})
            for kind, payload in events:
                if kind == "content":
                    if payload:
                        pending.append({"content": payload})
                else:  # tool_calls
                    calls = [
                        c.to_openai(tool_index + i)
                        for i, c in enumerate(payload)
                    ]
                    tool_index += len(calls)
                    saw_tool_calls = True
                    pending.append({"tool_calls": calls})

            if finish is not None and saw_tool_calls and finish == "stop":
                finish = "tool_calls"
            # keep a chunk per backend delta when not jailing (clients see
            # per-token progress even for invisible tokens); while jailed,
            # silence is the point
            if not pending and (jail is None or finish is not None):
                pending.append({})
            # logprob entries ride the first emitted chunk; while the jail
            # holds a delta back entirely they accumulate (clients align
            # logprobs.content to tokens, so none may be dropped)
            held_lp.extend(d.get("logprobs") or ())
            for i, delta in enumerate(pending):
                lp_out = None
                if i == 0 and held_lp:
                    lp_out, held_lp = held_lp, []
                yield chunk_for(
                    delta,
                    finish if (finish is not None and i == len(pending) - 1)
                    else None,
                    logprobs=lp_out,
                )
        if include_usage:
            yield {
                "id": rid,
                "object": "chat.completion.chunk",
                "created": created,
                "model": self.model_name,
                "choices": [],
                "usage": {
                    "prompt_tokens": prompt_tokens,
                    "completion_tokens": completion_tokens,
                    "total_tokens": prompt_tokens + completion_tokens,
                },
            }

    async def aggregate_chat(
        self,
        deltas: AsyncIterator[dict[str, Any]],
        *,
        request_id: str | None = None,
        prompt_tokens: int = 0,
        request: dict[str, Any] | None = None,
    ) -> dict[str, Any]:
        """Backend deltas -> one chat.completion response (non-streaming)."""
        rid = request_id or new_request_id()
        text_parts: list[str] = []
        completion_tokens = 0
        finish = "stop"
        lp_entries: list[dict] = []
        async for d in deltas:
            if d.get("text"):
                text_parts.append(d["text"])
            completion_tokens += len(d.get("token_ids", ()))
            lp_entries.extend(d.get("logprobs") or ())
            if d.get("finish_reason"):
                finish = d["finish_reason"]
        text = "".join(text_parts)

        message: dict[str, Any] = {"role": "assistant"}
        reasoning = self._reasoning()
        if reasoning is not None:
            r1, c1 = reasoning.feed(text)
            r2, c2 = reasoning.finish()
            if r1 + r2:
                message["reasoning_content"] = r1 + r2
            text = c1 + c2
        tool_cfg = self._tool_config(request)
        if tool_cfg is not None:
            from dynamo_tpu.parsers import parse_tool_calls

            calls, normal = parse_tool_calls(text, tool_cfg)
            if calls:
                message["tool_calls"] = [
                    c.to_openai(i) for i, c in enumerate(calls)
                ]
                message["content"] = normal or None
                if finish == "stop":
                    finish = "tool_calls"
            else:
                message["content"] = text
        else:
            message["content"] = text
        choice: dict[str, Any] = {
            "index": 0,
            "message": message,
            "finish_reason": finish,
        }
        if lp_entries:
            choice["logprobs"] = {
                "content": self._chat_logprob_content(lp_entries)
            }
        return {
            "id": rid,
            "object": "chat.completion",
            "created": now_unix(),
            "model": self.model_name,
            "choices": [choice],
            "usage": {
                "prompt_tokens": prompt_tokens,
                "completion_tokens": completion_tokens,
                "total_tokens": prompt_tokens + completion_tokens,
            },
        }

    @staticmethod
    def _completions_logprobs(entries: list[dict]) -> dict[str, Any]:
        """Engine logprob entries -> classic completions logprobs block."""
        return {
            "tokens": [e.get("token", "") for e in entries],
            "token_logprobs": [e["logprob"] for e in entries],
            "top_logprobs": [
                {t.get("token", ""): t["logprob"] for t in e.get("top", ())}
                for e in entries
            ],
        }

    async def postprocess_completions_stream(
        self,
        deltas: AsyncIterator[dict[str, Any]],
        *,
        request_id: str | None = None,
        include_usage: bool = False,
        prompt_tokens: int = 0,
    ) -> AsyncIterator[dict[str, Any]]:
        rid = request_id or new_request_id()
        created = now_unix()
        completion_tokens = 0
        async for d in deltas:
            completion_tokens += len(d.get("token_ids", ()))
            choice: dict[str, Any] = {
                "index": 0,
                "text": d.get("text", ""),
                "finish_reason": d.get("finish_reason"),
            }
            if d.get("logprobs"):
                choice["logprobs"] = self._completions_logprobs(d["logprobs"])
            yield {
                "id": rid,
                "object": "text_completion",
                "created": created,
                "model": self.model_name,
                "choices": [choice],
            }
        if include_usage:
            # OpenAI stream_options.include_usage: one final chunk with
            # empty choices and the token accounting
            yield {
                "id": rid,
                "object": "text_completion",
                "created": created,
                "model": self.model_name,
                "choices": [],
                "usage": {
                    "prompt_tokens": prompt_tokens,
                    "completion_tokens": completion_tokens,
                    "total_tokens": prompt_tokens + completion_tokens,
                },
            }

    async def aggregate_completions(
        self,
        deltas: AsyncIterator[dict[str, Any]],
        *,
        request_id: str | None = None,
        prompt_tokens: int = 0,
    ) -> dict[str, Any]:
        rid = request_id or new_request_id()
        text_parts: list[str] = []
        completion_tokens = 0
        finish = "stop"
        lp_entries: list[dict] = []
        async for d in deltas:
            if d.get("text"):
                text_parts.append(d["text"])
            completion_tokens += len(d.get("token_ids", ()))
            lp_entries.extend(d.get("logprobs") or ())
            if d.get("finish_reason"):
                finish = d["finish_reason"]
        choice: dict[str, Any] = {
            "index": 0, "text": "".join(text_parts), "finish_reason": finish
        }
        if lp_entries:
            choice["logprobs"] = self._completions_logprobs(lp_entries)
        return {
            "id": rid,
            "object": "text_completion",
            "created": now_unix(),
            "model": self.model_name,
            "choices": [choice],
            "usage": {
                "prompt_tokens": prompt_tokens,
                "completion_tokens": completion_tokens,
                "total_tokens": prompt_tokens + completion_tokens,
            },
        }
