"""Migration operator: fault-tolerant retry across workers.

If the response stream dies mid-generation (worker crash, connection loss ->
StreamError from the transport; draining/saturated worker ->
ServiceUnavailable), re-issue the request to another worker with the
already-generated tokens appended to the prompt, up to ``migration_limit``
times. The client never notices beyond a brief pause.
Ref: lib/llm/src/migration.rs (Migration :26, RetryManager :74).

Retry discipline (robustness PR):
  - jittered exponential backoff between attempts (base doubles per retry,
    multiplied by uniform [0.5, 1.5) jitter so a worker crash doesn't make
    every in-flight request hammer the survivors in lockstep);
  - a per-request retry BUDGET (total seconds spent backing off) replaces
    the old unbounded fixed ``retry_delay_s`` sleeps;
  - the request's end-to-end deadline is honored: no retry is attempted
    whose backoff would outlive the deadline (DeadlineExceeded instead);
  - non-retryable failures are never migrated: client cancellation
    (context stopped), DeadlineExceeded (not a StreamError), validation
    errors (plain RuntimeError from the worker);
  - cumulative resume-prompt growth is capped: each migration re-sends
    prompt+generated, so a crash-looping worker must not grow the resume
    prompt unboundedly (max_resume_tokens).

Recovery counters are exported on every /metrics surface as
``dynamo_migrations_total`` / ``dynamo_migrations_exhausted_total``
(runtime/metrics.py global providers) — the chaos soak asserts
recoveries > 0.
"""

from __future__ import annotations

from contextlib import aclosing

import asyncio
import logging
import random
from typing import Any, AsyncIterator

from dynamo_tpu.runtime import tracing
from dynamo_tpu.runtime.context import (
    Context,
    DeadlineExceeded,
    ServiceUnavailable,
    StreamError,
)
from dynamo_tpu.runtime.integrity import token_checksum

log = logging.getLogger("dynamo.migration")

# process-wide recovery counters (all Migration instances; read by the
# chaos soak and exported via the global metrics provider below)
STATS = {"migrations": 0, "exhausted": 0, "resumed_tokens": 0}


def _stats_exposition() -> str:
    return (
        "# HELP dynamo_migrations_total Requests re-driven on another "
        "worker after a stream failure.\n"
        "# TYPE dynamo_migrations_total counter\n"
        f"dynamo_migrations_total {STATS['migrations']}\n"
        "# HELP dynamo_migrations_exhausted_total Requests whose retry "
        "budget/attempts ran out.\n"
        "# TYPE dynamo_migrations_exhausted_total counter\n"
        f"dynamo_migrations_exhausted_total {STATS['exhausted']}\n"
        "# HELP dynamo_migration_resumed_tokens_total Pre-crash tokens "
        "re-sent in resume prompts across all migrations.\n"
        "# TYPE dynamo_migration_resumed_tokens_total counter\n"
        f"dynamo_migration_resumed_tokens_total {STATS['resumed_tokens']}\n"
    )


def _register_metrics() -> None:
    from dynamo_tpu.runtime import metrics

    metrics.register_global_provider("migration", _stats_exposition)


_register_metrics()


class Migration:
    def __init__(
        self,
        downstream,
        *,
        migration_limit: int = 3,
        retry_delay_s: float = 0.2,  # backoff BASE (first-retry delay)
        retry_budget_s: float = 5.0,  # total backoff seconds per request
        backoff_max_s: float = 2.0,
        max_resume_tokens: int = 8192,
        rng: random.Random | None = None,
    ):
        self.downstream = downstream
        self.migration_limit = migration_limit
        self.retry_delay_s = retry_delay_s
        self.retry_budget_s = retry_budget_s
        self.backoff_max_s = backoff_max_s
        self.max_resume_tokens = max_resume_tokens
        self._rng = rng or random.Random()

    def _backoff_s(self, attempt: int) -> float:
        """Jittered exponential backoff for retry ``attempt`` (0-based)."""
        base = min(self.retry_delay_s * (2 ** attempt), self.backoff_max_s)
        return base * (0.5 + self._rng.random())

    async def generate(
        self, request: dict[str, Any], context: Context
    ) -> AsyncIterator[dict[str, Any]]:
        request = dict(request)
        attempts_left = self.migration_limit
        budget_left = self.retry_budget_s
        attempt = 0
        generated: list[int] = []

        while True:
            retry = False
            try:
                # aclosing: the early return on finish_reason must tear
                # the downstream chain down synchronously, not via GC
                stream = self.downstream.generate(request, context)
                async with aclosing(stream):
                    async for item in stream:
                        if isinstance(item, dict):
                            generated.extend(item.get("token_ids") or [])
                        yield item
                        if isinstance(item, dict) and item.get("finish_reason"):
                            return
                    return  # clean end of stream
            except StreamError as e:
                # DeadlineExceeded and validation errors are NOT
                # StreamErrors — they propagate without a retry. Client
                # cancellation never retries either.
                if context.is_stopped or attempts_left <= 0:
                    if attempts_left <= 0:
                        STATS["exhausted"] += 1
                    raise
                if context.deadline_expired:
                    STATS["exhausted"] += 1
                    raise DeadlineExceeded(
                        f"deadline passed after stream failure ({e})"
                    ) from e
                delay = self._backoff_s(attempt)
                if isinstance(e, ServiceUnavailable):
                    delay = max(delay, min(e.retry_after_s, budget_left))
                if delay > budget_left:
                    STATS["exhausted"] += 1
                    raise
                remaining = context.remaining_s()
                if remaining is not None and delay >= remaining:
                    STATS["exhausted"] += 1
                    raise DeadlineExceeded(
                        f"no deadline budget left to retry ({e})"
                    ) from e
                resume_len = len(request.get("token_ids") or []) + len(
                    generated
                )
                if resume_len > self.max_resume_tokens:
                    # a crash-looping backend must not grow the resume
                    # prompt (prompt+generated, re-sent every migration)
                    # without bound
                    STATS["exhausted"] += 1
                    raise StreamError(
                        f"resume prompt would reach {resume_len} tokens "
                        f"(cap {self.max_resume_tokens}); not migrating"
                    ) from e
                budget_left -= delay
                attempts_left -= 1
                attempt += 1
                retry = True
                log.warning(
                    "stream died (%s); migrating request %s in %.2fs "
                    "(%d tokens generated, %d retries / %.1fs budget left)",
                    e, context.id, delay, len(generated), attempts_left,
                    budget_left,
                )
            if retry:
                STATS["migrations"] += 1
                STATS["resumed_tokens"] += len(generated)
                # the BACKOFF joins the request's trace — the invisible
                # "request went quiet" gap after a stream death. The
                # re-driven attempt itself shows up as the NEXT
                # transport.call span in the same trace (this span's
                # sibling), so the trace reads: call -> resume wait ->
                # call.
                with tracing.span(
                    "migration.resume", attempt=attempt,
                    resumed_tokens=len(generated),
                ):
                    await asyncio.sleep(delay)
                # resume: prompt = original + generated so far; shrink budget
                stop = dict(request.get("stop_conditions") or {})
                max_tokens = stop.get("max_tokens")
                if max_tokens is not None:
                    stop["max_tokens"] = max(max_tokens - len(generated), 1)
                resume_tokens = (
                    list(request.get("token_ids") or []) + generated
                )
                request = {
                    **request,
                    "token_ids": resume_tokens,
                    "stop_conditions": stop,
                    "backend_instance_id": None,  # re-route freely
                    # end-to-end integrity stamp: the receiving engine
                    # verifies the resume prompt arrived bit-identical —
                    # a corrupted resume raises IntegrityError back here
                    # and re-drives from this (pristine) request
                    "token_checksum": token_checksum(resume_tokens),
                }
                generated = []
                # fresh child context: the old request id may be poisoned on
                # the dead worker's peers
                context = context.child(f"{context.id}-m{attempt}")


def make_operator(sink, **kwargs) -> "Migration":
    """Operator-registry factory (runtime/pipeline.py): sink-first form."""
    return Migration(sink, **kwargs)
