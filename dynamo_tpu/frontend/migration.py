"""Migration operator: fault-tolerant retry across workers.

If the response stream dies mid-generation (worker crash, connection loss ->
StreamError from the transport), re-issue the request to another worker with
the already-generated tokens appended to the prompt, up to
``migration_limit`` times. The client never notices beyond a brief pause.
Ref: lib/llm/src/migration.rs (Migration :26, RetryManager :74).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator

from dynamo_tpu.runtime.context import Context, StreamError

log = logging.getLogger("dynamo.migration")


class Migration:
    def __init__(self, downstream, *, migration_limit: int = 3, retry_delay_s: float = 0.2):
        self.downstream = downstream
        self.migration_limit = migration_limit
        self.retry_delay_s = retry_delay_s

    async def generate(
        self, request: dict[str, Any], context: Context
    ) -> AsyncIterator[dict[str, Any]]:
        request = dict(request)
        attempts_left = self.migration_limit
        generated: list[int] = []

        while True:
            retry = False
            try:
                async for item in self.downstream.generate(request, context):
                    if isinstance(item, dict):
                        generated.extend(item.get("token_ids") or [])
                    yield item
                    if isinstance(item, dict) and item.get("finish_reason"):
                        return
                return  # clean end of stream
            except StreamError as e:
                if context.is_stopped or attempts_left <= 0:
                    raise
                attempts_left -= 1
                retry = True
                log.warning(
                    "stream died (%s); migrating request %s "
                    "(%d tokens generated, %d retries left)",
                    e, context.id, len(generated), attempts_left,
                )
            if retry:
                await asyncio.sleep(self.retry_delay_s)
                # resume: prompt = original + generated so far; shrink budget
                stop = dict(request.get("stop_conditions") or {})
                max_tokens = stop.get("max_tokens")
                if max_tokens is not None:
                    stop["max_tokens"] = max(max_tokens - len(generated), 1)
                request = {
                    **request,
                    "token_ids": list(request.get("token_ids") or []) + generated,
                    "stop_conditions": stop,
                    "backend_instance_id": None,  # re-route freely
                }
                # fresh child context: the old request id may be poisoned on
                # the dead worker's peers
                context = context.child(f"{context.id}-m{self.migration_limit - attempts_left}")


def make_operator(sink, **kwargs) -> "Migration":
    """Operator-registry factory (runtime/pipeline.py): sink-first form."""
    return Migration(sink, **kwargs)
