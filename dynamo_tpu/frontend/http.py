"""OpenAI-compatible HTTP frontend (aiohttp).

Routes (ref lib/llm/src/http/service/openai.rs + service_v2.rs):
  POST /v1/chat/completions   - streaming (SSE) + aggregated
  POST /v1/completions        - streaming (SSE) + aggregated
  POST /v1/embeddings         - embeddings models
  GET  /v1/models             - discovered model cards
  GET  /health, /live, /ready - liveness/readiness
  GET  /metrics               - Prometheus exposition (TTFT/ITL/duration
                                histograms per model, ref service/metrics.rs)

Client disconnect mid-SSE cancels the whole pipeline (ref disconnect.rs ->
AsyncEngineContext.stop_generating).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import time
from typing import Any

from aiohttp import web

from dynamo_tpu.frontend.protocols import new_request_id
from dynamo_tpu.frontend.validation import (
    RequestValidationError,
    validate_request,
)
from dynamo_tpu.frontend.watcher import ModelManager, ModelPipeline
from dynamo_tpu.runtime import tracing
from dynamo_tpu.runtime.compute import ComputePool
from dynamo_tpu.runtime.context import (
    PRIORITY_HEADER,
    TENANT_HEADER,
    Context,
    DeadlineExceeded,
    OverQuota,
    ServiceUnavailable,
    StreamError,
    tighten_timeout_s,
)
from dynamo_tpu.runtime.metrics import MetricsRegistry
from dynamo_tpu.runtime.push import NoInstancesError

log = logging.getLogger("dynamo.http")

# SSE fast path: static affixes built once and one reusable encoder — the
# per-token path used to assemble f-strings and re-resolve json.dumps'
# kwargs per chunk. Byte-identical to json.dumps (same default separators);
# tests/test_frontend.py asserts the exact wire bytes.
_SSE_DATA = b"data: "
_SSE_SEP = b"\n\n"
_SSE_DONE = b"data: [DONE]\n\n"
_SSE_EVENT = b"event: "
_SSE_EVENT_DATA = b"\ndata: "
_JSON_ENCODER = json.JSONEncoder()


def _sse_bytes(chunk: dict) -> bytes:
    return b"".join((_SSE_DATA, _JSON_ENCODER.encode(chunk).encode(), _SSE_SEP))


def _sse_event_bytes(event: str, payload: dict) -> bytes:
    return b"".join((
        _SSE_EVENT, event.encode(), _SSE_EVENT_DATA,
        _JSON_ENCODER.encode(payload).encode(), _SSE_SEP,
    ))

# per-request deadline override (ms); clamped to the server-side default
TIMEOUT_HEADER = "x-dyn-timeout-ms"


class HttpFrontend:
    def __init__(
        self,
        manager: ModelManager,
        *,
        host: str = "0.0.0.0",
        port: int = 8000,
        metrics: MetricsRegistry | None = None,
        drt=None,  # DistributedRuntime: enables admin routes
        audit=None,  # AuditBus (default: env-configured, see runtime/audit)
        request_timeout_s: float = 600.0,  # end-to-end deadline default
    ):
        self.manager = manager
        self.host = host
        self.port = port
        self.request_timeout_s = request_timeout_s
        self.metrics = metrics or MetricsRegistry()
        self._drt = drt
        self._compute = ComputePool()
        self._runner: web.AppRunner | None = None
        self.app = web.Application()
        from dynamo_tpu.runtime.audit import AuditBus

        self.audit = audit if audit is not None else AuditBus()
        self.app.add_routes(
            [
                web.post("/v1/chat/completions", self.chat_completions),
                web.post("/v1/completions", self.completions),
                web.post("/v1/responses", self.responses),
                web.post("/v1/embeddings", self.embeddings),
                web.get("/v1/models", self.models),
                web.post("/clear_kv_blocks", self.clear_kv_blocks),
                web.get("/debug/timeline", self.debug_timeline),
                web.get("/health", self.health),
                web.get("/live", self.health),
                web.get("/ready", self.health),
                web.get("/metrics", self.prometheus),
                web.get("/openapi.json", self.openapi),
                web.get("/docs", self.docs),
            ]
        )
        m = self.metrics
        self._m_requests = m.counter(
            "http_requests_total", "HTTP requests", ["model", "route", "status"]
        )
        self._m_ttft = m.histogram(
            "time_to_first_token_seconds", "TTFT", ["model"]
        )
        self._m_itl = m.histogram(
            "inter_token_latency_seconds", "ITL", ["model"],
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5),
        )
        self._m_duration = m.histogram(
            "request_duration_seconds", "request duration", ["model"]
        )
        self._m_tokens = m.counter(
            "output_tokens_total", "generated tokens", ["model"]
        )
        self._m_input_tokens = m.counter(
            "input_tokens_total", "prompt tokens", ["model"]
        )
        self._m_completed = m.counter(
            "requests_completed_total",
            "generation requests that reached the backend", ["model"],
        )
        self._m_inflight = m.gauge(
            "inflight_requests", "in-flight requests", ["model"]
        )

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> tuple[str, int]:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in site._server.sockets:  # real bound port when port=0
            self.port = s.getsockname()[1]
            break
        log.info("http frontend on %s:%d", self.host, self.port)
        return self.host, self.port

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
        self._compute.shutdown()

    # -- helpers -----------------------------------------------------------

    def _pipeline_or_error(
        self, body: dict[str, Any]
    ) -> tuple[ModelPipeline | None, web.Response | None]:
        model = body.get("model")
        if not model:
            return None, _error(400, "missing 'model' field")
        pipe = self.manager.get(model)
        if pipe is None:
            return None, _error(
                404, f"model {model!r} not found", code="model_not_found"
            )
        return pipe, None

    def _traced_context(self, request: web.Request) -> Context:
        """Per-request Context joined to the route's server span (the
        ``http.request`` span the caller opened after ``bind_trace``, so
        its traceparent continues the client's W3C trace or starts a new
        one); the traceparent rides Context.headers to workers, where the
        transport client re-stamps it with its own ``transport.call``
        span at send time (runtime/tracing.py). Every request gets an
        END-TO-END DEADLINE (default ``request_timeout_s``;
        ``x-dyn-timeout-ms`` tightens it), propagated frontend ->
        migration -> worker so no failure chain can cost a client more
        than its budget.

        Tenancy (overload-control plane): the validated tenant id +
        priority class (``x-dyn-tenant`` / ``x-dyn-priority`` /
        api-key digest — frontend/validation.py validate_tenancy) are
        stamped into the same baggage headers, so they travel EPP ->
        transport -> worker and the engine's fair-admission layer sees
        exactly what the edge authenticated. Raises
        RequestValidationError (-> 400) on malformed tenancy headers."""
        from dynamo_tpu.frontend.validation import validate_tenancy

        tenant, priority = validate_tenancy(request.headers)
        headers: dict[str, str] = {
            TENANT_HEADER: tenant, PRIORITY_HEADER: priority,
        }
        cur = tracing.current_trace()
        if cur is None:
            cur = tracing.ensure_trace(headers)
        else:
            headers[tracing.TRACEPARENT] = cur.to_traceparent()
        timeout_s = self.request_timeout_s
        raw = request.headers.get(TIMEOUT_HEADER)
        if raw:
            # one shared clamp rule for every serving surface
            # (runtime/context.py; the gRPC frontend uses the same)
            timeout_s = tighten_timeout_s(timeout_s, raw)
        deadline = (
            time.monotonic() + timeout_s if timeout_s > 0 else None
        )
        return Context(
            request_id=new_request_id(), headers=headers, deadline=deadline
        )

    # -- routes ------------------------------------------------------------

    async def openapi(self, request) -> "web.Response":
        """OpenAPI 3 description of the served surface (ref http/service/
        openapi_docs.rs). Models list reflects live discovery."""
        models = sorted(self.manager.names())
        def op(summary, tag, stream=False, method="post"):
            body = {
                "summary": summary,
                "tags": [tag],
                "responses": {"200": {"description": "OK"}},
            }
            if method == "post":
                body["requestBody"] = {
                    "content": {"application/json": {"schema": {
                        "type": "object",
                        "properties": {"model": {
                            "type": "string", "enum": models or None,
                        }},
                    }}}
                }
            if stream:
                body["responses"]["200"]["description"] = (
                    "OK (SSE stream when request sets stream=true)"
                )
            return {method: body}

        spec = {
            "openapi": "3.0.3",
            "info": {
                "title": "dynamo-tpu OpenAI-compatible frontend",
                "version": "0.3.0",
            },
            "paths": {
                "/v1/chat/completions": op(
                    "Chat completion", "openai", stream=True),
                "/v1/completions": op("Text completion", "openai",
                                      stream=True),
                "/v1/responses": op("Responses API", "openai"),
                "/v1/embeddings": op("Embeddings", "openai"),
                "/v1/models": op("Discovered models", "openai",
                                 method="get"),
                "/clear_kv_blocks": op("Evict inactive prefix-cache pages "
                                       "on every worker", "admin"),
                "/health": op("Liveness", "ops", method="get"),
                "/metrics": op("Prometheus exposition", "ops", method="get"),
                "/debug/timeline": op(
                    "Flight-recorder timelines from every worker", "ops",
                    method="get"),
            },
        }
        return web.json_response(spec)

    async def docs(self, request) -> "web.Response":
        """Minimal human-readable API index (no JS bundle dependencies)."""
        spec = await self.openapi(request)
        import json as _json

        paths = _json.loads(spec.text)["paths"]
        rows = "".join(
            f"<tr><td><code>{next(iter(ops)).upper()}</code></td>"
            f"<td><code>{path}</code></td>"
            f"<td>{next(iter(ops.values()))['summary']}</td></tr>"
            for path, ops in paths.items()
        )
        html = (
            "<html><head><title>dynamo-tpu API</title></head><body>"
            "<h1>dynamo-tpu OpenAI-compatible frontend</h1>"
            "<p>Machine-readable spec: <a href='/openapi.json'>"
            "/openapi.json</a></p>"
            f"<table border=1 cellpadding=6>{rows}</table></body></html>"
        )
        return web.Response(text=html, content_type="text/html")

    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        return await self._completions_common(request, chat=True)

    async def completions(self, request: web.Request) -> web.StreamResponse:
        return await self._completions_common(request, chat=False)

    async def _completions_common(
        self, request: web.Request, *, chat: bool
    ) -> web.StreamResponse:
        route = "chat" if chat else "completions"
        try:
            body = await request.json()
        except json.JSONDecodeError:
            self._m_requests.labels("?", route, "400").inc()
            return _error(400, "invalid JSON body")
        try:
            validate_request(body, "chat" if chat else "completions")
        except RequestValidationError as e:
            self._m_requests.labels(str(body.get("model")) if isinstance(body, dict) else "?", route, "400").inc()
            return _error(400, str(e), param=e.param)
        pipe, err = self._pipeline_or_error(body)
        if err is not None:
            self._m_requests.labels(str(body.get("model")), route, str(err.status)).inc()
            return err
        model = pipe.card.name
        # server span for the whole route handling (admission through
        # stream completion), child of the client's traceparent when one
        # came in — the root of this request's frontend-side span tree.
        # bind_trace also CLEARS any binding a previous request left on
        # this keep-alive connection's task.
        tracing.bind_trace(request.headers)
        with tracing.span("http.request", route=route, model=model):
            try:
                ctx = self._traced_context(request)
            except RequestValidationError as e:
                # malformed tenancy header: typed 400 naming the header
                self._m_requests.labels(model, route, "400").inc()
                return _error(400, str(e), param=e.param)
            return await self._serve_completions(
                request, body, pipe, route, chat=chat, ctx=ctx
            )

    async def _serve_completions(
        self, request: web.Request, body: dict, pipe: ModelPipeline,
        route: str, *, chat: bool, ctx: Context,
    ) -> web.StreamResponse:
        model = pipe.card.name
        t_start = time.monotonic()
        self._m_inflight.labels(model).inc()
        try:
            try:
                # CPU-bound render+tokenize runs on the compute pool, not
                # the serving event loop (ref compute/pool.rs)
                with tracing.span("http.preprocess"):
                    preprocessed = await self._compute.run(
                        pipe.preprocessor.preprocess, body
                    )
            except ValueError as e:
                self._m_requests.labels(model, route, "400").inc()
                return _error(400, str(e))
            prompt_tokens = len(preprocessed["token_ids"])
            deltas = pipe.generate(preprocessed, ctx)
            timed = self._timed_stream(deltas, model, t_start)

            if preprocessed.get("guided"):
                # worker-side grammar rejections (compile fault, guided
                # decoding unavailable) arrive as the FIRST stream item,
                # typed "invalid_request:". Peek it so they map to a
                # real 400 instead of a 200 that immediately errors —
                # the invalid-schema-must-never-500-mid-stream contract.
                try:
                    first = await timed.__anext__()
                except StopAsyncIteration:
                    first = None
                err = (
                    str(first.get("error") or "")
                    if isinstance(first, dict)
                    and first.get("finish_reason") == "error" else ""
                )
                if err.startswith("invalid_request:"):
                    ctx.stop_generating()
                    msg = err[len("invalid_request:"):].strip()
                    self._m_requests.labels(model, route, "400").inc()
                    self._audit(
                        route, model, ctx, body, 400, t_start, error=msg
                    )
                    return _error(400, msg)
                timed = self._rechain(first, timed)

            # streamed requests: observe the delta stream so the audit
            # record carries real output tokens / finish reason, and a
            # mid-stream failure (delivered to the client as an SSE error
            # event over an already-200 response) is recorded as an error
            audit_state = {"tokens": 0, "finish": None, "error": None}
            if body.get("stream") and self.audit.enabled:
                timed = self._observe_for_audit(timed, audit_state)

            if body.get("stream"):
                pp = (
                    pipe.preprocessor.postprocess_chat_stream(
                        timed,
                        request_id=ctx.id,
                        include_usage=bool(
                            (body.get("stream_options") or {}).get("include_usage")
                        ),
                        prompt_tokens=prompt_tokens,
                        request=body,
                    )
                    if chat
                    else pipe.preprocessor.postprocess_completions_stream(
                        timed, request_id=ctx.id,
                        include_usage=bool(
                            (body.get("stream_options") or {}).get(
                                "include_usage"
                            )
                        ),
                        prompt_tokens=prompt_tokens,
                    )
                )
                resp = await self._sse(request, pp, ctx)
                self._m_requests.labels(model, route, "200").inc()
                self._mark_completed(model, prompt_tokens)
                self._audit(
                    route, model, ctx, body, 200, t_start,
                    finish_reason=audit_state["finish"],
                    output_tokens=audit_state["tokens"],
                    error=audit_state["error"],
                )
                return resp
            else:
                agg = (
                    await pipe.preprocessor.aggregate_chat(
                        timed, request_id=ctx.id, prompt_tokens=prompt_tokens,
                        request=body,
                    )
                    if chat
                    else await pipe.preprocessor.aggregate_completions(
                        timed, request_id=ctx.id, prompt_tokens=prompt_tokens
                    )
                )
                self._m_requests.labels(model, route, "200").inc()
                self._mark_completed(model, prompt_tokens)
                self._audit(
                    route, model, ctx, body, 200, t_start,
                    finish_reason=(agg.get("choices") or [{}])[0].get(
                        "finish_reason"
                    ),
                    output_tokens=(agg.get("usage") or {}).get(
                        "completion_tokens", 0
                    ),
                )
                return web.json_response(agg)
        except OverQuota as e:
            # the tenant's token bucket refused the request: typed 429
            # whose Retry-After is the bucket's own deficit / refill
            # estimate (engine/tenancy.py) — distinct from the 503 below
            # because backing off is the CLIENT's job here, not ours
            ctx.stop_generating()
            self._m_requests.labels(model, route, "429").inc()
            self._audit(route, model, ctx, body, 429, t_start, error=str(e))
            return _error(
                429, f"over quota: {e}", code="over_quota",
                headers={"Retry-After": _retry_after_header(e.retry_after_s)},
            )
        except (ServiceUnavailable, NoInstancesError) as e:
            # every worker draining/saturated (or none left) and the retry
            # budget exhausted: tell the client WHEN to come back instead
            # of a generic 500 (ref Orca-style bounded admission: shedding
            # with a hint beats queueing until the deadline)
            ctx.stop_generating()
            retry_after = getattr(e, "retry_after_s", 1.0)
            self._m_requests.labels(model, route, "503").inc()
            self._audit(route, model, ctx, body, 503, t_start, error=str(e))
            return _error(
                503, f"service unavailable: {e}", code="service_unavailable",
                headers={"Retry-After": _retry_after_header(retry_after)},
            )
        except DeadlineExceeded as e:
            ctx.stop_generating()
            self._m_requests.labels(model, route, "504").inc()
            self._audit(route, model, ctx, body, 504, t_start, error=str(e))
            return _error(504, f"deadline exceeded: {e}", code="deadline_exceeded")
        except Exception as e:  # noqa: BLE001
            log.exception("request %s failed", ctx.id)
            ctx.stop_generating()
            self._m_requests.labels(model, route, "500").inc()
            self._audit(route, model, ctx, body, 500, t_start, error=str(e))
            return _error(500, f"internal error: {e}")
        finally:
            self._m_inflight.labels(model).dec()
            self._m_duration.labels(model).observe(time.monotonic() - t_start)

    @staticmethod
    async def _rechain(first, rest):
        """Put a peeked item back in front of its stream."""
        if first is not None:
            yield first
        async for d in rest:
            yield d

    @staticmethod
    async def _observe_for_audit(stream, state: dict):
        try:
            async for d in stream:
                state["tokens"] += len(d.get("token_ids") or ())
                if d.get("finish_reason"):
                    state["finish"] = d["finish_reason"]
                if d.get("error"):
                    state["error"] = str(d["error"])
                yield d
        except Exception as e:  # noqa: BLE001
            state["error"] = str(e)
            raise

    def _audit(
        self, route: str, model: str, ctx, body: dict, status: int,
        t_start: float, *, finish_reason=None, output_tokens: int = 0,
        error: str | None = None,
    ) -> None:
        """Emit one audit record AFTER the response completes (ref
        lib/llm/src/audit/: bus + sinks off the request path)."""
        if not self.audit.enabled:
            return
        from dynamo_tpu.runtime.audit import AuditRecord

        self.audit.emit(AuditRecord.make(
            route=route, model=model, request_id=ctx.id, request=body,
            status=status, finish_reason=finish_reason,
            output_tokens=output_tokens,
            duration_ms=(time.monotonic() - t_start) * 1e3,
            error=error,
        ))

    def _mark_completed(self, model: str, prompt_tokens: int) -> None:
        """ISL/OSL averages for the SLA planner: counted only when the
        stream actually finished (output tokens accumulate in
        _timed_stream), so isl = input_tokens / requests_completed and
        osl = output_tokens / requests_completed line up per interval."""
        self._m_input_tokens.labels(model).inc(prompt_tokens)
        self._m_completed.labels(model).inc()

    async def _timed_stream(self, deltas, model: str, t_start: float):
        """Wrap the backend stream with TTFT/ITL/token metrics."""
        last = None
        async for d in deltas:
            now = time.monotonic()
            if last is None:
                self._m_ttft.labels(model).observe(now - t_start)
            else:
                self._m_itl.labels(model).observe(now - last)
            last = now
            self._m_tokens.labels(model).inc(len(d.get("token_ids") or ()))
            yield d

    async def _sse(
        self, request: web.Request, chunks, ctx: Context
    ) -> web.StreamResponse:
        resp = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
            },
        )
        await resp.prepare(request)
        try:
            async for chunk in chunks:
                await resp.write(_sse_bytes(chunk))
            await resp.write(_SSE_DONE)
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away: cancel the whole pipeline
            ctx.stop_generating()
            raise
        except Exception as e:  # noqa: BLE001
            # mid-stream failure (e.g. migration exhausted): the response is
            # already streaming, so deliver the error as a final SSE event
            log.exception("stream %s failed mid-flight", ctx.id)
            try:
                err = {"error": {"message": str(e), "type": "server_error"}}
                await resp.write(_sse_bytes(err))
                await resp.write(_SSE_DONE)
            except (ConnectionError, ConnectionResetError):
                pass
        finally:
            ctx.stop_generating()
        await resp.write_eof()
        return resp

    async def responses(self, request: web.Request) -> web.StreamResponse:
        """OpenAI Responses API surface (/v1/responses, ref http service
        openai.rs route list): maps input onto the chat pipeline; streams
        response.output_text.delta events or returns one response object."""
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _error(400, "invalid JSON body")
        try:
            validate_request(body, "responses")
        except RequestValidationError as e:
            return _error(400, str(e), param=e.param)
        pipe, err = self._pipeline_or_error(body)
        if err is not None:
            return err
        model = pipe.card.name
        inp = body.get("input", "")
        messages = (
            inp if isinstance(inp, list)
            else [{"role": "user", "content": str(inp)}]
        )
        chat_body = {
            "model": model,
            "messages": messages,
            "max_tokens": body.get("max_output_tokens"),
            "temperature": body.get("temperature"),
            "top_p": body.get("top_p"),
        }
        chat_body = {k: v for k, v in chat_body.items() if v is not None}
        tracing.bind_trace(request.headers)
        with tracing.span("http.request", route="responses", model=model):
            try:
                ctx = self._traced_context(request)
            except RequestValidationError as e:
                self._m_requests.labels(model, "responses", "400").inc()
                return _error(400, str(e), param=e.param)
            t_start = time.monotonic()
            try:
                return await self._serve_responses(
                    request, body, pipe, chat_body, ctx
                )
            except OverQuota as e:
                # same 429 accounting contract as the completions routes:
                # counted + audited, never just silently returned
                ctx.stop_generating()
                self._m_requests.labels(model, "responses", "429").inc()
                self._audit(
                    "responses", model, ctx, body, 429, t_start,
                    error=str(e),
                )
                return _error(
                    429, f"over quota: {e}", code="over_quota",
                    headers={
                        "Retry-After": _retry_after_header(e.retry_after_s)
                    },
                )

    async def _serve_responses(
        self, request: web.Request, body: dict, pipe: ModelPipeline,
        chat_body: dict, ctx: Context,
    ) -> web.StreamResponse:
        model = pipe.card.name
        rid = f"resp_{ctx.id}"
        try:
            with tracing.span("http.preprocess"):
                preprocessed = await self._compute.run(
                    pipe.preprocessor.preprocess, chat_body
                )
        except ValueError as e:
            return _error(400, str(e))
        prompt_tokens = len(preprocessed["token_ids"])
        deltas = self._timed_stream(
            pipe.generate(preprocessed, ctx), model, time.monotonic()
        )

        if body.get("stream"):
            resp = web.StreamResponse(
                headers={"Content-Type": "text/event-stream",
                         "Cache-Control": "no-store"}
            )
            await resp.prepare(request)

            async def send(event: str, payload: dict) -> None:
                await resp.write(_sse_event_bytes(event, payload))

            await send("response.created",
                       {"response": {"id": rid, "status": "in_progress"}})
            n_out = 0
            try:
                async for d in deltas:
                    n_out += len(d.get("token_ids") or ())
                    if d.get("finish_reason") == "error":
                        await send("response.failed", {
                            "response": {
                                "id": rid, "status": "failed",
                                "error": {"message": d.get("error")
                                          or "generation error"},
                            }
                        })
                        await resp.write_eof()
                        return resp
                    if d.get("text"):
                        await send(
                            "response.output_text.delta",
                            {"delta": d["text"], "item_id": rid},
                        )
                await send("response.completed", {
                    "response": {
                        "id": rid, "status": "completed",
                        "usage": {"input_tokens": prompt_tokens,
                                  "output_tokens": n_out},
                    }
                })
            except (ConnectionResetError, asyncio.CancelledError, StreamError):
                ctx.stop_generating()
                raise
            await resp.write_eof()
            self._mark_completed(model, prompt_tokens)
            return resp

        try:
            agg = await pipe.preprocessor.aggregate_chat(
                deltas, request_id=ctx.id, prompt_tokens=prompt_tokens,
                request=body,
            )
        except StreamError as e:
            ctx.stop_generating()
            return _error(502, f"generation failed: {e}")
        if agg["choices"][0]["finish_reason"] == "error":
            return _error(502, "generation error")
        msg = agg["choices"][0]["message"]
        self._mark_completed(model, prompt_tokens)
        return web.json_response({
            "id": rid,
            "object": "response",
            "created_at": agg["created"],
            "status": "completed",
            "model": model,
            "output": [{
                "type": "message",
                "id": f"msg_{ctx.id}",
                "role": "assistant",
                "status": "completed",
                "content": [{
                    "type": "output_text",
                    "text": msg.get("content") or "",
                    "annotations": [],
                }],
            }],
            "usage": {
                "input_tokens": prompt_tokens,
                "output_tokens": agg["usage"]["completion_tokens"],
                "total_tokens": agg["usage"]["total_tokens"],
            },
        })

    async def _admin_components(self) -> list[tuple[str, str]]:
        """Discover every component exposing an admin endpoint — NOT via
        model cards: prefill workers register no card but do register
        admin (disagg deployments must reach both pools)."""
        instance_keys = await self._drt.hub.get_prefix("v1/instances/")
        admin_components: set[tuple[str, str]] = set()
        for key in instance_keys:
            parts = key.split("/")
            # v1/instances/{ns}/{component}/{endpoint}/{instance}
            if len(parts) >= 6 and parts[4] == "admin":
                admin_components.add((parts[2], parts[3]))
        return sorted(admin_components)

    async def debug_timeline(self, request: web.Request) -> web.Response:
        """Flight-recorder query: fan ``{"op": "timeline"}`` out to every
        worker's admin endpoint and merge the answers — by request id
        (``?request_id=``) for one full per-request event timeline
        (admission -> phase transitions -> finish, with trace_id), or
        without for each worker's summary view (active + recent tail +
        retained errors/slowest). The HTTP face of runtime/flight.py."""
        if self._drt is None:
            return _error(501, "admin plane unavailable (no runtime handle)")
        request_id = request.query.get("request_id")
        try:
            n = int(request.query.get("n") or 16)
        except ValueError:
            return _error(400, "n must be an integer")
        results: dict[str, Any] = {}
        for ns, comp in await self._admin_components():
            ep = self._drt.namespace(ns).component(comp).endpoint("admin")
            client = await ep.client().start()
            try:
                try:
                    await client.wait_for_instances(1, timeout=2)
                except TimeoutError:
                    results[f"{ns}/{comp}"] = {"error": "no admin instances"}
                    continue
                workers: dict[str, Any] = {}
                for inst in client.instances():
                    try:
                        # aclosing: breaking out of the stream must
                        # close the generator IN THIS TASK, so its
                        # transport.call span ends here instead of at
                        # GC finalization (where the contextvar binding
                        # would leak and mis-parent the next hop's span)
                        async with contextlib.aclosing(
                            client.call_instance(
                                inst.instance_id,
                                {"op": "timeline",
                                 "request_id": request_id, "n": n},
                                # bounded admin budget (DL008): one
                                # wedged worker must not hang the fan-out
                                Context(deadline=time.monotonic() + 10.0),
                            )
                        ) as stream:
                            async for item in stream:
                                workers[f"{inst.instance_id:x}"] = item
                                break
                    except (StreamError, DeadlineExceeded) as e:
                        workers[f"{inst.instance_id:x}"] = {
                            "error": str(e)
                        }
                results[f"{ns}/{comp}"] = workers
            finally:
                await client.close()
        return web.json_response(
            {"request_id": request_id, "results": results}
        )

    async def clear_kv_blocks(self, request: web.Request) -> web.Response:
        """Admin: evict every worker's inactive prefix-cache pages (ref
        http/service/clear_kv_blocks.rs -> worker admin endpoints)."""
        if self._drt is None:
            return _error(501, "admin plane unavailable (no runtime handle)")
        results: dict[str, Any] = {}
        for ns, comp in await self._admin_components():
            ep = self._drt.namespace(ns).component(comp).endpoint("admin")
            client = await ep.client().start()
            try:
                try:
                    await client.wait_for_instances(1, timeout=2)
                except TimeoutError:
                    results[f"{ns}/{comp}"] = {"error": "no admin instances"}
                    continue
                acks = 0
                for inst in client.instances():
                    try:
                        # aclosing: same early-break contract as
                        # debug_timeline — close the stream in-task so
                        # the transport.call span/context unwind here
                        async with contextlib.aclosing(
                            client.call_instance(
                                inst.instance_id,
                                {"op": "clear_kv_blocks"},
                                # bounded admin budget: one wedged worker
                                # must not hang the whole fan-out (DL008)
                                Context(deadline=time.monotonic() + 10.0),
                            )
                        ) as stream:
                            async for item in stream:
                                if isinstance(item, dict) and item.get("ok"):
                                    acks += 1
                                break
                    except (StreamError, DeadlineExceeded):
                        pass
                results[f"{ns}/{comp}"] = {"workers_cleared": acks}
            finally:
                await client.close()
        return web.json_response({"results": results})

    async def embeddings(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return _error(400, "invalid JSON body")
        try:
            validate_request(body, "embeddings")
        except RequestValidationError as e:
            return _error(400, str(e), param=e.param)
        pipe, err = self._pipeline_or_error(body)
        if err is not None:
            return err
        if pipe.card.model_type != "embeddings":
            return _error(
                400, f"model {pipe.card.name!r} is not an embeddings model"
            )
        # shape already validated at the edge (validate_request)
        inputs = body.get("input")
        if isinstance(inputs, str):
            inputs = [inputs]
        # same trace + end-to-end deadline contract as the generation
        # routes (dynalint DL008: a deadline-less root here left every
        # embedding fan-out unbounded)
        tracing.bind_trace(request.headers)
        with tracing.span(
            "http.request", route="embeddings", model=pipe.card.name
        ):
            try:
                ctx = self._traced_context(request)
            except RequestValidationError as e:
                return _error(400, str(e), param=e.param)
            return await self._serve_embeddings(pipe, inputs, ctx)

    async def _serve_embeddings(
        self, pipe: ModelPipeline, inputs: list, ctx: Context
    ) -> web.Response:
        data = []
        for i, text in enumerate(inputs):
            token_ids = pipe.preprocessor.tokenizer.encode(text)
            out = None
            try:
                async for item in pipe.generate(
                    {"token_ids": token_ids,
                     "stop_conditions": {"max_tokens": 1},
                     "embedding_request": True},
                    ctx.child(f"{ctx.id}-{i}"),
                ):
                    if isinstance(item, dict) and "embedding" in item:
                        out = item["embedding"]
            except DeadlineExceeded as e:
                # the context now carries a deadline: expiry mid-batch is
                # the 504 contract, same as the generation routes
                return _error(
                    504, f"deadline exceeded: {e}", code="deadline_exceeded"
                )
            if out is None:
                return _error(502, "worker returned no embedding")
            data.append({"object": "embedding", "index": i, "embedding": out})
        return web.json_response(
            {"object": "list", "data": data, "model": pipe.card.name,
             "usage": {"prompt_tokens": 0, "total_tokens": 0}}
        )

    async def models(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {
                        "id": c.name,
                        "object": "model",
                        "owned_by": "dynamo-tpu",
                        "created": 0,
                        "meta": {
                            "context_length": c.context_length,
                            "model_type": c.model_type,
                            "router_mode": c.router_mode,
                        },
                    }
                    for c in self.manager.cards()
                ],
            }
        )

    async def health(self, request: web.Request) -> web.Response:
        models = {}
        for pipe in [self.manager.get(n) for n in self.manager.names()]:
            if pipe:
                models[pipe.card.name] = {
                    "instances": len(pipe.push_router.client.instance_ids())
                }
        status = "healthy" if models else "no_models"
        return web.json_response({"status": status, "models": models})

    async def prometheus(self, request: web.Request) -> web.Response:
        return web.Response(
            body=self.metrics.exposition(),
            content_type="text/plain",
            charset="utf-8",
        )


def _retry_after_header(retry_after_s: float) -> str:
    """HTTP Retry-After is integer seconds: round UP so a 0.4 s hint
    becomes 1, never 0 (a zero would read as 'retry immediately' and
    defeat the backoff the hint exists to request)."""
    import math

    return str(max(int(math.ceil(retry_after_s)), 1))


def _error(
    status: int, message: str, code: str | None = None,
    param: str | None = None, headers: dict[str, str] | None = None,
) -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": "invalid_request_error",
                   "param": param, "code": code}},
        status=status,
        headers=headers,
    )
