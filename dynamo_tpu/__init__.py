"""dynamo-tpu: TPU-native distributed LLM inference-serving framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of NVIDIA Dynamo
(see SURVEY.md at the repo root): OpenAI-compatible frontend, KV-cache-aware
routing over a global radix index, disaggregated prefill/decode serving on
separate TPU slices, a multi-tier KV block manager (HBM -> host DRAM -> disk),
SLA-driven autoscaling, request migration and health-based fault tolerance,
and a mock-engine test harness.

Layering (mirrors reference layer map, SURVEY.md section 1):
  runtime/   - distributed runtime: components, endpoints, transports, hub
  tokens.py  - token block hashing primitives (ref: lib/tokens, lib/llm/src/tokens.rs)
  kv_router/ - KV-cache-aware routing (ref: lib/llm/src/kv_router/)
  mocker/    - simulated engine for infra tests (ref: lib/llm/src/mocker/)
  frontend/  - OpenAI HTTP frontend + preprocessor pipeline (ref: lib/llm/src/http, preprocessor.rs)
  engine/    - the JAX inference engine (genuinely new: paged attention, continuous batching)
  models/    - model definitions (llama, MoE) with mesh shardings
  ops/       - Pallas TPU kernels + pure-JAX references
  parallel/  - mesh construction, ring attention, KV transfer over ICI/DCN
  kvbm/      - tiered KV block manager (ref: lib/llm/src/block_manager/)
  planner/   - SLA autoscaler (ref: components/src/dynamo/planner/)
"""

__version__ = "0.1.0"
