"""Sim harness: fleet/cluster building blocks + artifact plumbing.

Everything here composes REAL components — ``MockEngine`` workers served
through the real ``DistributedRuntime`` endpoint plumbing (so the KV
router sees real KV events and worker metrics), in-process and
subprocess ``HubReplica`` quorum clusters, and the Migration-wrapped
client path the frontend uses — the scenarios in ``scenarios.py`` only
script traffic and chaos on top.
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import signal
import time
from dataclasses import asdict, dataclass
from pathlib import Path

from dynamo_tpu.frontend.migration import STATS as MIGRATION_STATS
from dynamo_tpu.frontend.migration import Migration
from dynamo_tpu.kv_router.protocols import RouterConfig
from dynamo_tpu.kv_router.publisher import (
    KvEventPublisher,
    WorkerMetricsPublisher,
)
from dynamo_tpu.kv_router.router import KvPushRouter, KvRouter
from dynamo_tpu.mocker.engine import MockEngine, MockEngineConfig
from dynamo_tpu.runtime.context import StreamError
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.hub import InMemoryHub
from dynamo_tpu.runtime.hub_replica import HubReplica
from dynamo_tpu.runtime.push import PushRouter, RouterMode
from dynamo_tpu.sim import cluster as hubctl

log = logging.getLogger("dynamo.sim")

NS, COMP, EP = "sim", "mock", "generate"


@dataclass
class SimConfig:
    """One knob set for a whole sim run; scenarios read what they need.
    Defaults are the full-matrix (nightly) scale; the tier-1 smoke in
    tests/test_cluster_sim.py shrinks everything."""

    workers: int = 200
    speedup: float = 150.0  # time dilation: simulated s per wall s
    block_size: int = 16
    worker_blocks: int = 2048
    max_batch_size: int = 8
    seed: int = 0
    # pick_scaling: fleet sizes for the saturation curve (empty =
    # derived: workers/4, workers/2, workers)
    fleet_sizes: tuple = ()
    picks: int = 400
    pick_concurrency: int = 8
    # hub scenarios
    replicas: int = 3
    lease_s: float = 0.5
    commit_timeout_s: float = 1.5
    storm_writers: int = 8
    storm_duration_s: float = 8.0
    partition_window_s: float = 3.0
    # churn / storms
    trace_requests: int = 0  # 0 = 2 * workers
    trace_rate_per_s: float = 0.0  # 0 = workers * 10 req/s (wall)
    churn_waves: int = 3
    churn_kill_frac: float = 0.12
    osl: int = 8
    # tenant storm SLO: contended interactive TTFT p99 must stay under
    # max(slo_ttft_factor * uncontended p50, slo_ttft_floor_s)
    slo_ttft_factor: float = 4.0
    slo_ttft_floor_s: float = 0.25
    # autoscale scenario: diurnal wave + flash spike against the closed
    # autoscaler loop. The scenario builds its OWN small fleets (slow
    # engines so concurrency is visible demand), so these knobs are
    # independent of the churn-scale ones above.
    autoscale_duration_s: float = 12.0
    autoscale_base_rate: float = 12.0  # wall req/s at the trough
    autoscale_peak_rate: float = 40.0  # diurnal crest
    autoscale_spike_factor: float = 10.0  # flash spike = factor * base
    autoscale_tick_s: float = 0.3  # controller cadence (wall)
    autoscale_lead_ticks: int = 3  # predictive pass forecast horizon
    autoscale_start_workers: int = 2
    autoscale_max_workers: int = 24
    autoscale_slots: int = 2  # decode slots per worker
    autoscale_speedup: float = 5.0
    autoscale_osl: int = 40
    autoscale_slo_ttft_s: float = 0.75  # wall TTFT p99 bar
    autoscale_compare: bool = True  # also run the reactive baseline
    # worker ForwardPassMetrics publish cadence (wall s); the gray
    # scenario shrinks it so degradation fingerprints propagate fast
    # enough to meet its dilated detection budget
    metrics_interval_s: float = 0.25
    # gray_failure scenario: one worker degraded to ``gray_slowdown``x
    # step time via a sticky per-instance delay fault must be detected
    # peer-relatively, quarantined within ``gray_detect_budget_s``
    # DILATED seconds, excluded by routers, replaced by the autoscaler,
    # and re-admitted after it heals — with zero client-visible errors.
    # Builds its OWN small, mildly-dilated fleet (like autoscale).
    gray_workers: int = 6
    gray_speedup: float = 5.0
    gray_slowdown: float = 10.0
    gray_requests: int = 36  # per traffic phase (baseline / degraded / after)
    gray_rate_per_s: float = 40.0
    gray_osl: int = 6
    gray_detect_budget_s: float = 5.0  # dilated seconds
    data_dir: str | None = None  # replica WALs; None = tempdir

    def trace_n(self) -> int:
        return self.trace_requests or 2 * self.workers

    def trace_rate(self) -> float:
        # wall req/s; the DILATED rate (x speedup) is what the artifact
        # reports — at the default dilation the achieved replay clears
        # 100k req/s dilated even where the single replay loop binds
        return self.trace_rate_per_s or self.workers * 10.0

    def sizes(self) -> list[int]:
        if self.fleet_sizes:
            return sorted(set(int(s) for s in self.fleet_sizes))
        w = self.workers
        return sorted({max(w // 4, 2), max(w // 2, 4), w})


# -- mock worker fleet -------------------------------------------------------


class SimWorker:
    """One mock worker with a power switch: ``kill()`` makes in-flight
    streams die exactly like a cut connection (StreamError at the next
    item — the transport's peer-vanished contract, which the migration
    operator retries) and withdraws the instance registration."""

    def __init__(self, fleet: "MockFleet", engine: MockEngine):
        self.fleet = fleet
        self.engine = engine
        self.alive = True
        self.served = None
        self.events: KvEventPublisher | None = None
        self.metrics: WorkerMetricsPublisher | None = None
        # gray-failure state: a quarantined worker is ALIVE (card stays
        # in the hub, flagged) but cuts its in-flight streams so the
        # migration operator re-drives them on healthy peers
        self.quarantined = False
        self.served_requests = 0

    @property
    def wid(self) -> int:
        return self.served.instance.instance_id if self.served else 0

    @property
    def fault_instance(self) -> str:
        """Identity this worker presents to ``~instance``-scoped faults."""
        return self.engine.config.fault_instance

    def handler(self):
        async def _serve(request, context):
            if not self.alive:
                raise StreamError(f"sim worker {self.wid:x} is dead")
            self.served_requests += 1
            async for item in self.engine.generate(request, context):
                if not self.alive:
                    raise StreamError(
                        f"sim worker {self.wid:x} killed mid-stream"
                    )
                if self.quarantined:
                    # proactive migration off gray capacity: the stream
                    # dies with the peer-vanished contract the migration
                    # operator already re-drives
                    raise StreamError(
                        f"sim worker {self.wid:x} quarantined mid-stream"
                    )
                yield item
        return _serve

    async def kill(self) -> None:
        """SIGKILL-shaped: no drain, no dying KV events — the fleet's
        router keeps stale radix state exactly as it would for a real
        crashed worker until instance reconciliation prunes it."""
        self.alive = False
        if self.events is not None:
            await self.events.close()
        if self.metrics is not None:
            await self.metrics.close()
        # drain=False: a crash does not get the withdraw grace — the
        # handler vanishes with the key, exactly like a dead process
        await self.fleet.drt.deregister_endpoint(self.served, drain=False)

    async def drain(self, timeout_s: float = 10.0) -> None:
        """SIGTERM-shaped scale-down: withdraw the instance key FIRST
        (routers stop picking; racing picks still land on the live
        handler through the withdraw grace), then wait for in-flight
        streams to finish before tearing the worker down — the sim twin
        of the worker drain contract (zero client-visible errors)."""
        await self.fleet.drt.deregister_endpoint(self.served, drain=True)
        deadline = time.monotonic() + timeout_s
        while self.engine._running > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.005)
        self.alive = False
        if self.events is not None:
            await self.events.close()
        if self.metrics is not None:
            await self.metrics.close()


class MockFleet:
    """N time-dilated mock workers on one DistributedRuntime, with kill
    and rejoin waves for churn scenarios."""

    def __init__(self, cfg: SimConfig, n: int, *, hub=None, seed: int = 0):
        self.cfg = cfg
        self.n = n
        self.hub = hub or InMemoryHub()
        self.drt = DistributedRuntime(self.hub)
        self.workers: list[SimWorker] = []
        self.launched = 0
        self.rng = random.Random(seed or cfg.seed)
        self._push: PushRouter | None = None
        self._kv: KvRouter | None = None

    async def start(self) -> "MockFleet":
        for _ in range(self.n):
            await self.launch_worker()
        return self

    async def launch_worker(self) -> SimWorker:
        i = self.launched
        self.launched += 1
        engine = MockEngine(MockEngineConfig(
            block_size=self.cfg.block_size,
            total_kv_blocks=self.cfg.worker_blocks,
            max_batch_size=self.cfg.max_batch_size,
            speedup_ratio=self.cfg.speedup,
            seed=self.cfg.seed * 100003 + i,
            # per-worker fault identity: many sim workers share one
            # process (one FAULTS registry), so ~instance-scoped rules
            # (the sticky gray-failure straggler) need each engine to
            # say who it is on every fire
            fault_instance=f"sim-w{i}",
        ))
        w = SimWorker(self, engine)
        ep = self.drt.namespace(NS).component(COMP).endpoint(EP)
        w.served = await ep.serve(
            w.handler(),
            metadata={"model": "sim-model", "engine": "mocker"},
        )
        comp_path = f"{NS}/{COMP}"
        w.events = KvEventPublisher(self.drt.hub, comp_path, w.wid).start()
        w.metrics = WorkerMetricsPublisher(
            self.drt.hub, comp_path, w.wid,
            interval_s=self.cfg.metrics_interval_s,
        ).start()
        engine.events = w.events
        engine.metrics = w.metrics
        engine._publish_metrics()
        self.workers.append(w)
        return w

    def alive_workers(self) -> list[SimWorker]:
        return [w for w in self.workers if w.alive]

    async def kill_wave(
        self, k: int, wait_busy_s: float = 2.0
    ) -> list[SimWorker]:
        """Kill up to ``k`` workers, catching BUSY ones in the act: at
        heavy time dilation a request lives for ~ms, so a wave that
        picks victims blindly almost never cuts an in-flight stream —
        and cutting streams (so migration re-drives them) is the point.
        Polls for workers with running requests and flips their power
        switch mid-flight; falls back to idle victims at the deadline."""
        victims: list[SimWorker] = []
        deadline = time.monotonic() + wait_busy_s
        while len(victims) < k and time.monotonic() < deadline:
            alive = self.alive_workers()
            if len(alive) <= 1:
                break
            busy = [
                w for w in alive
                if w.engine._running > 0 and w not in victims
            ]
            if busy:
                w = self.rng.choice(busy)
                w.alive = False  # streams on it die at the next item
                victims.append(w)
                await w.kill()
            else:
                await asyncio.sleep(0.001)
        idle = [w for w in self.alive_workers() if w not in victims]
        self.rng.shuffle(idle)
        while len(victims) < k and len(idle) > 1:
            w = idle.pop()
            victims.append(w)
            await w.kill()
        return victims

    async def rejoin_wave(self, k: int) -> None:
        # thundering-herd shape on purpose: all replacements register at
        # once (hub put + event/metrics stream (re)subscription each)
        await asyncio.gather(*(self.launch_worker() for _ in range(k)))

    async def quarantine_worker(self, w: SimWorker, reason: str) -> None:
        """Soft-withdraw a gray worker: its instance card stays in the
        hub flagged ``quarantined`` (routers exclude it through the
        exclude= fail-open path, the autoscaler counts it as zero
        capacity), and its in-flight streams are cut so the migration
        operator re-drives them on healthy peers."""
        from dynamo_tpu.runtime.health import count_quarantine, quarantined_card

        w.quarantined = True
        count_quarantine(reason)
        card = quarantined_card(w.served.instance, reason)
        # plain put (no lease arg): the key keeps its existing binding to
        # the worker's lease, so worker death still removes the card
        await self.drt.hub.put(card.path, card.to_dict())

    async def readmit_worker(self, w: SimWorker) -> None:
        """Lift a quarantine: republish the clean card; routers pick the
        worker again and the autoscaler's replacement overlay unwinds."""
        from dynamo_tpu.runtime.health import admitted_card

        w.quarantined = False
        card = admitted_card(w.served.instance)
        await self.drt.hub.put(card.path, card.to_dict())

    async def client_path(
        self, *, migration: bool = True, **mig_kwargs
    ):
        """The frontend's serving path, minus HTTP: KV-aware routing
        wrapped in the migration operator. Returns (engine-like, parts)
        where parts need closing via ``close_client``."""
        ep = self.drt.namespace(NS).component(COMP).endpoint(EP)
        self._push = await PushRouter.from_endpoint(ep, RouterMode.DIRECT)
        await self._push.client.wait_for_instances(
            len(self.alive_workers()), timeout=15
        )
        self._kv = await KvRouter(
            self.drt.hub, f"{NS}/{COMP}",
            RouterConfig(block_size=self.cfg.block_size),
        ).start()
        engine = KvPushRouter(self._push, self._kv)
        if migration:
            mig_kwargs.setdefault("migration_limit", 6)
            mig_kwargs.setdefault("retry_budget_s", 15.0)
            mig_kwargs.setdefault("retry_delay_s", 0.05)
            engine = Migration(engine, **mig_kwargs)
        return engine

    @property
    def kv_router(self) -> KvRouter | None:
        return self._kv

    async def close(self) -> None:
        if self._kv is not None:
            await self._kv.close()
        if self._push is not None:
            await self._push.client.close()
        for w in self.alive_workers():
            if w.events is not None:
                await w.events.close()
            if w.metrics is not None:
                await w.metrics.close()
        await self.drt.close()


def migrations_snapshot() -> int:
    return MIGRATION_STATS["migrations"]


# -- hub replica clusters ----------------------------------------------------


class ReplicaCluster:
    """In-process quorum cluster (HubReplica objects): fast to start,
    partitionable live via ``FAULTS.configure`` (the partition site is
    consulted inside this process's replica links)."""

    def __init__(self, cfg: SimConfig, base_dir: Path):
        self.cfg = cfg
        self.base = Path(base_dir)
        self.reps: list[HubReplica] = []
        self.addrs: list[str] = []

    async def start(self) -> "ReplicaCluster":
        ports = sorted(hubctl.free_port() for _ in range(self.cfg.replicas))
        self.addrs = [f"127.0.0.1:{p}" for p in ports]
        peers = ",".join(self.addrs)
        self.reps = [
            HubReplica(
                "127.0.0.1", p, peers, self.base / f"replica{i}",
                lease_s=self.cfg.lease_s,
                commit_timeout_s=self.cfg.commit_timeout_s,
            )
            for i, p in enumerate(ports)
        ]
        for r in self.reps:
            await r.start()
        return self

    async def wait_leader(self, timeout: float = 20.0) -> HubReplica:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            live = [r for r in self.reps if not r._stopping]
            leaders = [r for r in live if r.hub.role == "leader"]
            if len(leaders) == 1 and all(
                r.leader_addr == leaders[0].advertise for r in live
            ):
                return leaders[0]
            await asyncio.sleep(0.02)
        raise AssertionError(
            f"no single leader: "
            f"{[(r.advertise, r.hub.role) for r in self.reps]}"
        )

    def data_dirs(self) -> list[Path]:
        return [r.hub.store.dir for r in self.reps]

    async def stop_all(self) -> None:
        for r in self.reps:
            await r.stop()


class ProcReplicaCluster:
    """Subprocess quorum cluster (``python -m
    dynamo_tpu.runtime.hub_replica``): the leader can be SIGKILLed for
    real — the kill -9 mid-commit-storm scenario."""

    def __init__(self, cfg: SimConfig, base_dir: Path):
        self.cfg = cfg
        self.base = Path(base_dir)
        self.addrs: list[str] = []
        self.procs: dict[str, object] = {}
        self.dirs: dict[str, Path] = {}

    async def start(self) -> "ProcReplicaCluster":
        ports = sorted(hubctl.free_port() for _ in range(self.cfg.replicas))
        self.addrs = [f"127.0.0.1:{p}" for p in ports]
        peers = ",".join(self.addrs)
        for i, a in enumerate(self.addrs):
            d = self.base / f"rep{i}"
            self.dirs[a] = d
            self.procs[a] = await asyncio.to_thread(
                hubctl.spawn_replica, a, peers, str(d), self.cfg.lease_s
            )
        return self

    async def find_leader(self, timeout: float = 20.0) -> str:
        return await hubctl.find_leader(self.addrs, timeout)

    def sigkill(self, addr: str) -> None:
        self.procs[addr].send_signal(signal.SIGKILL)

    def terminate_all(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=10)
            # dynalint: disable=DL003 -- last-resort teardown: a replica
            # that ignores SIGTERM for 10s gets SIGKILLed; the escalation
            # IS the handling (WALs are read post-mortem either way)
            except Exception:  # noqa: BLE001
                p.kill()

    def data_dirs(self) -> list[Path]:
        return [self.dirs[a] for a in self.addrs]


# -- telemetry overhead micro-measure ---------------------------------------


def telemetry_overhead(cfg: SimConfig, iters: int = 4000) -> dict:
    """Span/metric emission cost as a fraction of a (dilated) engine
    step — the 'does observability self-DoS at fleet scale' number
    ROADMAP #7 asks for. Measures the real emit paths: a catalogued
    ``tracing.span`` (epp.pick — the hot control-plane span) and a
    labeled prometheus counter inc."""
    from dynamo_tpu.runtime import tracing
    from dynamo_tpu.runtime.metrics import MetricsRegistry

    t0 = time.perf_counter()
    for _ in range(iters):
        with tracing.span("epp.pick"):
            pass
    span_s = (time.perf_counter() - t0) / iters

    reg = MetricsRegistry()
    # dynalint: disable=DL006 -- throwaway probe counter on a private
    # registry, never exported on any /metrics surface: cataloguing it
    # would advertise a metric no dashboard can ever scrape
    ctr = reg.counter("sim_overhead_probe_total", "sim micro-bench", ["k"])
    t0 = time.perf_counter()
    for _ in range(iters):
        ctr.labels("x").inc()
    ctr_s = (time.perf_counter() - t0) / iters

    dilated_step_s = MockEngineConfig().decode_step_s / max(cfg.speedup, 1e-9)
    # a serving step emits ~1 span-equivalent + ~4 counter/gauge updates
    per_step = span_s + 4 * ctr_s
    return {
        "span_emit_us": round(span_s * 1e6, 3),
        "counter_inc_us": round(ctr_s * 1e6, 3),
        "dilated_step_us": round(dilated_step_s * 1e6, 3),
        "emission_frac_of_step": round(per_step / dilated_step_s, 4),
        # the undilated fraction is what a REAL worker pays (step time
        # not shrunk by speedup): the honest production number
        "emission_frac_of_real_step": round(
            per_step / MockEngineConfig().decode_step_s, 6
        ),
    }


# -- orchestration + artifact ------------------------------------------------


async def run_scenarios(
    cfg: SimConfig, names: list[str]
) -> dict:
    """Run the named scenarios sequentially; AssertionError = a failed
    invariant (verdict fail with the reason), any other exception is a
    harness error (verdict error). Returns the artifact dict."""
    import shutil
    import tempfile

    from dynamo_tpu.sim.scenarios import SCENARIOS

    # one run-scoped scratch dir for every scenario's WALs and traces
    # (kept on a failing run for post-mortem, removed on pass) — per-
    # scenario mkdtemps would accumulate in /tmp across nightlies
    own_scratch = not cfg.data_dir
    if own_scratch:
        cfg.data_dir = tempfile.mkdtemp(prefix="dynamo-sim-")
    artifact: dict = {
        "schema": "dynamo-sim/v1",
        "config": asdict(cfg),
        "scenarios": {},
    }
    for name in names:
        fn = SCENARIOS[name]
        log.warning("sim scenario %s starting", name)
        t0 = time.monotonic()
        try:
            out = await fn(cfg)
            out.setdefault("verdict", _verdict(out))
        except AssertionError as e:
            out = {"verdict": "fail", "reason": str(e)}
        except Exception as e:  # noqa: BLE001 — harness error != invariant fail
            log.exception("sim scenario %s errored", name)
            out = {"verdict": "error", "reason": f"{type(e).__name__}: {e}"}
        out["wall_s"] = round(time.monotonic() - t0, 2)
        artifact["scenarios"][name] = out
        log.warning(
            "sim scenario %s: %s (%.1fs)", name, out["verdict"], out["wall_s"]
        )
    artifact["verdict"] = (
        "pass"
        if all(
            s["verdict"] == "pass" for s in artifact["scenarios"].values()
        )
        else "fail"
    )
    if own_scratch:
        if artifact["verdict"] == "pass":
            shutil.rmtree(cfg.data_dir, ignore_errors=True)
        else:
            log.warning(
                "sim scratch kept for post-mortem: %s", cfg.data_dir
            )
    return artifact


def _verdict(out: dict) -> str:
    inv = out.get("invariants") or {}
    ok = all(
        (v.get("pass") if isinstance(v, dict) else bool(v))
        for v in inv.values()
    )
    return "pass" if ok else "fail"


def write_artifact(artifact: dict, path: str) -> None:
    Path(path).write_text(json.dumps(artifact, indent=1, default=str) + "\n")
    log.warning("sim artifact written to %s", path)
