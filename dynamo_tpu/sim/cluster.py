"""Hub-replica cluster drivers + the jepsen-style WAL invariant checker.

Promoted out of ``tests/hub_cluster.py`` (which re-exports everything
here, so existing test imports keep working) because the cluster sim
(dynamo_tpu/sim/scenarios.py) asserts the SAME raft-lite safety contract
its chaos scenarios rely on: spawn ``python -m
dynamo_tpu.runtime.hub_replica`` subprocesses, poll their ``repl.status``
over the framed transport, build ``transport.partition`` fault specs,
and replay replica WALs through ``check_cluster_invariants``. One copy
of each protocol, so a CLI-flag or schema change has a single place to
land."""

from __future__ import annotations

import asyncio
import os
import socket
import struct
import subprocess
import sys
import time
from pathlib import Path

import msgpack

from dynamo_tpu.runtime import framing

__all__ = [
    "free_port",
    "spawn_replica",
    "repl_status",
    "find_leader",
    "partition_spec",
    "isolate_spec",
    "read_wal",
    "check_cluster_invariants",
]


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def spawn_replica(
    addr: str, peers: str, data_dir: str, lease_s: float = 1.0
) -> subprocess.Popen:
    """Start one replica process and block until it prints DYNAMO_HUB=
    (listening); callers SIGKILL it freely."""
    host, port = addr.rsplit(":", 1)
    proc = subprocess.Popen(
        [sys.executable, "-m", "dynamo_tpu.runtime.hub_replica",
         "--host", host, "--port", port, "--peers", peers,
         "--data-dir", data_dir, "--lease-s", str(lease_s)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    line = proc.stdout.readline().decode()
    assert "DYNAMO_HUB=" in line, line
    return proc


async def repl_status(addr: str) -> dict | None:
    """One ``repl.status`` probe; None when unreachable/unresponsive."""
    host, port = addr.rsplit(":", 1)
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, int(port)), 1.0
        )
    except (OSError, asyncio.TimeoutError):
        return None
    try:
        await framing.write_frame(writer, {"id": 1, "op": "repl.status"})
        msg = await asyncio.wait_for(framing.read_frame(reader), 1.0)
        return msg.get("result") if msg and msg.get("ok") else None
    except (OSError, asyncio.TimeoutError):
        return None
    finally:
        writer.close()


async def find_leader(addrs: list[str], timeout: float = 15.0) -> str:
    """Poll until exactly ONE replica claims leadership; its address."""
    statuses: list = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        statuses = [await repl_status(a) for a in addrs]
        leaders = [
            s["addr"] for s in statuses if s and s.get("role") == "leader"
        ]
        if len(leaders) == 1:
            return leaders[0]
        await asyncio.sleep(0.1)
    raise AssertionError(f"no unique leader among {addrs}: {statuses}")


# -- partition fault specs ---------------------------------------------------


def partition_spec(*pairs: tuple[str, str], one_way: bool = False) -> str:
    """``transport.partition`` DYN_FAULTS entries for the given address
    pairs (``one_way=True``: traffic a -> b is cut, b -> a still flows)."""
    sep = ">" if one_way else "|"
    return ",".join(
        f"transport.partition:drop={a}{sep}{b}" for a, b in pairs
    )


def isolate_spec(addr: str, others: list[str]) -> str:
    """Symmetric partition cutting ``addr`` off from every other replica."""
    return partition_spec(*[(addr, o) for o in others if o != addr])


# -- jepsen-style WAL invariant checker --------------------------------------

_LEN = struct.Struct(">I")


def read_wal(data_dir: str | Path) -> tuple[dict | None, list[dict]]:
    """Read-only WAL load: (snapshot state or None, records of the
    snapshot's generation). Unlike HubStore.load this never truncates a
    torn tail — safe on a live replica's dir once writes are quiesced."""
    d = Path(data_dir)
    state = None
    gen = 0
    snap = d / "hub.snap"
    if snap.exists():
        try:
            state = msgpack.unpackb(snap.read_bytes(), raw=False)
            gen = int(state.get("gen", 0))
        except (ValueError, msgpack.exceptions.ExtraData):
            state = None
    records: list[dict] = []
    wal = d / f"hub.wal.{gen}"
    if wal.exists():
        data = wal.read_bytes()
        off = 0
        while off + _LEN.size <= len(data):
            (n,) = _LEN.unpack_from(data, off)
            if off + _LEN.size + n > len(data):
                break  # torn tail
            try:
                records.append(msgpack.unpackb(
                    data[off + _LEN.size: off + _LEN.size + n], raw=False
                ))
            except ValueError:
                break
            off += _LEN.size + n
    return state, records


def _canonical(rec: dict) -> dict:
    """Replication-stream identity of a record: the leader's stamp minus
    the follower-local replay tag."""
    return {k: v for k, v in rec.items() if k != "rsq"}


def check_cluster_invariants(
    data_dirs: list, *, quorum: int | None = None
) -> dict:
    """Replay every replica's WAL and assert the raft-lite safety
    contract:

    - UNIQUE LEADER PER TERM: promote records across all WALs never name
      two different leaders for the same fencing epoch;
    - NO SEQ GAPS: each replica's record stream is contiguous from its
      snapshot base (``sq``/``rsq`` stamps strictly +1);
    - NO COMMITTED FORKS: any seq held by a majority of replicas (the
      committed prefix) is byte-identical everywhere it appears, and the
      committed seq set is itself contiguous.

    Returns {"promotes": {...}, "committed": [...]} for further checks.
    """
    n = len(data_dirs)
    quorum = quorum or (n // 2 + 1)
    promotes: dict[int, set] = {}
    seq_maps: list[dict[int, dict]] = []
    for d in data_dirs:
        state, records = read_wal(d)
        base = int(state.get("wal_seq", 0)) if state else 0
        seqs: dict[int, dict] = {}
        prev = None
        for rec in records:
            seq = rec.get("rsq", rec.get("sq"))
            assert seq is not None, f"{d}: unstamped WAL record {rec}"
            seq = int(seq)
            assert seq > base, (
                f"{d}: record seq {seq} at or below snapshot base {base}"
            )
            if prev is not None:
                assert seq == prev + 1, (
                    f"{d}: WAL seq gap {prev} -> {seq}"
                )
            prev = seq
            seqs[seq] = _canonical(rec)
            if rec.get("op") == "promote":
                promotes.setdefault(int(rec["epoch"]), set()).add(
                    rec.get("addr")
                )
        seq_maps.append(seqs)
    for epoch, addrs in sorted(promotes.items()):
        named = {a for a in addrs if a is not None}
        assert len(named) <= 1, (
            f"DUAL-LEAD: term {epoch} has promote records from {named}"
        )
    committed = sorted(
        seq
        for seq in {s for m in seq_maps for s in m}
        if sum(1 for m in seq_maps if seq in m) >= quorum
    )
    for seq in committed:
        copies = [m[seq] for m in seq_maps if seq in m]
        assert all(c == copies[0] for c in copies[1:]), (
            f"FORK at committed seq {seq}: {copies}"
        )
    for a, b in zip(committed, committed[1:]):
        assert b == a + 1, f"committed-seq gap {a} -> {b}"
    return {"promotes": promotes, "committed": committed}
