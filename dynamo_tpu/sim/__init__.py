"""Cluster-scale chaos simulation: trace-replay fleet harness.

We cannot rent a million users, but the mocker + time-dilation backbone
(SURVEY §"mocker, time dilation") can fake one: this package composes
REAL control-plane components — the replicated quorum hub
(runtime/hub_replica.py), the KV-aware router (kv_router/), the EPP with
circuit breakers (gateway/epp.py), the migration operator
(frontend/migration.py) and the SLA planner's replica math (planner/) —
with 100s of ``MockEngine``-backed workers (time-dilated via
``speedup_ratio``) driving mooncake-style trace replay
(benchmarks/replay.py), and runs named chaos SCENARIOS through the
existing ``DYN_FAULTS`` / ``transport.partition`` grammar:

    pick_scaling    EPP pick latency vs instance count (the flatness bar)
    leader_kill     SIGKILL the quorum leader mid-commit-storm
    partition       symmetric + one-way partitions during election
    churn           worker kill + rejoin waves under open-loop replay
    breaker_storm   injected epp.breaker failures -> eject -> recovery
    tenant_storm    batch-tenant flood vs the interactive TTFT SLO
    telemetry_overhead   span/metric emission cost vs dilated step time

Each scenario asserts its invariants continuously (no dual-lead per term
via the jepsen-style WAL checker, zero client-visible errors with
migrations > 0 under churn, commit unavailability bounded to the
partition window, interactive TTFT SLO held during storms) and the run
writes a saturation-curve artifact (``SIM_r0x.json``) — the
control-plane analogue of the serving ladder.

Run: ``python -m dynamo_tpu.sim --scenario all --workers 200``.
"""

from dynamo_tpu.sim.harness import SimConfig, run_scenarios, write_artifact
from dynamo_tpu.sim.scenarios import SCENARIOS

__all__ = ["SimConfig", "SCENARIOS", "run_scenarios", "write_artifact"]
