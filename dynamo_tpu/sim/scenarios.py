"""Named chaos scenarios: traffic + faults + continuously-asserted
invariants over real control-plane components.

Every scenario returns a dict with an ``invariants`` map ({name: {pass,
...detail}}); the harness derives the verdict. AssertionError anywhere
(including inside ``check_cluster_invariants``) is a failed invariant.

Scenario ingredients are ALL production code paths: the quorum hub
(runtime/hub_replica.py) with its fencing/commit machinery, the
multi-address failover client (runtime/hub_client.py), the KV-aware
router + EPP breakers (kv_router/, gateway/), the migration operator
(frontend/migration.py), the planner's replica math (planner/core.py),
and the ``DYN_FAULTS`` / ``transport.partition`` grammar
(runtime/faults.py). Only the workers are mocks — time-dilated
``MockEngine``s that honor the same fault sites and deadline contract
as the real engine (mocker/engine.py chaos parity).
"""

from __future__ import annotations

import asyncio
import logging
import random
import tempfile
import time
from pathlib import Path

import aiohttp

from benchmarks.loadgen import pct_ms
from benchmarks.replay import load_trace, replay_trace, synthesize_trace
from dynamo_tpu.gateway.breaker import BreakerConfig
from dynamo_tpu.gateway.epp import EndpointPicker
from dynamo_tpu.kv_router.protocols import RouterConfig
from dynamo_tpu.runtime.faults import FAULTS
from dynamo_tpu.runtime.hub_client import RemoteHub, failover_stats
from dynamo_tpu.sim import cluster as hubctl
from dynamo_tpu.sim.harness import (
    COMP,
    EP,
    NS,
    MockFleet,
    ProcReplicaCluster,
    ReplicaCluster,
    SimConfig,
    migrations_snapshot,
    telemetry_overhead,
)

log = logging.getLogger("dynamo.sim")


def _inv(ok: bool, **detail) -> dict:
    return {"pass": bool(ok), **detail}


def _tmpdir(cfg: SimConfig, tag: str) -> Path:
    """Scenario scratch under ONE run-scoped base dir. run_scenarios
    pins cfg.data_dir for the whole run (and cleans it up on a passing
    run); the mkdtemp branch only fires for direct scenario calls."""
    if not cfg.data_dir:
        cfg.data_dir = tempfile.mkdtemp(prefix="dynamo-sim-")
    d = Path(cfg.data_dir) / tag
    d.mkdir(parents=True, exist_ok=True)
    return d


def _mk_trace(cfg: SimConfig, tag: str, *, requests: int, rate: float,
              osl: int | None = None, groups: int | None = None,
              seed: int | None = None) -> list[dict]:
    path = _tmpdir(cfg, "traces") / f"{tag}.jsonl"
    synthesize_trace(
        str(path), requests=requests, block_size=cfg.block_size,
        groups=groups or max(12, cfg.workers // 8), rate_per_s=rate,
        osl=osl or cfg.osl, seed=cfg.seed if seed is None else seed,
    )
    return load_trace(str(path), cfg.block_size)


# -- pick_scaling ------------------------------------------------------------


async def pick_scaling(cfg: SimConfig) -> dict:
    """EPP pick latency vs instance count: the flatness bar. For each
    fleet size, a fresh mock fleet registers against an in-memory hub,
    the real EndpointPicker serves /pick over HTTP, and we measure the
    full pick path (tokenless token_ids pick: KV score + instance
    resolve + breaker walk) client-side. Steady-state picks must do ZERO
    hub round-trips (hub_scans flat while picks grow) and the latency
    curve must stay flat-ish as the fleet grows to 100s of instances."""
    from dynamo_tpu.gateway.pickline import PickLineClient

    curve = []
    rng = random.Random(cfg.seed)
    for size in cfg.sizes():
        fleet = await MockFleet(cfg, size).start()
        epp = None
        try:
            epp = await EndpointPicker(
                fleet.drt, namespace=NS, target_component=COMP,
                target_endpoint=EP,
                config=RouterConfig(block_size=cfg.block_size),
                host="127.0.0.1", port=0, pick_port=0,
            ).start()
            deadline = time.monotonic() + 20
            while len(epp.kv.scheduler.workers()) < size:
                if time.monotonic() > deadline:
                    raise AssertionError(
                        f"EPP saw {len(epp.kv.scheduler.workers())}/{size} "
                        "workers"
                    )
                await asyncio.sleep(0.05)

            prompts = [
                [rng.randrange(10, 30000) for _ in range(cfg.block_size * 4)]
                for _ in range(32)
            ]
            lats: list[float] = []
            sem = asyncio.Semaphore(cfg.pick_concurrency)
            url = f"http://127.0.0.1:{epp.port}"

            async def one(i: int, sess):
                async with sem:
                    t0 = time.perf_counter()
                    async with sess.post(f"{url}/pick", json={
                        "token_ids": prompts[i % len(prompts)],
                        "request_id": f"pk-{i}",
                    }) as resp:
                        assert resp.status == 200, await resp.text()
                        await resp.json()
                    lats.append(time.perf_counter() - t0)

            async with aiohttp.ClientSession() as sess:
                # warmup fills the pick-path caches (cards + instances)
                for i in range(8):
                    await one(i, sess)
                lats.clear()
                scans0 = epp._cards.scans + epp._instances.scans
                picks0 = epp.kv.picks
                phases0 = dict(epp.kv.pick_phase_totals)
                full_scans0 = epp.kv.scheduler.full_pick_scans
                await asyncio.gather(
                    *(one(i, sess) for i in range(cfg.picks))
                )
                scans1 = epp._cards.scans + epp._instances.scans
            # per-phase decision attribution (hash/overlap/select) over
            # the measured window — the rest of the client-observed pick
            # latency is transport + HTTP plumbing (ROADMAP #7c)
            dp = max(epp.kv.picks - picks0, 1)
            phase_us = {
                k: round(
                    1e6 * (epp.kv.pick_phase_totals[k] - phases0[k]) / dp,
                    2,
                )
                for k in phases0
            }
            # the pickline fast path over the same prompts: persistent
            # connection, pipelined by the same concurrency semaphore
            line = await PickLineClient(
                "127.0.0.1", epp.pick_port
            ).connect()
            line_lats: list[float] = []

            async def one_line(i: int):
                async with sem:
                    t0 = time.perf_counter()
                    r = await line.pick({
                        "token_ids": prompts[i % len(prompts)],
                        "request_id": f"pl-{i}",
                    })
                    assert r["status"] == 200, r
                    line_lats.append(time.perf_counter() - t0)

            await asyncio.gather(*(one_line(i) for i in range(cfg.picks)))
            await line.close()
            curve.append({
                "instances": size,
                "picks": cfg.picks,
                "pick_ms_p50": pct_ms(lats, 0.5),
                "pick_ms_p90": pct_ms(lats, 0.9),
                "pick_ms_p99": pct_ms(lats, 0.99),
                "pickline_ms_p50": pct_ms(line_lats, 0.5),
                "pickline_ms_p99": pct_ms(line_lats, 0.99),
                "decision_phase_us": phase_us,
                "steady_state_hub_scans": scans1 - scans0,
                "full_fleet_scans": (
                    epp.kv.scheduler.full_pick_scans - full_scans0
                ),
            })
        finally:
            if epp is not None:
                await epp.close()
            await fleet.close()
    lo, hi = curve[0], curve[-1]
    flat_ratio = hi["pick_ms_p50"] / max(lo["pick_ms_p50"], 1.0)
    return {
        "curve": curve,
        "invariants": {
            # the flatness bar: growing the fleet 4x must not grow the
            # median pick more than ~3x (sub-linear; floor 1 ms so tiny
            # absolute numbers don't flap the ratio)
            "pick_latency_flat": _inv(
                flat_ratio <= 3.0, ratio=round(flat_ratio, 2),
                p50_small_ms=lo["pick_ms_p50"], p50_large_ms=hi["pick_ms_p50"],
            ),
            "zero_hub_roundtrips_steady_state": _inv(
                all(c["steady_state_hub_scans"] == 0 for c in curve),
                scans=[c["steady_state_hub_scans"] for c in curve],
            ),
            # the incremental selector's contract at fleet scale: no
            # pick ever falls back to an O(instances) full-fleet scan
            "zero_full_fleet_scans": _inv(
                all(c["full_fleet_scans"] == 0 for c in curve),
                scans=[c["full_fleet_scans"] for c in curve],
            ),
            # the pickline transport must beat the aiohttp route it
            # displaces at the largest fleet
            "pickline_beats_http": _inv(
                hi["pickline_ms_p50"] <= hi["pick_ms_p50"],
                pickline_ms=hi["pickline_ms_p50"],
                http_ms=hi["pick_ms_p50"],
            ),
        },
    }


# -- leader_kill -------------------------------------------------------------


async def leader_kill(cfg: SimConfig) -> dict:
    """SIGKILL the quorum leader mid-commit-storm (real subprocesses,
    real kill -9). Writers hammer majority-committed puts through the
    multi-address failover client; the kill lands a third of the way in.
    Asserts: every ACKED write survives into the recovered cluster, the
    unavailability window is bounded by election + reconnect scale, the
    post-kill commit rate recovers, and the WAL invariant checker holds
    across all three data dirs (including the corpse's)."""
    base = _tmpdir(cfg, "leader_kill")
    cl = await ProcReplicaCluster(cfg, base).start()
    client = None
    acked: list[tuple[float, str, float]] = []  # (t_done, key, latency)
    failed: list[str] = []
    stop = asyncio.Event()
    writers: list[asyncio.Future] = []
    redirects0 = failover_stats()
    try:
        leader = await cl.find_leader()
        client = await RemoteHub.connect(
            ",".join(cl.addrs), reconnect_window_s=20.0
        )
        t_start = time.monotonic()

        async def writer(w: int):
            i = 0
            while not stop.is_set():
                key = f"storm/{w}/{i}"
                t0 = time.monotonic()
                try:
                    await client.put(key, i)
                    acked.append((time.monotonic(), key, time.monotonic() - t0))
                except (ConnectionError, RuntimeError) as e:
                    failed.append(f"{key}: {e}")
                    # a closed/unreachable client raises without ever
                    # suspending — without this pause a failure path
                    # that forgot us would busy-starve the event loop
                    await asyncio.sleep(0.01)
                i += 1

        writers = [
            asyncio.ensure_future(writer(w))
            for w in range(cfg.storm_writers)
        ]
        kill_at = cfg.storm_duration_s * 0.35
        await asyncio.sleep(kill_at)
        t_kill = time.monotonic()
        cl.sigkill(leader)
        log.warning("sim: SIGKILLed hub leader %s mid-storm", leader)
        await asyncio.sleep(cfg.storm_duration_s - kill_at)
        stop.set()
        await asyncio.gather(*writers, return_exceptions=True)

        new_leader = await cl.find_leader()
        assert new_leader != leader, "dead leader still answers as leader"
        await client.put("post/recovery", 1)

        # durability of the acked prefix: every write the client saw
        # acked (majority-committed by contract) must be readable now
        sample = acked if len(acked) <= 400 else random.Random(
            cfg.seed
        ).sample(acked, 400)
        lost = []
        for _t, key, _l in sample:
            i = int(key.rsplit("/", 1)[1])
            if await client.get(key) != i:
                lost.append(key)

        # throughput timeline around the kill
        pre = [t for t, _k, _l in acked if t < t_kill]
        post = [t for t, _k, _l in acked if t >= t_kill]
        pre_rate = len(pre) / max(t_kill - t_start, 1e-9)
        post_win = max(acked[-1][0] - t_kill, 1e-9) if acked else 1.0
        post_rate = len(post) / post_win
        # unavailability: the longest gap between consecutive acks that
        # spans the kill moment
        times = sorted([t for t, _k, _l in acked] + [t_kill])
        outage = max(
            (b - a for a, b in zip(times, times[1:])), default=0.0
        )
        outage_bound = cfg.lease_s * 12 + cfg.commit_timeout_s + 2.0
        redirects = {
            k: v - redirects0.get(k, 0.0)
            for k, v in failover_stats().items()
        }
    finally:
        # stop the storm FIRST: failure paths must not leave writer
        # tasks looping against a closed client for the rest of the run
        stop.set()
        await asyncio.gather(*writers, return_exceptions=True)
        if client is not None:
            await client.close()
        cl.terminate_all()
    inv_detail = hubctl.check_cluster_invariants(cl.data_dirs())
    return {
        "commits_acked": len(acked),
        "commit_rate_pre_kill": round(pre_rate, 1),
        "commit_rate_post_kill": round(post_rate, 1),
        "commit_ms_p50": pct_ms([x for _t, _k, x in acked], 0.5),
        "commit_ms_p99": pct_ms([x for _t, _k, x in acked], 0.99),
        "outage_s": round(outage, 3),
        "committed_records": len(inv_detail["committed"]),
        "client_failover": redirects,
        "invariants": {
            "cluster_invariants": _inv(True),  # checker above raised if not
            "no_acked_write_lost": _inv(
                not lost, lost=lost[:5], sampled=len(sample)
            ),
            "outage_bounded": _inv(
                outage <= outage_bound,
                outage_s=round(outage, 3), bound_s=outage_bound,
            ),
            "throughput_recovered": _inv(
                post_rate >= 0.4 * pre_rate,
                pre=round(pre_rate, 1), post=round(post_rate, 1),
            ),
            "write_failures_zero": _inv(
                not failed, failures=failed[:5]
            ),
        },
    }


# -- partition ---------------------------------------------------------------


async def partition(cfg: SimConfig) -> dict:
    """Partition matrix during live traffic: a symmetric partition
    isolates the leader mid-write-storm (the majority side must elect
    and keep committing; no_quorum stalls bounded to the window), then a
    one-way cut that must NOT depose the leader. Invariants via the
    jepsen-style WAL checker: no dual-lead per term, no committed fork,
    no seq gap — and every acked write survives the heals."""
    base = _tmpdir(cfg, "partition")
    cl = await ReplicaCluster(cfg, base).start()
    client = None
    acked: list[tuple[float, str, float]] = []
    failed: list[str] = []
    stop = asyncio.Event()
    wt: asyncio.Future | None = None
    windows: list[tuple[float, float]] = []  # (start, end) of chaos
    redirects0 = failover_stats()
    try:
        leader = await cl.wait_leader()
        client = await RemoteHub.connect(
            ",".join(cl.addrs), reconnect_window_s=20.0
        )

        async def writer():
            i = 0
            while not stop.is_set():
                key = f"part/{i}"
                t0 = time.monotonic()
                try:
                    await client.put(key, i)
                    acked.append(
                        (time.monotonic(), key, time.monotonic() - t0)
                    )
                except (ConnectionError, RuntimeError) as e:
                    failed.append(f"{key}: {e}")
                i += 1
                await asyncio.sleep(0.01)

        wt = asyncio.ensure_future(writer())
        await asyncio.sleep(0.5)

        # round 1: symmetric partition cutting the leader off
        t0 = time.monotonic()
        FAULTS.configure(
            hubctl.isolate_spec(leader.advertise, cl.addrs), seed=cfg.seed
        )
        try:
            survivors = [r for r in cl.reps if r is not leader]
            deadline = time.monotonic() + 15
            while not any(r.hub.role == "leader" for r in survivors):
                assert time.monotonic() < deadline, (
                    "majority side failed to elect within 15s"
                )
                await asyncio.sleep(0.05)
            await asyncio.sleep(cfg.partition_window_s)
        finally:
            FAULTS.clear()
            windows.append((t0, time.monotonic()))
        await cl.wait_leader()

        # round 2: one-way cut (leader -> follower) must not depose
        await asyncio.sleep(0.5)
        new_leader = await cl.wait_leader()
        follower = next(r for r in cl.reps if r is not new_leader)
        t0 = time.monotonic()
        FAULTS.configure(hubctl.partition_spec(
            (new_leader.advertise, follower.advertise), one_way=True,
        ), seed=cfg.seed + 1)
        try:
            await asyncio.sleep(cfg.partition_window_s)
            leaders = [r for r in cl.reps if r.hub.role == "leader"]
            one_way_stable = leaders == [new_leader]
        finally:
            FAULTS.clear()
            windows.append((t0, time.monotonic()))

        await cl.wait_leader()
        await asyncio.sleep(0.5)
        stop.set()
        await asyncio.gather(wt, return_exceptions=True)

        # acked durability after both heals
        sample = acked if len(acked) <= 300 else random.Random(
            cfg.seed
        ).sample(acked, 300)
        lost = [
            key for _t, key, _l in sample
            if await client.get(key) != int(key.rsplit("/", 1)[1])
        ]
        # no_quorum stalls bounded to the chaos windows: outside them
        # (with slack for the failover tail) every commit is fast
        slack = cfg.lease_s * 8 + cfg.commit_timeout_s
        stalled_outside = [
            key for t, key, lat in acked
            if lat > 1.0 and not any(
                s <= t <= e + slack for s, e in windows
            )
        ]
    finally:
        FAULTS.clear()
        # failure paths included: the writer must not outlive the
        # scenario and spin against a closed client
        stop.set()
        if wt is not None:
            await asyncio.gather(wt, return_exceptions=True)
        if client is not None:
            await client.close()
        dirs = cl.data_dirs()
        await cl.stop_all()
    inv_detail = hubctl.check_cluster_invariants(dirs)
    return {
        "commits_acked": len(acked),
        "committed_records": len(inv_detail["committed"]),
        "terms_seen": sorted(inv_detail["promotes"]),
        "chaos_windows": [
            [round(e - s, 2) for s, e in [w]][0] for w in windows
        ],
        # delta over the scenario, not process-lifetime absolutes — the
        # redirect counters are process-global and earlier scenarios
        # (leader_kill in an --scenario all run) already moved them
        "client_failover": {
            k: v - redirects0.get(k, 0.0)
            for k, v in failover_stats().items()
        },
        "invariants": {
            "cluster_invariants": _inv(True),
            "no_acked_write_lost": _inv(
                not lost, lost=lost[:5], sampled=len(sample)
            ),
            "one_way_keeps_leader": _inv(one_way_stable),
            "stalls_bounded_to_partition": _inv(
                not stalled_outside, stalled=stalled_outside[:5]
            ),
            "write_failures_zero": _inv(not failed, failures=failed[:5]),
        },
    }


# -- churn -------------------------------------------------------------------


async def churn(cfg: SimConfig) -> dict:
    """Worker kill + rejoin waves under open-loop trace replay, through
    the REAL client path (KV-aware routing + migration operator). The
    acceptance bar from the soak tier, at fleet scale: ZERO
    client-visible errors with migrations > 0 — every stream cut by a
    kill wave must be transparently re-driven. The rejoin waves are
    deliberate thundering herds (all replacements register at once).
    Feeds the observed interval into the real SLA planner's replica math
    and records its recommendation."""
    fleet = await MockFleet(cfg, cfg.workers).start()
    mig0 = migrations_snapshot()
    killed = rejoined = 0
    try:
        engine = await fleet.client_path(migration=True)
        trace = _mk_trace(
            cfg, "churn", requests=cfg.trace_n(), rate=cfg.trace_rate()
        )
        replay_window = trace[-1]["t_ms"] / 1000.0 if trace else 1.0

        async def chaos():
            nonlocal killed, rejoined
            waves = max(cfg.churn_waves, 1)
            t_begin = time.monotonic()
            for i in range(waves):
                # absolute schedule: wave i lands at (i+0.5)/waves of
                # the replay window regardless of how long earlier
                # kills/rejoins took (cumulative sleeps would push late
                # waves past the end of the replay onto an idle fleet)
                target = t_begin + replay_window * (i + 0.5) / waves
                await asyncio.sleep(max(target - time.monotonic(), 0.0))
                k = max(1, int(len(fleet.alive_workers())
                               * cfg.churn_kill_frac))
                victims = await fleet.kill_wave(k)
                killed += len(victims)
                log.warning(
                    "sim churn wave %d: killed %d workers (%d alive)",
                    i, len(victims), len(fleet.alive_workers()),
                )
                await asyncio.sleep(0.2)
                await fleet.rejoin_wave(len(victims))
                rejoined += len(victims)

        chaos_task = asyncio.ensure_future(chaos())
        res = await replay_trace(
            engine.generate, trace, id_prefix="churn"
        )
        await chaos_task
        migrations = migrations_snapshot() - mig0
        summary = res.summary()
        itls = res.itls()
        incomplete = [
            r for r in res.results
            if r["ttft"] is None and r["error"] is None
        ]
    finally:
        await fleet.close()

    # the real planner's replica math over the observed interval: would
    # the SLA planner have scaled this fleet, given what the storm did?
    from dynamo_tpu.planner.core import Metrics, PlannerConfig, SlaPlanner
    from dynamo_tpu.planner.interpolation import (
        DecodeInterpolator,
        PrefillInterpolator,
        synthetic_profile,
    )

    prof = synthetic_profile()
    planner = SlaPlanner(
        PlannerConfig(
            ttft_sla_s=0.5, itl_sla_s=0.05,
            adjustment_interval_s=max(res.elapsed_s, 1e-3),
            predictor="constant", no_correction=True,
            max_chip_budget=cfg.workers * 2,
        ),
        PrefillInterpolator(prof), DecodeInterpolator(prof),
    )
    isl_avg = (
        sum(len(r["token_ids"]) for r in trace) / max(len(trace), 1)
    )
    planner.ingest(Metrics(
        ttft=(summary["ttft_ms_p50"] or 0.0) / 1e3,
        itl=(pct_ms(itls, 0.5) or 0.0) / 1e3,
        num_req=float(len(trace)), isl=isl_avg, osl=float(cfg.osl),
        request_duration=sum(
            r["duration"] for r in res.results
        ) / max(len(res.results), 1),
    ))
    n_p, n_d = planner.compute_replicas(
        float(len(trace)), isl_avg, float(cfg.osl)
    )

    return {
        **summary,
        # offered = the trace's open-loop schedule; achieved = what the
        # single replay process actually sustained (the gap is the
        # one-router throughput cap — see ROADMAP)
        "offered_req_per_s": round(cfg.trace_rate(), 1),
        "dilated_offered_req_per_s": round(
            cfg.trace_rate() * cfg.speedup, 1
        ),
        "dilated_req_per_s": round(summary["req_per_s"] * cfg.speedup, 1),
        "workers": cfg.workers,
        "killed": killed,
        "rejoined": rejoined,
        "migrations": migrations,
        "itl_ms_p50": pct_ms(itls, 0.5),
        "planner_recommendation": {"prefill": n_p, "decode": n_d},
        "invariants": {
            "zero_client_errors": _inv(
                not res.errors, errors=res.errors[:5]
            ),
            "migrations_gt_zero": _inv(
                migrations > 0, migrations=migrations
            ),
            "all_requests_completed": _inv(
                not incomplete, incomplete=len(incomplete)
            ),
            "workers_actually_churned": _inv(killed > 0, killed=killed),
        },
    }


# -- breaker_storm -----------------------------------------------------------


async def breaker_storm(cfg: SimConfig) -> dict:
    """Injected ``epp.breaker`` failures brown out picked instances:
    breakers must OPEN (instances ejected from picks) while /pick stays
    100% available (fail-open contract), then — after the fault clears
    and /report feeds recoveries — every breaker must CLOSE again."""
    size = min(cfg.workers, 16)
    fleet = await MockFleet(cfg, size).start()
    epp = None
    storm_statuses: list[int] = []
    try:
        epp = await EndpointPicker(
            fleet.drt, namespace=NS, target_component=COMP,
            target_endpoint=EP,
            config=RouterConfig(block_size=cfg.block_size),
            host="127.0.0.1", port=0,
            breaker_config=BreakerConfig(
                window=16, min_samples=4, failure_threshold=0.5,
                open_cooldown_s=0.2, half_open_probes=2, close_after=2,
                probe_timeout_s=5.0,
            ),
        ).start()
        deadline = time.monotonic() + 20
        while len(epp.kv.scheduler.workers()) < size:
            assert time.monotonic() < deadline, "EPP never saw the fleet"
            await asyncio.sleep(0.05)
        rng = random.Random(cfg.seed)
        url = f"http://127.0.0.1:{epp.port}"

        async def one_pick(sess, i: int) -> int:
            async with sess.post(f"{url}/pick", json={
                "token_ids": [
                    rng.randrange(10, 30000)
                    for _ in range(cfg.block_size * 2)
                ],
                "request_id": f"bs-{i}",
            }) as resp:
                await resp.read()
                return resp.status

        async with aiohttp.ClientSession() as sess:
            # storm: every pick records an injected failure outcome
            # against the chosen instance (the epp.breaker fault site)
            FAULTS.configure("epp.breaker:error@1x200", seed=cfg.seed)
            try:
                for i in range(150):
                    storm_statuses.append(await one_pick(sess, i))
                    if len(epp.breakers.ejected()) >= max(size // 3, 1):
                        break
            finally:
                FAULTS.clear()
            ejected_peak = len(epp.breakers.ejected())

            # recovery: keep picking (half-open probes re-admit) and
            # report success for everything still tracked as ejected
            deadline = time.monotonic() + 15
            while epp.breakers.ejected() and time.monotonic() < deadline:
                storm_statuses.append(await one_pick(sess, 10_000))
                for iid in list(epp.breakers.ejected()):
                    async with sess.post(f"{url}/report", json={
                        "worker_id": f"{iid:x}", "ok": True,
                        "latency_ms": 1.0,
                    }) as resp:
                        await resp.read()
                await asyncio.sleep(0.05)
            ejected_final = len(epp.breakers.ejected())
    finally:
        FAULTS.clear()
        if epp is not None:
            await epp.close()
        await fleet.close()
    return {
        "fleet": size,
        "picks": len(storm_statuses),
        "ejected_peak": ejected_peak,
        "ejected_after_recovery": ejected_final,
        "invariants": {
            "breakers_opened": _inv(
                ejected_peak >= 1, ejected_peak=ejected_peak
            ),
            "breakers_recovered": _inv(
                ejected_final == 0, still_open=ejected_final
            ),
            "pick_availability_100": _inv(
                all(s == 200 for s in storm_statuses),
                non_200=[s for s in storm_statuses if s != 200][:5],
            ),
        },
    }


# -- tenant_storm ------------------------------------------------------------


async def tenant_storm(cfg: SimConfig) -> dict:
    """A batch tenant floods the fleet while an interactive tenant keeps
    its dribble of traffic: the mock engines' class-priority admission
    (the parity mirror of engine/tenancy.py's lanes) must hold the
    interactive TTFT SLO through the storm. Baseline first (interactive
    alone), then the same interactive trace under the batch flood.

    Runs on a slot-constrained sub-fleet at modest dilation so the storm
    saturates WORKER SLOTS (the thing priority admission arbitrates)
    rather than the harness event loop — at full fleet scale a single
    replay process saturates on routing CPU first, which is a real
    finding (see ROADMAP) but a different one."""
    from dataclasses import replace

    size = min(cfg.workers, 16)
    storm_cfg = replace(
        cfg, workers=size, speedup=4.0, max_batch_size=2,
        trace_rate_per_s=size * 16.0,
    )
    fleet = await MockFleet(storm_cfg, size).start()
    try:
        engine = await fleet.client_path(migration=True)
        n_int = max(cfg.trace_n() // 4, 24)
        int_rate = storm_cfg.trace_rate() / 16.0
        int_trace = _mk_trace(
            storm_cfg, "tenant_int", requests=n_int, rate=int_rate,
            seed=cfg.seed,
        )
        # storm length scales with the SUB-fleet (≈1.5s of flood at the
        # storm rate), not the global worker count — a small --workers
        # run must still saturate the slots it has, or the falsifiable
        # batch_actually_stormed invariant correctly calls it out
        n_batch = max(cfg.trace_n(), size * 25)
        batch_trace = _mk_trace(
            storm_cfg, "tenant_batch", requests=n_batch,
            rate=storm_cfg.trace_rate(), osl=cfg.osl * 4,
            seed=cfg.seed + 7,
        )
        # the contended phase replays a DIFFERENT interactive trace
        # (fresh seed, same shape): re-running the baseline's exact
        # tokens would ride the prefix caches the baseline just warmed
        # and mask real contention in the SLO comparison
        int_trace_cold = _mk_trace(
            storm_cfg, "tenant_int_cold", requests=n_int, rate=int_rate,
            seed=cfg.seed + 13,
        )
        hdr_int = {"x-dyn-tenant": "live", "x-dyn-priority": "interactive"}
        hdr_batch = {"x-dyn-tenant": "bulk", "x-dyn-priority": "batch"}

        base = await replay_trace(
            engine.generate, int_trace, headers=hdr_int, id_prefix="tb",
        )
        base_sum = base.summary()

        contended, flood = await asyncio.gather(
            replay_trace(
                engine.generate, int_trace_cold, headers=hdr_int,
                id_prefix="ti",
            ),
            replay_trace(
                engine.generate, batch_trace, headers=hdr_batch,
                id_prefix="tf",
            ),
        )
        cont_sum = contended.summary()
        flood_sum = flood.summary()

        # cluster-level tenant steering: replay decision-only picks for
        # a prompt with TOTAL prefix affinity (one warm radix group, so
        # overlap-argmax wants exactly one worker). Untagged picks must
        # pin that worker (the falsifiable control — steering never
        # touches the untenanted path); the same picks tagged as a hot
        # tenant must spread across several workers.
        kv = fleet.kv_router
        hot_toks = int_trace[0]["token_ids"]
        pinned: set[int] = set()
        steered: set[int] = set()
        for i in range(64):
            wid, _ = kv.find_best_match(f"pin-{i}", hot_toks)
            kv.free(f"pin-{i}")
            pinned.add(wid)
        for i in range(64):
            wid, _ = kv.find_best_match(
                f"hot-{i}", hot_toks, tenant="hot-tenant"
            )
            kv.free(f"hot-{i}")
            steered.add(wid)
    finally:
        await fleet.close()
    slo_s = max(
        cfg.slo_ttft_factor * (base_sum["ttft_ms_p50"] or 0.0) / 1e3,
        cfg.slo_ttft_floor_s,
    )
    p99_s = (cont_sum["ttft_ms_p99"] or float("inf")) / 1e3
    return {
        "fleet": size,
        "slots_per_worker": storm_cfg.max_batch_size,
        "interactive_baseline": base_sum,
        "interactive_contended": cont_sum,
        "batch_flood": flood_sum,
        "slo_ttft_ms": round(slo_s * 1e3, 1),
        "invariants": {
            "interactive_ttft_slo_held": _inv(
                p99_s <= slo_s,
                p99_ms=cont_sum["ttft_ms_p99"],
                slo_ms=round(slo_s * 1e3, 1),
            ),
            "interactive_zero_errors": _inv(
                not contended.errors, errors=contended.errors[:5]
            ),
            # falsifiable saturation check: if the flood never actually
            # contended for slots (e.g. every request bounced), batch
            # TTFT would sit at the uncontended baseline and the SLO
            # invariant above would be passing against an idle fleet
            "batch_actually_stormed": _inv(
                not flood.errors
                and (flood_sum["ttft_ms_p50"] or 0.0)
                >= 2.0 * (base_sum["ttft_ms_p50"] or float("inf")),
                batch_ttft_ms_p50=flood_sum["ttft_ms_p50"],
                baseline_ttft_ms_p50=base_sum["ttft_ms_p50"],
                flood_errors=len(flood.errors),
            ),
            # cluster-level steering: the hot tenant spreads across
            # workers while untagged picks (the control) stay pinned to
            # the affinity winner
            "hot_tenant_spreads": _inv(
                len(pinned) == 1 and len(steered) >= 2,
                pinned_workers=len(pinned),
                steered_workers=len(steered),
            ),
        },
    }


# -- telemetry overhead ------------------------------------------------------


async def telemetry(cfg: SimConfig) -> dict:
    """Span/metric emission overhead as a fraction of step time — the
    'does observability self-DoS at fleet scale' check (ROADMAP #7 named
    PR 10's telemetry volume as an open question)."""
    out = telemetry_overhead(cfg)
    return {
        **out,
        "invariants": {
            # a real (undilated) engine step must spend <5% of its time
            # on span+metric emission
            "emission_under_5pct_of_real_step": _inv(
                out["emission_frac_of_real_step"] < 0.05,
                frac=out["emission_frac_of_real_step"],
            ),
        },
    }


# -- autoscale ---------------------------------------------------------------


def _autoscale_config(cfg: SimConfig, *, lead_ticks: int) -> "AutoscalerConfig":
    from dynamo_tpu.autoscaler import AutoscalerConfig

    tick = cfg.autoscale_tick_s
    return AutoscalerConfig(
        slots_per_worker=cfg.autoscale_slots,
        target_occupancy=0.75,
        min_workers=cfg.autoscale_start_workers,
        max_workers=cfg.autoscale_max_workers,
        scale_up_at=0.85,
        scale_down_at=0.5,
        up_cooldown_s=1.5 * tick,
        down_cooldown_s=8.0 * tick,
        max_step_up=4,
        max_step_down=2,
        predict_ahead_ticks=lead_ticks,
        predictor="holt",
        tick_interval_s=tick,
    )


async def _autoscale_pass(
    cfg: SimConfig, trace: list[dict], *, lead_ticks: int, tag: str
) -> dict:
    """One full closed loop over the wave trace: small slow fleet, live
    hub-fed telemetry, the real control law, SimBackend actuation.
    Returns the replay summary + per-tick capacity accounting."""
    import dataclasses

    from dynamo_tpu.autoscaler import (
        AutoscaleController,
        FleetTelemetry,
        SimBackend,
    )

    fcfg = dataclasses.replace(
        cfg,
        workers=cfg.autoscale_start_workers,
        speedup=cfg.autoscale_speedup,
        max_batch_size=cfg.autoscale_slots,
    )
    tick = cfg.autoscale_tick_s
    fleet = await MockFleet(fcfg, fcfg.workers).start()
    backend = SimBackend(fleet)
    tel = FleetTelemetry(
        fleet.hub, f"{NS}/{COMP}", stale_after_s=max(1.0, 4 * tick)
    ).start()
    ctrl = AutoscaleController(
        _autoscale_config(cfg, lead_ticks=lead_ticks), tel, backend,
        initial_workers=cfg.autoscale_start_workers,
    )
    samples: list[tuple[float, int]] = []  # (demand, alive workers)
    stop = asyncio.Event()

    async def drive():
        while not stop.is_set():
            await ctrl.tick()
            samples.append(
                (tel.signal().demand, len(fleet.alive_workers()))
            )
            await asyncio.sleep(tick)

    try:
        engine = await fleet.client_path(migration=True)
        mig0 = migrations_snapshot()
        driver = asyncio.ensure_future(drive())
        res = await replay_trace(engine.generate, trace, id_prefix=tag)
        # tail: keep the loop ticking past the trough so the down-
        # cooldown expires and scale-down actually happens in-scenario
        await asyncio.sleep(12 * tick)
        stop.set()
        await driver
        migrations = migrations_snapshot() - mig0
    finally:
        await ctrl.close()
        await tel.close()
        await fleet.close()

    slots = cfg.autoscale_slots
    deficit = sum(
        max(0.0, d - w * slots) * tick for d, w in samples
    )
    peak_demand = max((d for d, _ in samples), default=0.0)
    report = ctrl.report()
    return {
        **res.summary(),
        "migrations": migrations,
        "peak_demand": round(peak_demand, 1),
        "deficit_area": round(deficit, 2),
        "max_workers_seen": max((w for _, w in samples), default=0),
        "final_workers": report["final"]["workers"],
        "spawned": backend.spawned,
        "drained": backend.drained,
        "errors_detail": res.errors[:5],
        "autoscaler": report,
    }


async def autoscale(cfg: SimConfig) -> dict:
    """The closed-loop SLA autoscaler under a diurnal wave + 10x flash
    spike, actuated in the live sim fleet (SimBackend spawn/drain over
    the real runtime). Acceptance (ISSUE 17): interactive TTFT p99
    within SLO on the predictive pass, ZERO client-visible errors while
    replicas scale down through the drain contract, plans converge
    within bounded ticks, over-provisioning bounded, and the predictive
    pre-scaler measurably beats the reactive baseline on capacity
    deficit (the queue the fleet was short, integrated over time)."""
    import math as _math

    wave_path = _tmpdir(cfg, "autoscale") / "wave.jsonl"
    from benchmarks.replay import synthesize_wave_trace

    synthesize_wave_trace(
        str(wave_path),
        duration_s=cfg.autoscale_duration_s,
        base_rate=cfg.autoscale_base_rate,
        peak_rate=cfg.autoscale_peak_rate,
        spike_rate=cfg.autoscale_spike_factor * cfg.autoscale_base_rate,
        block_size=cfg.block_size,
        osl=cfg.autoscale_osl,
        seed=cfg.seed,
    )
    trace = load_trace(str(wave_path), cfg.block_size)

    predictive = await _autoscale_pass(
        cfg, trace, lead_ticks=cfg.autoscale_lead_ticks, tag="as-pred"
    )
    reactive = None
    if cfg.autoscale_compare:
        reactive = await _autoscale_pass(
            cfg, trace, lead_ticks=0, tag="as-react"
        )

    acfg = _autoscale_config(cfg, lead_ticks=cfg.autoscale_lead_ticks)
    needed_peak = _math.ceil(
        predictive["peak_demand"]
        / (cfg.autoscale_slots * acfg.target_occupancy)
    )
    slo_ms = cfg.autoscale_slo_ttft_s * 1e3
    invariants = {
        "ttft_slo_held": _inv(
            (predictive["ttft_ms_p99"] or 0.0) <= slo_ms,
            ttft_ms_p99=predictive["ttft_ms_p99"], slo_ms=slo_ms,
        ),
        "zero_client_errors_during_scaling": _inv(
            predictive["errors"] == 0 and predictive["drained"] > 0,
            errors=predictive["errors_detail"],
            drained=predictive["drained"],
        ),
        "fleet_actually_scaled": _inv(
            predictive["spawned"] > 0 and predictive["drained"] > 0,
            spawned=predictive["spawned"], drained=predictive["drained"],
        ),
        "overprovisioning_bounded": _inv(
            predictive["max_workers_seen"]
            <= min(needed_peak + acfg.max_step_up, acfg.max_workers),
            max_workers_seen=predictive["max_workers_seen"],
            needed_at_peak=needed_peak,
        ),
        "convergence_bounded": _inv(
            predictive["autoscaler"]["converge_ticks_max"] <= 3
            and not predictive["autoscaler"]["unconverged"],
            converge_ticks_max=(
                predictive["autoscaler"]["converge_ticks_max"]
            ),
        ),
    }
    if reactive is not None:
        # the margin: predictive's capacity deficit must be at most 70%
        # of reactive's — unless predictive's own deficit is already
        # below the control loop's resolution (one bounded step of
        # capacity held for the pre-scale horizon). On a calm host the
        # reactive pass can actuate fast enough to incur ~zero deficit;
        # demanding a 30% win over noise turns the gate into a coin
        # flip, while a predictive deficit under the noise floor means
        # pre-scaling delivered everything the spike could ask of it.
        noise_floor = (
            acfg.max_step_up * cfg.autoscale_tick_s
            * max(cfg.autoscale_lead_ticks, 1)
        )
        invariants["predictive_beats_reactive"] = _inv(
            predictive["deficit_area"]
            <= max(0.7 * reactive["deficit_area"], noise_floor),
            predictive_deficit=predictive["deficit_area"],
            reactive_deficit=reactive["deficit_area"],
            noise_floor=round(noise_floor, 2),
        )
    return {
        "trace_requests": len(trace),
        "predictive": predictive,
        "reactive": reactive,
        "invariants": invariants,
    }


# -- gray_failure ------------------------------------------------------------


async def gray_failure(cfg: SimConfig) -> dict:
    """One worker degrades GRAY — 10x step time via a sticky per-instance
    ``engine.step:delay`` fault, still answering everything — and the
    self-healing plane must catch it without any absolute threshold:
    peer-relative degradation scoring over the step-time fingerprints in
    ForwardPassMetrics flags it, quarantine soft-withdraws it (card stays
    in the hub, flagged), routers exclude it fail-open, in-flight streams
    migrate off through the existing re-drive path, the autoscaler counts
    it as zero capacity and spawns a replacement, and healing (the fault
    cleared + clean fingerprints) re-admits it and unwinds the
    replacement. Acceptance (ISSUE 18): quarantined within the dilated
    detection budget, ZERO client-visible errors end to end, TTFT p99
    back under the healthy baseline x1.5 after quarantine, desired
    workers +1 while quarantined."""
    import dataclasses

    from dynamo_tpu.autoscaler import (
        AutoscaleController,
        AutoscalerConfig,
        FleetTelemetry,
        SimBackend,
    )
    from dynamo_tpu.runtime.context import Context
    from dynamo_tpu.runtime.health import DegradationDetector, is_quarantined

    n = cfg.gray_workers
    fcfg = dataclasses.replace(
        cfg,
        workers=n,
        speedup=cfg.gray_speedup,
        max_batch_size=4,
        metrics_interval_s=0.05,
    )
    fleet = await MockFleet(fcfg, n).start()
    tel = FleetTelemetry(
        fleet.hub, f"{NS}/{COMP}", stale_after_s=2.0
    ).start()
    backend = SimBackend(fleet)
    ctrl = AutoscaleController(
        AutoscalerConfig(
            # demand never drives scaling here: capacity per worker is
            # set far above the offered load, so the ONLY mover is the
            # quarantine replacement overlay
            slots_per_worker=64,
            min_workers=n, max_workers=n + 2,
            up_cooldown_s=0.05, down_cooldown_s=60.0,
            tick_interval_s=0.05, predict_ahead_ticks=0,
        ),
        tel, backend, initial_workers=n,
    )
    detector = DegradationDetector(tolerance=3.0, min_peers=3)
    mig0 = migrations_snapshot()
    victim = fleet.workers[1]
    quarantined_at: list[float] = []
    readmitted: list[float] = []
    desired_peak = n
    stop = asyncio.Event()

    def _by_wid(wid: int) -> "object | None":
        for w in fleet.workers:
            if w.wid == wid:
                return w
        return None

    async def watchdog():
        """The fleet-side gray-failure plane: score fingerprints, flip
        cards. Same observe path the EPP uses (scheduler worker states
        fed by the kv_metrics subscription)."""
        nonlocal desired_peak
        while not stop.is_set():
            if fleet.kv_router is not None:
                for ws in fleet.kv_router.scheduler.workers():
                    detector.observe(ws.worker_id, ws.metrics.step_time_ms)
            scores = detector.scores()
            for wid, s in scores.items():
                w = _by_wid(wid)
                if w is None or not w.alive:
                    continue
                if s >= detector.tolerance and not w.quarantined:
                    await fleet.quarantine_worker(w, "degraded")
                    quarantined_at.append(time.monotonic())
                    tel.set_quarantined({wid})
                    log.warning(
                        "sim gray: worker %x quarantined (score %.1f)",
                        wid, s,
                    )
                elif w.quarantined and s < detector.tolerance:
                    await fleet.readmit_worker(w)
                    readmitted.append(time.monotonic())
                    tel.set_quarantined(set())
                    log.warning(
                        "sim gray: worker %x re-admitted (score %.1f)",
                        wid, s,
                    )
            await ctrl.tick()
            desired_peak = max(desired_peak, ctrl.engine.current()[0])
            await asyncio.sleep(0.02)

    async def probe_victim():
        """Keep the victim decoding so its fingerprint reflects reality
        (a gray worker is degraded, not idle)."""
        k = 0
        while not stop.is_set():
            k += 1
            ctx = Context(request_id=f"gray-probe-{k}")
            try:
                async for _ in victim.engine.generate(
                    {"token_ids": [7, 8, 9],
                     "stop_conditions": {"max_tokens": 4,
                                         "ignore_eos": True}},
                    ctx,
                ):
                    pass
            except Exception as exc:  # noqa: BLE001 — probe loss not the SUT
                log.debug("sim gray: probe request failed "
                          "(expected while degraded): %s", exc)
            await asyncio.sleep(0.02)

    try:
        engine = await fleet.client_path(migration=True)
        rate, reqs, osl = cfg.gray_rate_per_s, cfg.gray_requests, cfg.gray_osl

        # phase A: healthy baseline
        base = (await replay_trace(
            engine.generate,
            _mk_trace(cfg, "gray-base", requests=reqs, rate=rate, osl=osl,
                      groups=n, seed=cfg.seed),
            id_prefix="gray-base",
        )).summary()

        driver = asyncio.ensure_future(watchdog())
        prober = asyncio.ensure_future(probe_victim())

        # degrade ONE worker: sticky per-instance delay, sized to take
        # its dilated step time to gray_slowdown x the fleet's
        step_s = victim.engine.config.decode_step_s / cfg.gray_speedup
        delay_ms = (cfg.gray_slowdown - 1.0) * step_s * 1000.0
        FAULTS.configure(
            f"engine.step:delay={delay_ms:g}ms~{victim.fault_instance}"
        )
        t_degrade = time.monotonic()

        # phase B: traffic THROUGH the degradation + detection window
        degraded = (await replay_trace(
            engine.generate,
            _mk_trace(cfg, "gray-deg", requests=reqs, rate=rate, osl=osl,
                      groups=n, seed=cfg.seed + 1),
            id_prefix="gray-deg",
        )).summary()
        budget_wall = cfg.gray_detect_budget_s / cfg.gray_speedup
        deadline = t_degrade + 3 * budget_wall
        while not quarantined_at and time.monotonic() < deadline:
            await asyncio.sleep(0.01)

        # phase C: post-quarantine — victim excluded, replacement live
        served_before = victim.served_requests
        after = (await replay_trace(
            engine.generate,
            _mk_trace(cfg, "gray-after", requests=reqs, rate=rate, osl=osl,
                      groups=n, seed=cfg.seed + 2),
            id_prefix="gray-after",
        )).summary()
        victim_served_after_q = victim.served_requests - served_before
        desired_while_q = ctrl.engine.current()[0]

        # heal: clear the fault; the probe loop refreshes the fingerprint
        # and the watchdog re-admits on score decay
        FAULTS.clear()
        heal_deadline = time.monotonic() + 20 * budget_wall
        while not readmitted and time.monotonic() < heal_deadline:
            await asyncio.sleep(0.01)
        await ctrl.tick()  # unwind the replacement overlay
        desired_final = ctrl.engine.current()[0]
        victim_card = await fleet.hub.get(victim.served.instance.path)

        stop.set()
        await prober
        await driver
        migrations = migrations_snapshot() - mig0
    finally:
        FAULTS.clear()
        stop.set()
        await ctrl.close()
        await tel.close()
        await fleet.close()

    detect_dilated_s = (
        (quarantined_at[0] - t_degrade) * cfg.gray_speedup
        if quarantined_at else None
    )
    errors = base["errors"] + degraded["errors"] + after["errors"]
    base_p99 = base["ttft_ms_p99"] or 0.0
    after_p99 = after["ttft_ms_p99"] or 0.0
    return {
        "workers": n,
        "slowdown": cfg.gray_slowdown,
        "detect_dilated_s": (
            round(detect_dilated_s, 3) if detect_dilated_s else None
        ),
        "baseline_ttft_ms_p99": base_p99,
        "degraded_ttft_ms_p99": degraded["ttft_ms_p99"],
        "after_ttft_ms_p99": after_p99,
        "migrations": migrations,
        "victim_served_after_quarantine": victim_served_after_q,
        "desired_while_quarantined": desired_while_q,
        "desired_final": desired_final,
        "spawned": backend.spawned,
        "invariants": {
            "quarantined_within_budget": _inv(
                detect_dilated_s is not None
                and detect_dilated_s <= cfg.gray_detect_budget_s,
                detect_dilated_s=detect_dilated_s,
                budget_dilated_s=cfg.gray_detect_budget_s,
            ),
            "zero_client_errors": _inv(errors == 0, errors=errors),
            "ttft_recovered_after_quarantine": _inv(
                after_p99 <= 1.5 * base_p99,
                after_ms=after_p99, baseline_ms=base_p99,
            ),
            "victim_excluded_while_quarantined": _inv(
                victim_served_after_q == 0,
                served=victim_served_after_q,
            ),
            "autoscaler_replaced_quarantined": _inv(
                desired_while_q == n + 1 and backend.spawned >= 1,
                desired_while_quarantined=desired_while_q,
                spawned=backend.spawned,
            ),
            "readmitted_and_unwound": _inv(
                bool(readmitted)
                and not is_quarantined(victim_card or {})
                and desired_final == n,
                readmitted=bool(readmitted),
                desired_final=desired_final,
            ),
        },
    }


SCENARIOS = {
    "pick_scaling": pick_scaling,
    "leader_kill": leader_kill,
    "partition": partition,
    "churn": churn,
    "breaker_storm": breaker_storm,
    "tenant_storm": tenant_storm,
    "telemetry_overhead": telemetry,
    "autoscale": autoscale,
    "gray_failure": gray_failure,
}
