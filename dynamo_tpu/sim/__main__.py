"""``python -m dynamo_tpu.sim``: run the cluster chaos scenarios and
write the saturation-curve artifact.

    python -m dynamo_tpu.sim --scenario all --workers 200
    python -m dynamo_tpu.sim --scenario churn,partition --workers 32 \
        --speedup 200 --out SIM_smoke.json

Exit code is 0 only when every scenario's invariants pass — the nightly
chaos recipe (recipes/chaos/nightly.sh) treats a nonzero exit as a red
run. The artifact schema is documented in the README's "Cluster
simulation" section.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys

from dynamo_tpu.sim.harness import SimConfig, run_scenarios, write_artifact
from dynamo_tpu.sim.scenarios import SCENARIOS


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "dynamo-tpu cluster chaos sim",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--scenario", default="all",
                   help="'all' or comma-separated names: "
                        + ",".join(SCENARIOS))
    p.add_argument("--workers", type=int, default=200)
    p.add_argument("--speedup", type=float, default=150.0)
    p.add_argument("--fleet-sizes", default=None,
                   help="pick_scaling curve sizes, e.g. 50,100,200 "
                        "(default: workers/4, workers/2, workers)")
    p.add_argument("--trace-requests", type=int, default=0,
                   help="replay length (0 = 2 * workers)")
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--lease-s", type=float, default=0.5)
    p.add_argument("--storm-duration-s", type=float, default=8.0)
    p.add_argument("--partition-window-s", type=float, default=3.0)
    p.add_argument("--churn-waves", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="SIM_r01.json")
    args = p.parse_args(argv)

    logging.basicConfig(
        level=logging.WARNING,
        format="%(asctime)s %(name)s %(message)s",
    )
    cfg = SimConfig(
        workers=args.workers,
        speedup=args.speedup,
        fleet_sizes=tuple(
            int(s) for s in args.fleet_sizes.split(",")
        ) if args.fleet_sizes else (),
        trace_requests=args.trace_requests,
        replicas=args.replicas,
        lease_s=args.lease_s,
        storm_duration_s=args.storm_duration_s,
        partition_window_s=args.partition_window_s,
        churn_waves=args.churn_waves,
        seed=args.seed,
    )
    names = (
        list(SCENARIOS)
        if args.scenario == "all"
        else [s.strip() for s in args.scenario.split(",") if s.strip()]
    )
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        p.error(f"unknown scenario(s) {unknown}; have {list(SCENARIOS)}")

    artifact = asyncio.run(run_scenarios(cfg, names))
    write_artifact(artifact, args.out)
    for name, sc in artifact["scenarios"].items():
        print(f"{name:>20}: {sc['verdict']:5} ({sc['wall_s']}s)"
              + (f" — {sc.get('reason')}" if sc.get("reason") else ""))
    print(json.dumps({
        "verdict": artifact["verdict"], "artifact": args.out,
    }))
    return 0 if artifact["verdict"] == "pass" else 1


if __name__ == "__main__":
    sys.exit(main())
