"""Leader-driven SPMD mirroring: one logical worker across many hosts.

Multi-controller JAX requires EVERY process of a multi-host mesh to issue
the same compiled programs in the same order — a follower that merely
joins ``jax.distributed`` and parks would deadlock the leader's first
collective. This module closes that loop (SURVEY §7 hard part (d); the
reference leans on engine-internal NCCL/MPI worlds for the same job,
e.g. components/backends/trtllm/multinode/):

- The LEADER runs the full serving engine (scheduler, paged-cache
  bookkeeping, sampling, streaming). Before every device dispatch on the
  serving path it broadcasts a step descriptor — op tag + the host-side
  arrays the jit call consumes.
- Every FOLLOWER holds an identical engine shell (same spec, config,
  deterministic params, same mesh over the same global device set) and
  replays each descriptor with the SAME jitted entry points, so the
  compiled SPMD programs and their collectives line up across processes.
  Followers keep only the device state (their parameter + KV-cache
  shards); all logits/token results are discarded — the leader is the
  single identity routers and clients see.

TRANSPORT: a dedicated leader->follower TCP stream with binary msgpack
framing (runtime/framing.py) — array payloads travel as raw bytes, no
base64, no hub round-trip on the dispatch path. The hub carries only the
leader's descriptor address (``spmd/<group>/addr``); per-connection FIFO
gives ordering, and a bounded ring buffer replays the backlog to
followers that connect late (beyond the window, the follower fails
loudly instead of silently desyncing).

PIPELINED decode replays too: burst descriptors carry the chain-validity
masks, and each follower chains fed tokens from ITS OWN pending burst
results exactly as the leader does on its shards — multi-host decode
keeps the deep-pipeline throughput. (Async admissions stay leader-local:
their first tokens reach followers through the next burst's host token
array.)
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Any

import numpy as np

from dynamo_tpu.runtime.framing import read_frame, write_frame

log = logging.getLogger("dynamo.spmd")

ADDR_KEY_FMT = "spmd/{group}/addr"
RING_FRAMES = 1024  # catch-up window cap (descriptors)
RING_BYTES = 64 * 1024 * 1024  # catch-up window cap (payload bytes)
SYNC_CHUNK_BYTES = 64 * 1024 * 1024  # rejoin snapshot chunk (< MAX_FRAME)
# how long a rejoiner may overflow its (bounded) sync queue without
# latching the strict-mode plane broken: dropping it forces a clean
# re-sync, which is recoverable — unlike a live follower losing frames
SYNC_DRAIN_GRACE_S = 300.0

# queue sentinel: the leader dropped this follower (stopped draining);
# closing its stream makes the loss VISIBLE so it re-syncs
_DROPPED = object()


def _enc(arr: np.ndarray) -> dict[str, Any]:
    arr = np.ascontiguousarray(arr)
    return {
        "dtype": arr.dtype.name,
        "shape": list(arr.shape),
        "data": arr.tobytes(),  # raw bytes: msgpack bin, no base64
    }


def _dec(d: dict[str, Any]) -> np.ndarray:
    return np.frombuffer(
        d["data"], dtype=np.dtype(d["dtype"])
    ).reshape(d["shape"])


class SpmdLeader:
    """Streams step descriptors to followers over direct TCP.

    ``publish`` is called from the engine's step THREAD and never blocks:
    it appends to the ring and hands the frame to each connection's
    writer queue on the event loop. A follower that disconnects after
    joining, or that asks for history beyond the ring, breaks lockstep
    permanently — the plane latches broken (surfaced via engine.is_dead).
    """

    def __init__(self, hub, loop: asyncio.AbstractEventLoop, group: str,
                 host: str = "127.0.0.1", strict: bool | None = None):
        self.hub = hub
        self.loop = loop
        self.group = group
        self.host = host
        self.publish_failures = 0
        self.publish_count = 0  # monotonic; lets callers scope failures
        self._broken = False
        # STRICT mode: any follower loss latches the plane broken. This
        # is the only honest policy when the mesh SPANS processes
        # (jax.distributed is not elastic — a dead process hangs the next
        # collective; ranks restart together, exactly like the
        # reference's NCCL/MPI worlds). In MIRROR topologies (each
        # process runs its own local mesh and replays descriptors), a
        # lost follower is recoverable: the leader keeps serving and the
        # restarted follower re-joins with a state sync (hello
        # {"sync": true} -> quiesced KV snapshot -> live stream).
        if strict is None:
            try:
                import jax

                strict = jax.process_count() > 1
            except Exception:  # noqa: BLE001
                # jax absent/uninitialized: single-process default. Log it
                # — a mis-probed multi-host run silently losing strictness
                # is exactly the lockstep bug class (dynalint DL003)
                log.debug("jax process_count probe failed; strict=False",
                          exc_info=True)
                strict = False
        self.strict = strict
        # rejoin state-sync requests parked until the engine reaches a
        # step boundary (serve_sync); count readable cross-thread. Each
        # entry carries its connection's writer so _resolve can skip
        # requesters that died while parked (crash-looping followers)
        self._sync_waiting: list[tuple[asyncio.Future, Any]] = []
        self._sync_pending = 0
        self.on_sync_request = None  # engine wake hook (set by engine)
        # catch-up ring: bounded by frames AND payload bytes (decode
        # descriptors are tens of KB at production batch shapes; an
        # unbounded byte footprint would pin hundreds of MB per worker)
        self._ring: deque[tuple[int, dict, int]] = deque()
        self._ring_bytes = 0
        # highest seq visible ON THE EVENT LOOP (mutated only in
        # _enqueue): the join handshake must not race the step thread's
        # publish_count, which increments before the loop callback runs
        self._loop_seq = 0
        self._conns: list[asyncio.Queue] = []
        self._server: asyncio.AbstractServer | None = None

    async def start(self) -> "SpmdLeader":
        self._server = await asyncio.start_server(
            self._serve_conn, self.host, 0
        )
        port = self._server.sockets[0].getsockname()[1]
        await self.hub.put(
            ADDR_KEY_FMT.format(group=self.group), f"{self.host}:{port}"
        )
        log.info("spmd leader descriptor plane on %s:%d", self.host, port)
        return self

    @property
    def healthy(self) -> bool:
        return not self._broken

    def mark_broken(self, reason: str) -> None:
        """Latch the plane broken: a lost/failed descriptor (or a local
        dispatch that failed after its descriptor went out) leaves
        followers permanently out of lockstep — there is no re-sync
        protocol, so it must be VISIBLE, not a silent deadlock."""
        if not self._broken:
            log.error("spmd plane broken: %s", reason)
        self._broken = True

    async def _serve_conn(self, reader, writer) -> None:
        peer = writer.get_extra_info("peername")
        hello = await read_frame(reader)
        if hello is None:
            writer.close()
            return
        if hello.get("sync"):
            # REJOIN: instead of a descriptor backlog, this follower gets
            # a quiesced state snapshot. Park until the engine reaches a
            # step boundary and calls serve_sync (on_sync_request wakes
            # an idle step loop), then stream the snapshot + live frames.
            # (A requester that dies while parked costs the engine one
            # wasted quiesce — bounded per connection attempt.)
            fut: asyncio.Future = self.loop.create_future()
            self._sync_waiting.append((fut, writer))
            self._sync_pending += 1
            if self.on_sync_request is not None:
                self.on_sync_request()
            log.info("spmd follower %s requested rejoin sync", peer)
            try:
                sync_frames, q = await fut
            except asyncio.CancelledError:
                writer.close()
                raise
            await self._stream_to(peer, writer, q, sync_frames)
            return
        from_seq = int(hello.get("from_seq", 0))
        oldest = self._ring[0][0] if self._ring else self._loop_seq + 1
        if from_seq + 1 < oldest:
            # history beyond the catch-up window: joining would silently
            # desync — refuse loudly (the follower falls back to a sync
            # rejoin)
            await write_frame(writer, {
                "op": "__reject__",
                "scalars": {"reason": f"catch-up window exceeded "
                            f"(need {from_seq + 1}, oldest {oldest})"},
                "arrays": {},
            })
            writer.close()
            if self.strict:
                self.mark_broken(
                    f"follower {peer} beyond catch-up window"
                )
            return
        # bounded to the SAME window as the catch-up ring: a join within
        # the advertised window must never be broken by publishes landing
        # during its backlog drain, while a follower that stops draining
        # latches loudly once it falls a full window behind (and the
        # bound caps the payload bytes a slow follower can pin)
        q: asyncio.Queue = asyncio.Queue(maxsize=RING_FRAMES)
        # backlog + live, no gap: single-threaded event loop between the
        # ring snapshot and the queue registration
        backlog = [f for s, f, _n in self._ring if s > from_seq]
        self._conns.append(q)
        log.info("spmd follower %s joined (%d backlog frames)",
                 peer, len(backlog))
        await self._stream_to(peer, writer, q, backlog)

    async def _stream_to(self, peer, writer, q: asyncio.Queue,
                         first_frames) -> None:
        """Shared send loop for both join paths: initial frames (backlog
        or sync snapshot), then live queue frames until the connection
        ends or the leader dropped this follower (_DROPPED sentinel —
        closing the stream makes the drop visible so it re-syncs)."""
        try:
            for f in first_frames:
                await write_frame(writer, f)
            while True:
                frame = await q.get()
                if frame is _DROPPED:
                    break
                await write_frame(writer, frame)
        except asyncio.CancelledError:
            raise  # orderly teardown, not a broken plane
        except (ConnectionError, OSError) as e:
            self._follower_lost(peer, e)
        finally:
            if q in self._conns:
                self._conns.remove(q)
            writer.close()

    def _follower_lost(self, peer, err) -> None:
        """Connection-loss policy: spanning mesh -> latch broken (the
        next collective would hang anyway); mirror topology -> keep
        serving, the follower re-syncs when it comes back."""
        if self.strict:
            self.mark_broken(f"follower {peer} connection lost: {err}")
        else:
            log.warning(
                "spmd follower %s lost (%s); serving continues, "
                "awaiting rejoin", peer, err,
            )

    @property
    def sync_pending(self) -> int:
        """Rejoin syncs waiting for the engine's next step boundary."""
        return self._sync_pending

    def serve_sync(self, chunks: list[tuple]) -> None:
        """Resolve every parked rejoin with a quiesced state snapshot.
        Called from the engine's step THREAD at a step boundary (pipeline
        flushed, admission waves landed) so the snapshot is exact; the
        queue registration happens on the loop BEFORE any later
        publish's _enqueue callback, so the follower sees snapshot ->
        every subsequent descriptor with no gap.

        ``chunks`` is a list of (page_ids, k, v) numpy chunks, already
        sized under SYNC_CHUNK_BYTES at extraction (a production cache
        runs to GBs, far past the wire codec's MAX_FRAME and far past
        what the leader host should materialize at once); the follower
        installs chunks as they arrive (the final carries ``last``)."""
        seq = self.publish_count
        frames: list[dict] = []
        if not chunks:
            frames.append({
                "op": "__sync__",
                "scalars": {"seq": seq, "last": True},
                "arrays": {"page_ids": _enc(np.zeros((0,), np.int32))},
            })
        else:
            for i, (ids, k, v) in enumerate(chunks):
                frames.append({
                    "op": "__sync__",
                    "scalars": {"seq": seq, "last": i == len(chunks) - 1},
                    "arrays": {
                        "page_ids": _enc(ids),
                        "k": _enc(k),
                        "v": _enc(v),
                    },
                })
        self._sync_pending = 0

        def _resolve() -> None:
            waiting, self._sync_waiting = self._sync_waiting, []
            for fut, writer in waiting:
                if fut.done():
                    continue
                if writer.is_closing():
                    # the requester died while parked (crash-looping
                    # follower): cancelling sends its handler to the
                    # close path instead of registering an orphan queue
                    # that would absorb every descriptor until the next
                    # failed write discovered the corpse
                    fut.cancel()
                    continue
                # live queue bounded at 4x the catch-up window: a
                # GB-scale snapshot takes tens of seconds to cross the
                # wire while the leader keeps publishing, so the sync
                # queue gets generous headroom — but NOT unbounded, so a
                # follower that died (or stalled) mid-snapshot hits the
                # normal overflow path (drop backlog + _DROPPED) instead
                # of pinning leader memory forever. The grace deadline
                # exempts that overflow from the strict-mode broken
                # latch: a rejoiner drowning in its own snapshot is a
                # recoverable re-sync, not a lost-lockstep event.
                q = asyncio.Queue(maxsize=4 * RING_FRAMES)
                q.sync_grace_until = (
                    time.monotonic() + SYNC_DRAIN_GRACE_S
                )
                self._conns.append(q)
                fut.set_result((frames, q))

        try:
            self.loop.call_soon_threadsafe(_resolve)
        except RuntimeError:
            pass  # loop closed during shutdown

    def publish(self, op: str, scalars: dict[str, Any] | None = None,
                arrays: dict[str, np.ndarray] | None = None) -> None:
        msg = {
            "op": op,
            "scalars": scalars or {},
            "arrays": {
                k: _enc(np.asarray(v)) for k, v in (arrays or {}).items()
            },
        }
        self.publish_count += 1
        seq = self.publish_count

        nbytes = sum(
            len(v["data"]) for v in msg["arrays"].values()
        ) + 256

        def _enqueue() -> None:
            self._loop_seq = seq
            self._ring.append((seq, msg, nbytes))
            self._ring_bytes += nbytes
            while self._ring and (
                len(self._ring) > RING_FRAMES
                or self._ring_bytes > RING_BYTES
            ):
                _s, _m, n = self._ring.popleft()
                self._ring_bytes -= n
            for q in list(self._conns):
                try:
                    q.put_nowait(msg)
                except asyncio.QueueFull:
                    self._conns.remove(q)
                    backlog = q.qsize()
                    # make the drop VISIBLE to the follower: flush the
                    # backlog and leave only the sentinel, so its stream
                    # closes at a clean frame boundary (applying frames
                    # past a gap would diverge its replay; a silently-
                    # frozen stream would never trigger the rejoin)
                    try:
                        while True:
                            q.get_nowait()
                    except asyncio.QueueEmpty:
                        pass
                    q.put_nowait(_DROPPED)
                    in_sync_grace = (
                        getattr(q, "sync_grace_until", 0.0)
                        > time.monotonic()
                    )
                    if self.strict and not in_sync_grace:
                        self.mark_broken(
                            "follower stopped draining descriptors "
                            f"({backlog} backlogged)"
                        )
                    else:
                        log.warning(
                            "spmd follower stopped draining; dropped "
                            "(it will rejoin with a state sync)"
                        )

        try:
            self.loop.call_soon_threadsafe(_enqueue)
        except RuntimeError as e:  # loop closed
            self.publish_failures += 1
            self.mark_broken(f"descriptor publish failed: {e}")

    def stop(self) -> None:
        self.publish("stop")

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
        try:
            # drop the advertised address: a follower from a later run
            # must not connect to this dead leader
            await self.hub.delete(ADDR_KEY_FMT.format(group=self.group))
        # dynalint: disable=DL003 -- best-effort address withdrawal during
        # close; the hub being already gone is the expected failure here
        except Exception:  # noqa: BLE001 - hub may already be gone
            pass


class SpmdFollower:
    """Replays the leader's step descriptors against a local engine shell.

    The engine shell must be constructed EXACTLY as the leader's (spec,
    EngineConfig, mesh, params init) — descriptor replay only drives the
    jitted entry points; any divergence in static shapes would compile a
    different program and desynchronize the collectives.
    """

    def __init__(self, hub, group: str, engine, rejoin: bool | None = None):
        self.hub = hub
        self.group = group
        self.engine = engine
        # follower-side pipeline mirror: device results of the last
        # decode bursts, for chain replay (oldest first). Sized from the
        # engine's pipeline depth — a mirror shorter than the leader's
        # chain would misalign every mask
        depth = int(getattr(engine.config, "pipeline_depth", 2) or 2)
        self._pending: deque = deque(maxlen=max(8, depth + 2))
        # rejoin: on stream loss, reconnect with a state-sync join
        # instead of dying. Only valid in MIRROR topologies (local mesh
        # per process); a spanning jax.distributed mesh is not elastic.
        if rejoin is None:
            try:
                import jax

                rejoin = jax.process_count() == 1
            except Exception:  # noqa: BLE001
                # jax absent/uninitialized: mirror-topology default; log
                # the probe failure (see SpmdLeader.strict — dynalint DL003)
                log.debug("jax process_count probe failed; rejoin=True",
                          exc_info=True)
                rejoin = True
        self.rejoin = rejoin
        self.rejoins = 0  # completed state-sync rejoins (test hook)
        self._sync_pages = 0  # pages installed across the current sync
        # pre-restart tier hashes that already bought one re-sync: a
        # second miss zero-fills loudly instead of looping quiesces
        self._tier_missed: set[int] = set()

    async def _leader_addr(self, timeout: float = 60.0) -> str:
        key = ADDR_KEY_FMT.format(group=self.group)
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            addr = await self.hub.get(key)
            if addr:
                return addr
            if asyncio.get_running_loop().time() > deadline:
                raise TimeoutError(f"no spmd leader address at {key}")
            await asyncio.sleep(0.2)

    async def run(self) -> None:
        """Replay forever; in rejoin mode a lost stream (leader dropped
        us, network blip, or we restarted) reconnects with a state-sync
        join and resumes lockstep from the snapshot."""
        import os

        # a RESTARTED follower process can skip the backlog attempt and
        # go straight to the snapshot (a fresh process's from_seq=0 only
        # works while the leader's ring still reaches back to seq 1)
        sync_join = os.environ.get("DYNAMO_SPMD_SYNC_JOIN") == "1"
        while True:
            try:
                await self._run_once(sync_join)
                return  # leader sent "stop": orderly end
            except ConnectionError as e:
                if not self.rejoin:
                    raise
                log.warning(
                    "spmd stream lost (%s); rejoining with state sync", e
                )
                self._pending.clear()
                sync_join = True
                await asyncio.sleep(0.2)

    async def _run_once(self, sync_join: bool) -> None:
        # the hub key may briefly hold a PREVIOUS leader's address
        # (leader restarting): retry connect, re-reading the key
        deadline = asyncio.get_running_loop().time() + 60.0
        while True:
            addr = await self._leader_addr()
            host, port = addr.rsplit(":", 1)
            try:
                reader, writer = await asyncio.open_connection(
                    host, int(port)
                )
                break
            except OSError as e:
                if asyncio.get_running_loop().time() > deadline:
                    raise ConnectionError(
                        f"spmd leader at {addr} unreachable: {e}"
                    ) from e
                await asyncio.sleep(0.3)
        await write_frame(writer, {"from_seq": 0, "sync": sync_join})
        log.info(
            "spmd follower replaying from %s%s", addr,
            " (sync join)" if sync_join else "",
        )
        try:
            await self._replay(reader, writer)
        finally:
            writer.close()  # a replay abort must not leak the socket

    async def _replay(self, reader, writer) -> None:
        import os
        import time as _time

        import jax.numpy as jnp

        eng = self.engine
        fam = eng.fam  # family adapter: replay works for GQA AND MLA
        spec, mesh = eng.spec, eng.mesh
        trace = os.environ.get("DYNAMO_SPMD_TRACE") == "1"
        t_prev = _time.perf_counter()
        while True:
            msg = await read_frame(reader)
            t_recv = _time.perf_counter()
            if msg is None:
                raise ConnectionError(
                    "spmd descriptor stream closed by leader"
                )
            op = msg["op"]
            if trace:
                print(
                    f"SPMDTRACE wait={_time.perf_counter() - t_prev:.4f} "
                    f"op={op}", flush=True,
                )
            sc = msg["scalars"]
            ar = {k: _dec(v) for k, v in msg["arrays"].items()}
            if op == "stop":
                log.info("spmd follower: leader stopped")
                writer.close()
                return
            if op == "__reject__":
                if self.rejoin:
                    # beyond the catch-up window: fall back to a fresh
                    # state-sync join instead of dying
                    raise ConnectionError(
                        f"join rejected ({sc.get('reason')})"
                    )
                raise RuntimeError(
                    f"spmd leader rejected join: {sc.get('reason')}"
                )
            if op == "__sync__":
                # rejoin snapshot (possibly one of several chunks):
                # install the leader's quiesced KV pages. Params are
                # deterministic — same init/checkpoint — and the leader
                # flushed its pipeline, so the chain mirror starts empty.
                ids = ar["page_ids"].astype(np.int32)
                if ids.size:
                    eng.k_pages, eng.v_pages = fam.insert_pages(
                        eng.k_pages, eng.v_pages, jnp_i32(ids),
                        jnp.asarray(ar["k"]), jnp.asarray(ar["v"]),
                    )
                self._sync_pages += int(ids.size)
                if sc.get("last", True):
                    self._pending.clear()
                    self.rejoins += 1
                    log.info(
                        "spmd rejoin complete: %d pages synced at seq %s",
                        self._sync_pages, sc.get("seq"),
                    )
                    self._sync_pages = 0
                t_prev = _time.perf_counter()
                continue
            # every branch matches one leader dispatch site in
            # engine/core.py; keep in lockstep with it. All model calls
            # go through the family adapter so the compiled programs are
            # the leader's exact entry points for this architecture.
            if op == "prefill":
                mm_kwargs = {}
                if "mm_embeds" in ar:
                    mm_kwargs = {
                        "mm_embeds": jnp.asarray(
                            ar["mm_embeds"].astype(np.float32)
                        ),
                        "mm_pos": jnp_i32(ar["mm_pos"]),
                    }
                _logits, eng.k_pages, eng.v_pages, _d = fam.prefill(
                    spec, eng.params,
                    jnp_i32(ar["tokens"]), jnp_i32(ar["block_table"]),
                    jnp_scalar(sc["start"]), eng.k_pages, eng.v_pages,
                    jnp_scalar(sc["num_tokens"]), mesh=mesh, **mm_kwargs,
                )
            elif op == "ring_prefill":
                (_logits, eng.k_pages, eng.v_pages,
                 _d) = fam.prefill_ring(
                    spec, eng.params,
                    jnp_i32(ar["tokens"]), jnp_i32(ar["block_table"]),
                    eng.k_pages, eng.v_pages,
                    jnp_scalar(sc["num_tokens"]), mesh=mesh,
                )
            elif op == "prefill_batch":
                (_lg, eng.k_pages, eng.v_pages,
                 _d) = fam.prefill_batch(
                    spec, eng.params,
                    jnp_i32(ar["tokens"]), jnp_i32(ar["block_tables"]),
                    jnp_i32(ar["start"]), eng.k_pages, eng.v_pages,
                    jnp_i32(ar["num_tokens"]), mesh=mesh,
                )
            elif op == "kv_offload":
                # mirror the leader's tier offload: extract the SAME pages
                # (this process keeps its shard) and offer them to the
                # local KVBM tiers (ref KvbmWorker, distributed/worker.rs)
                ids = jnp_i32(ar["page_ids"])
                kb, vb = fam.extract_pages(eng.k_pages, eng.v_pages, ids)
                try:
                    kb.copy_to_host_async()
                    vb.copy_to_host_async()
                except AttributeError:
                    pass
                if eng.offload is not None:
                    eng.offload.submit(
                        [int(h) for h in sc["hashes"]], kb, vb
                    )
            elif op == "kv_onboard":
                hashes = [int(h) for h in sc["hashes"]]
                missing = (
                    [h for h in hashes if h not in eng.kvbm]
                    if self.rejoins and eng.kvbm is not None else []
                )
                fresh_miss = [
                    h for h in missing if h not in self._tier_missed
                ]
                if fresh_miss:
                    # this process's tier copy died with the pre-restart
                    # incarnation; a fresh state sync recovers the
                    # leader's post-onboard DEVICE pages exactly. ONE
                    # re-sync per hash: tier content itself is
                    # unrecoverable (it died with the old process), so a
                    # second miss of the same hash falls through to the
                    # loud zero-fill instead of looping quiesces forever.
                    self._tier_missed.update(fresh_miss)
                    raise ConnectionError(
                        f"kvbm tier miss after rejoin "
                        f"({len(fresh_miss)} blocks); re-syncing"
                    )
                if missing:
                    log.error(
                        "kvbm onboard of %d pre-restart blocks after "
                        "re-sync: tier data unrecoverable, shard "
                        "zero-fills (mirror fidelity degraded until the "
                        "blocks cycle out)", len(missing),
                    )
                eng.onboard_from_tiers(
                    hashes, ar["page_ids"].astype(np.int32),
                )
            elif op == "decode":
                tokens_in = jnp_i32(ar["tokens"])
                n_chain = int(sc.get("n_chain", 0))
                if n_chain:
                    # chain replay: same masks the leader used, against
                    # THIS process's pending burst results (its shards)
                    prevs = list(self._pending)[-n_chain:]
                    if len(prevs) < n_chain:
                        raise RuntimeError(
                            f"chain replay misaligned: leader chained "
                            f"{n_chain} bursts, mirror holds {len(prevs)}"
                        )
                    for i, prev in enumerate(prevs):
                        valid = jnp.asarray(
                            ar[f"chain_valid_{i}"].astype(bool)
                        )
                        tokens_in = jnp.where(
                            valid, prev[:, -1], tokens_in
                        )
                result = fam.decode_steps(
                    spec, eng.params,
                    tokens_in, jnp_i32(ar["block_tables"]),
                    jnp_i32(ar["seq_lens"]), eng.k_pages, eng.v_pages,
                    jnp.asarray(ar["active"].astype(bool)),
                    jnp.asarray(ar["temps"]), jnp_i32(ar["topk"]),
                    jnp.asarray(ar["topp"]),
                    jnp.asarray(ar["seeds"].astype(np.uint32)),
                    jnp_i32(ar["steps"]),
                    n_steps=int(sc["n_steps"]), n_logprobs=int(sc["n_lp"]),
                    mesh=mesh,
                )
                eng.k_pages, eng.v_pages = result[-2], result[-1]
                self._pending.append(result[0])  # sampled [B, n]
            else:  # pragma: no cover - protocol drift guard
                raise RuntimeError(f"unknown spmd op {op!r}")
            if trace:
                # n_steps lets tests assert descriptor amortization (one
                # frame covering N decode steps) without timing anything
                extra = (
                    f" n_steps={int(sc['n_steps'])}" if op == "decode" else ""
                )
                print(
                    f"SPMDTRACE apply={_time.perf_counter() - t_recv:.4f} "
                    f"op={op}{extra}", flush=True,
                )
            t_prev = _time.perf_counter()


def jnp_i32(a: np.ndarray):
    import jax.numpy as jnp

    return jnp.asarray(a.astype(np.int32))


def jnp_scalar(v):
    import jax.numpy as jnp

    return jnp.asarray(int(v), jnp.int32)
