"""Leader-driven SPMD mirroring: one logical worker across many hosts.

Multi-controller JAX requires EVERY process of a multi-host mesh to issue
the same compiled programs in the same order — a follower that merely
joins ``jax.distributed`` and parks would deadlock the leader's first
collective. This module closes that loop (SURVEY §7 hard part (d); the
reference leans on engine-internal NCCL/MPI worlds for the same job,
e.g. components/backends/trtllm/multinode/):

- The LEADER runs the full serving engine (scheduler, paged-cache
  bookkeeping, sampling, streaming). Before every device dispatch on the
  serving path it broadcasts a step descriptor — op tag + the host-side
  arrays the jit call consumes — on a hub subject.
- Every FOLLOWER holds an identical engine shell (same spec, config,
  deterministic params, same mesh over the same global device set) and
  replays each descriptor with the SAME jitted entry points, so the
  compiled SPMD programs and their collectives line up across processes.
  Followers keep only the device state (their parameter + KV-cache
  shards); all logits/token results are discarded — the leader is the
  single identity routers and clients see.

The hub stream is retained + seq-ordered (JetStream-style), so a
follower that connects late replays the backlog in order. Descriptors
are small (batch metadata, not activations): tokens, block tables,
sampling params — a few KB per step.

Trade-off: hub round-trips add per-dispatch latency vs. a raw ICI
broadcast; correctness and testability (the whole flow runs as N local
CPU processes) come first, and the descriptor plane is swappable.
"""

from __future__ import annotations

import asyncio
import base64
import logging
from typing import Any

import numpy as np

log = logging.getLogger("dynamo.spmd")

SUBJECT_FMT = "spmd/{group}/steps"


def _enc(arr: np.ndarray) -> dict[str, Any]:
    arr = np.ascontiguousarray(arr)
    return {
        "dtype": arr.dtype.name,
        "shape": list(arr.shape),
        "b64": base64.b64encode(arr.tobytes()).decode(),
    }


def _dec(d: dict[str, Any]) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(d["b64"]), dtype=np.dtype(d["dtype"])
    ).reshape(d["shape"])


class SpmdLeader:
    """Publishes step descriptors from the engine's step THREAD.

    Publishes are fire-and-forget onto the hub client's event loop: the
    hub assigns sequence numbers in publish order (FIFO per connection),
    so followers see the exact dispatch order without the step thread
    blocking on a network round-trip.
    """

    def __init__(self, hub, loop: asyncio.AbstractEventLoop, group: str):
        self.hub = hub
        self.loop = loop
        self.subject = SUBJECT_FMT.format(group=group)
        # broadcast-plane health: a STICKY latch. One lost descriptor
        # leaves followers permanently out of lockstep (there is no
        # re-sync protocol), so a later successful publish must NOT
        # clear the flag — the broken plane has to stay VISIBLE
        # (EngineMonitor surfaces `healthy`) rather than silently
        # deadlocking the next collective.
        self.publish_failures = 0
        self.publish_count = 0  # monotonic; lets callers scope failures
        self._broken = False

    @property
    def healthy(self) -> bool:
        return not self._broken

    def mark_broken(self, reason: str) -> None:
        """Latch the plane broken for a POST-publish failure: the local
        dispatch raised after its descriptor already went out, so
        followers replayed (or are blocked inside) a program the leader
        abandoned — lockstep is gone even though the publish worked."""
        if not self._broken:
            log.error("spmd plane broken: %s", reason)
        self._broken = True

    def _on_publish_done(self, fut) -> None:
        if fut.cancelled():
            exc: BaseException | None = asyncio.CancelledError()
        else:
            exc = fut.exception()
        if exc is not None:
            self.publish_failures += 1
            self._broken = True
            log.error(
                "spmd descriptor publish failed (%d total): %s — "
                "followers are no longer in lockstep", self.publish_failures,
                exc,
            )

    def publish(self, op: str, scalars: dict[str, Any] | None = None,
                arrays: dict[str, np.ndarray] | None = None) -> None:
        msg = {
            "op": op,
            "scalars": scalars or {},
            "arrays": {k: _enc(np.asarray(v)) for k, v in (arrays or {}).items()},
        }
        self.publish_count += 1
        fut = asyncio.run_coroutine_threadsafe(
            self.hub.publish(self.subject, msg), self.loop
        )
        fut.add_done_callback(self._on_publish_done)

    def stop(self) -> None:
        self.publish("stop")


class SpmdFollower:
    """Replays the leader's step descriptors against a local engine shell.

    The engine shell must be constructed EXACTLY as the leader's (spec,
    EngineConfig, mesh, params init) — descriptor replay only drives the
    jitted entry points; any divergence in static shapes would compile a
    different program and desynchronize the collectives.
    """

    def __init__(self, hub, group: str, engine):
        self.hub = hub
        self.subject = SUBJECT_FMT.format(group=group)
        self.engine = engine

    async def run(self) -> None:
        eng = self.engine
        fam = eng.fam  # family adapter: replay works for GQA AND MLA
        spec, mesh = eng.spec, eng.mesh
        log.info("spmd follower replaying %s", self.subject)
        async for _subj, msg in self.hub.subscribe(self.subject, replay=True):
            op = msg["op"]
            sc = msg["scalars"]
            ar = {k: _dec(v) for k, v in msg["arrays"].items()}
            if op == "stop":
                log.info("spmd follower: leader stopped")
                return
            # every branch matches one leader dispatch site in
            # engine/core.py; keep in lockstep with it. All model calls
            # go through the family adapter so the compiled programs are
            # the leader's exact entry points for this architecture.
            if op == "prefill":
                import jax.numpy as _jnp

                mm_kwargs = {}
                if "mm_embeds" in ar:
                    mm_kwargs = {
                        "mm_embeds": _jnp.asarray(
                            ar["mm_embeds"].astype(np.float32)
                        ),
                        "mm_pos": jnp_i32(ar["mm_pos"]),
                    }
                _logits, eng.k_pages, eng.v_pages, _d = fam.prefill(
                    spec, eng.params,
                    jnp_i32(ar["tokens"]), jnp_i32(ar["block_table"]),
                    jnp_scalar(sc["start"]), eng.k_pages, eng.v_pages,
                    jnp_scalar(sc["num_tokens"]), mesh=mesh, **mm_kwargs,
                )
            elif op == "ring_prefill":
                (_logits, eng.k_pages, eng.v_pages,
                 _d) = fam.prefill_ring(
                    spec, eng.params,
                    jnp_i32(ar["tokens"]), jnp_i32(ar["block_table"]),
                    eng.k_pages, eng.v_pages,
                    jnp_scalar(sc["num_tokens"]), mesh=mesh,
                )
            elif op == "prefill_batch":
                (_lg, eng.k_pages, eng.v_pages,
                 _d) = fam.prefill_batch(
                    spec, eng.params,
                    jnp_i32(ar["tokens"]), jnp_i32(ar["block_tables"]),
                    jnp_i32(ar["start"]), eng.k_pages, eng.v_pages,
                    jnp_i32(ar["num_tokens"]), mesh=mesh,
                )
            elif op == "kv_offload":
                # mirror the leader's tier offload: extract the SAME pages
                # (this process keeps its shard) and offer them to the
                # local KVBM tiers (ref KvbmWorker, distributed/worker.rs)
                ids = jnp_i32(ar["page_ids"])
                kb, vb = fam.extract_pages(eng.k_pages, eng.v_pages, ids)
                try:
                    kb.copy_to_host_async()
                    vb.copy_to_host_async()
                except AttributeError:
                    pass
                if eng.offload is not None:
                    eng.offload.submit(
                        [int(h) for h in sc["hashes"]], kb, vb
                    )
            elif op == "kv_onboard":
                eng.onboard_from_tiers(
                    [int(h) for h in sc["hashes"]],
                    ar["page_ids"].astype(np.int32),
                )
            elif op == "decode":
                import jax.numpy as jnp

                result = fam.decode_steps(
                    spec, eng.params,
                    jnp_i32(ar["tokens"]), jnp_i32(ar["block_tables"]),
                    jnp_i32(ar["seq_lens"]), eng.k_pages, eng.v_pages,
                    jnp.asarray(ar["active"].astype(bool)),
                    jnp.asarray(ar["temps"]), jnp_i32(ar["topk"]),
                    jnp.asarray(ar["topp"]),
                    jnp.asarray(ar["seeds"].astype(np.uint32)),
                    jnp_i32(ar["steps"]),
                    n_steps=int(sc["n_steps"]), n_logprobs=int(sc["n_lp"]),
                    mesh=mesh,
                )
                eng.k_pages, eng.v_pages = result[-2], result[-1]
            else:  # pragma: no cover - protocol drift guard
                raise RuntimeError(f"unknown spmd op {op!r}")


def jnp_i32(a: np.ndarray):
    import jax.numpy as jnp

    return jnp.asarray(a.astype(np.int32))


def jnp_scalar(v):
    import jax.numpy as jnp

    return jnp.asarray(int(v), jnp.int32)
