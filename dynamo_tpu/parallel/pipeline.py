"""Pipeline parallelism: layer stages over the "pp" mesh axis.

The reference expresses PP as engine configuration
(components/backends/trtllm/engine_configs/deepseek_r1/wide_ep/
wide_ep_decode.yaml:25 ``pipeline_parallel_size``) and delegates the
mechanics to TRT-LLM. Here the engine is ours, so PP is built
TPU-natively: parameters and the paged KV cache are layer-partitioned
across the "pp" axis, and a step is a GPipe-style software pipeline
inside ONE ``shard_map`` — activations hop stage-to-stage with
``lax.ppermute`` over ICI while every stage computes a different
microbatch, so the chips stay busy outside the fill/drain bubbles.

Layout:
- ``stack_params`` restacks the per-layer param dicts into leaves with a
  leading layer axis ``[L, ...]``, sharded ``P("pp", ...)`` — each stage
  holds ``L / pp`` layers. Embedding / final norm / lm_head replicate
  across pp; lm_head column-shards over tp.
- The KV cache keeps its usual ``[L, pages, KH, page, D]`` layout,
  sharded ``P("pp", None, "tp", ...)``: a stage owns its layers' pages.
- TP composes INSIDE the stage body (shard_map exposes per-device
  shards, so Megatron TP is explicit here: column-parallel projections,
  ``psum`` over "tp" after attention-out and MLP-down). dp composes by
  sharding the batch. MoE layers are not yet expressible under pp
  (dense path only) — wide-EP decode runs pp=1 with ep/tp instead.

Scheduling (decode): the slot batch splits into ``pp`` microbatches;
at tick t stage s processes microbatch t-s. Invalid (bubble) ticks
compute on garbage and write their KV rows to the trash page, exactly
like padded slots in the non-pp path — no control flow, fixed shapes.
A full step takes 2*pp-1 ticks; per-stage work is 1/pp of the model, so
decode latency ~doubles at the bubble-heavy extreme while throughput
scales with the extra chips — PP here is a memory-capacity axis (fit
bigger models), not a latency axis, same trade the reference's configs
make.

Prefill runs the same pipeline with ONE microbatch (the whole prompt):
pure fill/drain, acceptable because prefill is compute-dense per stage.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.ops.shard import shard_map as compat_shard_map

from dynamo_tpu.engine.config import ModelSpec
from dynamo_tpu.models.llama import TRASH_PAGE, rms_norm, rope
from dynamo_tpu.ops.attention import (
    causal_attention,
    page_tiles,
    paged_decode_attention_auto,
)
from dynamo_tpu.ops.pallas.kv_write import write_new_kv

Params = dict


# ---------------------------------------------------------------- params


def stack_params(spec: ModelSpec, params: Params) -> Params:
    """Per-layer dicts -> stacked leaves [L, ...] (pp-shardable)."""
    if spec.num_experts:
        raise NotImplementedError(
            "pipeline parallelism currently covers dense layers only; "
            "run MoE models with ep/tp (wide-EP) instead"
        )
    layers = params["layers"]
    stacked = {
        key: jnp.stack([lp[key] for lp in layers]) for key in layers[0]
    }
    out = {k: v for k, v in params.items() if k != "layers"}
    out["layers"] = stacked
    return out


def pp_param_shardings(spec: ModelSpec, mesh: Mesh) -> Params:
    def ns(*axes):
        return NamedSharding(mesh, P(*axes))

    layers = {
        "attn_norm": ns("pp", None),
        "wq": ns("pp", None, "tp"),
        "wk": ns("pp", None, "tp"),
        "wv": ns("pp", None, "tp"),
        "wo": ns("pp", "tp", None),
        "mlp_norm": ns("pp", None),
        "w_gate": ns("pp", None, "tp"),
        "w_up": ns("pp", None, "tp"),
        "w_down": ns("pp", "tp", None),
    }
    out = {"embed": ns(), "final_norm": ns(), "layers": layers}
    if not spec.tie_embeddings:
        out["lm_head"] = ns(None, "tp")
    return out


def pp_cache_shardings(mesh: Mesh) -> tuple[NamedSharding, NamedSharding]:
    """[L, pages, KH, page, D]: layers over pp, kv heads over tp."""
    s = NamedSharding(mesh, P("pp", None, "tp", None, None))
    return s, s


# ------------------------------------------------------------- stage body


def _stage_decode(
    spec: ModelSpec,
    lp,  # stacked local leaves [L_local, ...]
    x: jax.Array,  # [Bm, d] (microbatch activations)
    positions: jax.Array,  # [Bm]
    k_pages,  # local [L_local, pages, KH_local, page, D]
    v_pages,
    block_tables: jax.Array,  # [Bm, P]
    seq_lens: jax.Array,  # [Bm]
    dst_page: jax.Array,  # [Bm] (already trash-masked for bubbles)
    dst_off: jax.Array,  # [Bm]
    n_local: int,
    tp_size: int,
    dp_size: int,
):
    """One pipeline stage's layers over one microbatch (manual Megatron
    TP: projections are column-local, outputs psum over "tp").

    The page pool replicates over dp while slots are dp-sharded, so every
    dp replica must apply EVERY replica's KV-row writes (the slot groups'
    pages are disjoint): new rows are tiny, so an all-gather over "dp"
    before the write keeps the replicated pool bit-identical — the manual
    form of what GSPMD inserts for scatters onto replicated operands."""
    Bm = x.shape[0]
    hd = spec.head_dim
    for i in range(n_local):
        h = rms_norm(x, lp["attn_norm"][i], spec.rms_eps)
        q = (h @ lp["wq"][i]).reshape(Bm, -1, hd)
        k = (h @ lp["wk"][i]).reshape(Bm, -1, hd)
        v = (h @ lp["wv"][i]).reshape(Bm, -1, hd)
        q = rope(q, positions, spec.rope_theta)
        k = rope(k, positions, spec.rope_theta)
        k_w, v_w, page_w, off_w = k, v, dst_page, dst_off
        if dp_size > 1:
            k_w = jax.lax.all_gather(k, "dp", axis=0, tiled=True)
            v_w = jax.lax.all_gather(v, "dp", axis=0, tiled=True)
            page_w = jax.lax.all_gather(dst_page, "dp", axis=0, tiled=True)
            off_w = jax.lax.all_gather(dst_off, "dp", axis=0, tiled=True)
        k_pages, v_pages = write_new_kv(
            k_pages, v_pages, k_w, v_w, page_w, off_w, layer=i, mesh=None
        )
        attn = paged_decode_attention_auto(
            q, k_pages[i], v_pages[i], block_tables, seq_lens, mesh=None
        )
        o = attn.reshape(Bm, -1) @ lp["wo"][i]
        if tp_size > 1:
            o = jax.lax.psum(o, "tp")
        x = x + o
        h = rms_norm(x, lp["mlp_norm"][i], spec.rms_eps)
        m = (jax.nn.silu(h @ lp["w_gate"][i]) * (h @ lp["w_up"][i])) @ lp[
            "w_down"
        ][i]
        if tp_size > 1:
            m = jax.lax.psum(m, "tp")
        x = x + m
    return x, k_pages, v_pages


def _stage_prefill(
    spec: ModelSpec,
    lp,
    x: jax.Array,  # [T, d]
    positions: jax.Array,  # [T]
    k_pages,
    v_pages,
    safe_pg: jax.Array,  # [n_pg] (trash-masked for bubbles)
    num_tokens: jax.Array,
    n_local: int,
    tp_size: int,
    page_size: int,
):
    """One stage's layers over the whole (cold) prompt: causal
    self-attention, page-tile KV writes — the pp form of
    models/llama.py prefill_forward_impl."""
    T = x.shape[0]
    hd = spec.head_dim

    def to_tiles(arr):  # pads to the pool width when lane-padded
        return page_tiles(arr, page_size, k_pages.shape[-1])

    for i in range(n_local):
        h = rms_norm(x, lp["attn_norm"][i], spec.rms_eps)
        q = (h @ lp["wq"][i]).reshape(T, -1, hd)
        k = (h @ lp["wk"][i]).reshape(T, -1, hd)
        v = (h @ lp["wv"][i]).reshape(T, -1, hd)
        q = rope(q, positions, spec.rope_theta)
        k = rope(k, positions, spec.rope_theta)
        k_pages = k_pages.at[i, safe_pg].set(to_tiles(k))
        v_pages = v_pages.at[i, safe_pg].set(to_tiles(v))
        attn = causal_attention(q, k, v, positions, num_tokens)
        o = attn.reshape(T, -1) @ lp["wo"][i]
        if tp_size > 1:
            o = jax.lax.psum(o, "tp")
        x = x + o
        h = rms_norm(x, lp["mlp_norm"][i], spec.rms_eps)
        m = (jax.nn.silu(h @ lp["w_gate"][i]) * (h @ lp["w_up"][i])) @ lp[
            "w_down"
        ][i]
        if tp_size > 1:
            m = jax.lax.psum(m, "tp")
        x = x + m
    return x, k_pages, v_pages


def _logits_local(spec: ModelSpec, pp_params, x, tp_size: int):
    """Final norm + lm head; head column-sharded over tp -> all-gather."""
    xn = rms_norm(x, pp_params["final_norm"], spec.rms_eps)
    head = (
        pp_params["embed"].T
        if spec.tie_embeddings
        else pp_params["lm_head"]
    )
    lg = (xn @ head).astype(jnp.float32)
    if tp_size > 1 and not spec.tie_embeddings:
        lg = jax.lax.all_gather(lg, "tp", axis=lg.ndim - 1, tiled=True)
    return lg


# ------------------------------------------------------------ pp decode


@partial(jax.jit, static_argnames=("spec", "mesh"), donate_argnums=(5, 6))
def pp_decode_step(
    spec: ModelSpec,
    pp_params: Params,
    tokens: jax.Array,  # [B] int32
    block_tables: jax.Array,  # [B, P]
    seq_lens: jax.Array,  # [B] incl. the new token
    k_pages,  # [L, pages, KH, page, D] pp/tp-sharded
    v_pages,
    active: jax.Array,  # [B] bool
    *,
    mesh: Mesh,
):
    """One decode step for the whole batch, pipelined over pp stages.

    Returns (logits [B, V], k_pages, v_pages). The batch divides into pp
    microbatches; bubbles write to the trash page.
    """
    S = mesh.shape["pp"]
    tp_size = mesh.shape["tp"]
    dp_size = mesh.shape["dp"]
    B = tokens.shape[0]
    if (B // dp_size) % S:
        raise ValueError(f"batch {B}/dp={dp_size} must divide pp={S}")
    if spec.num_layers % S:
        raise ValueError(f"layers {spec.num_layers} must divide pp={S}")
    n_local = spec.num_layers // S
    page_size = k_pages.shape[3]

    def body(emb, positions, block_tables, seq_lens, dst_page, dst_off,
             lp, fnorm, head, k_l, v_l):
        s = jax.lax.axis_index("pp")
        Bl = emb.shape[0]
        mb = Bl // S
        # [S, mb, ...] microbatch views
        embs = emb.reshape(S, mb, -1)
        pos_m = positions.reshape(S, mb)
        bt_m = block_tables.reshape(S, mb, -1)
        len_m = seq_lens.reshape(S, mb)
        pg_m = dst_page.reshape(S, mb)
        off_m = dst_off.reshape(S, mb)

        state = jnp.zeros_like(embs[0])
        outs = jnp.zeros((S, mb, embs.shape[-1]), embs.dtype)
        perm = [(i, (i + 1) % S) for i in range(S)]
        for t in range(2 * S - 1):  # static unroll; S is small
            j = t - s  # this stage's microbatch index at tick t
            jc = jnp.clip(j, 0, S - 1)
            valid = (j >= 0) & (j < S)
            x_in = jnp.where((s == 0) & (t < S), embs[jnp.clip(t, 0, S - 1)],
                             state)
            x_out, k_l, v_l = _stage_decode(
                spec, lp, x_in, pos_m[jc], k_l, v_l, bt_m[jc], len_m[jc],
                jnp.where(valid, pg_m[jc], TRASH_PAGE), off_m[jc],
                n_local, tp_size, dp_size,
            )
            done = (s == S - 1) & valid
            outs = outs.at[jc].set(
                jnp.where(done, x_out, outs[jc])
            )
            state = jax.lax.ppermute(x_out, "pp", perm)
        # final activations live on the last stage: broadcast over pp
        outs = jax.lax.psum(
            jnp.where(s == S - 1, outs, jnp.zeros_like(outs)), "pp"
        )
        x = outs.reshape(Bl, -1)
        lg = _logits_local(spec, {"final_norm": fnorm, "embed": head,
                                  "lm_head": head}, x, tp_size)
        return lg, k_l, v_l

    positions = seq_lens - 1
    page_idx = jnp.take_along_axis(
        block_tables, (positions // page_size)[:, None], axis=1
    )[:, 0]
    dst_page = jnp.where(active, page_idx, TRASH_PAGE)
    dst_off = positions % page_size
    emb = pp_params["embed"][tokens]
    head = (
        pp_params["embed"] if spec.tie_embeddings else pp_params["lm_head"]
    )

    shard = compat_shard_map(
        partial(body),
        mesh=mesh,
        in_specs=(
            P("dp", None),  # emb
            P("dp"),  # positions
            P("dp", None),  # block_tables
            P("dp"),  # seq_lens
            P("dp"),  # dst_page
            P("dp"),  # dst_off
            {  # stacked layers: pp x tp
                "attn_norm": P("pp", None),
                "wq": P("pp", None, "tp"),
                "wk": P("pp", None, "tp"),
                "wv": P("pp", None, "tp"),
                "wo": P("pp", "tp", None),
                "mlp_norm": P("pp", None),
                "w_gate": P("pp", None, "tp"),
                "w_up": P("pp", None, "tp"),
                "w_down": P("pp", "tp", None),
            },
            P(None),  # final_norm
            P(None, "tp") if not spec.tie_embeddings else P(None, None),
            P("pp", None, "tp", None, None),  # k_pages
            P("pp", None, "tp", None, None),
        ),
        out_specs=(
            P("dp", None),  # logits (replicated over pp/tp post-gather)
            P("pp", None, "tp", None, None),
            P("pp", None, "tp", None, None),
        ),
        check_vma=False,
    )
    logits, k_pages, v_pages = shard(
        emb, positions, block_tables, seq_lens, dst_page, dst_off,
        pp_params["layers"], pp_params["final_norm"], head,
        k_pages, v_pages,
    )
    return logits, k_pages, v_pages


# ------------------------------------------------------------ pp prefill


@partial(jax.jit, static_argnames=("spec", "mesh"), donate_argnums=(4, 5))
def pp_prefill(
    spec: ModelSpec,
    pp_params: Params,
    tokens: jax.Array,  # [T] int32 (page-aligned length)
    block_table: jax.Array,  # [max_pages_per_seq]
    k_pages,
    v_pages,
    num_tokens: jax.Array,  # scalar
    *,
    mesh: Mesh,
):
    """Cold-prompt prefill through the pp pipeline (one microbatch: pure
    fill/drain). Returns (last-token logits [V], k_pages, v_pages)."""
    S = mesh.shape["pp"]
    tp_size = mesh.shape["tp"]
    n_local = spec.num_layers // S
    T = tokens.shape[0]
    page_size = k_pages.shape[3]
    n_pg = T // page_size
    page_starts = jnp.arange(n_pg) * page_size
    pg_idx = block_table[page_starts // page_size]
    base_pg = jnp.where(page_starts < num_tokens, pg_idx, TRASH_PAGE)

    emb = pp_params["embed"][tokens]
    head = (
        pp_params["embed"] if spec.tie_embeddings else pp_params["lm_head"]
    )

    def body(emb, base_pg, num_tokens, lp, fnorm, head, k_l, v_l):
        s = jax.lax.axis_index("pp")
        positions = jnp.arange(T)
        state = jnp.zeros_like(emb)
        out = jnp.zeros_like(emb)
        perm = [(i, (i + 1) % S) for i in range(S)]
        for t in range(S):
            valid = t == s
            x_in = jnp.where((s == 0) & (t == 0), emb, state)
            x_out, k_l, v_l = _stage_prefill(
                spec, lp, x_in, positions, k_l, v_l,
                jnp.where(valid, base_pg, TRASH_PAGE), num_tokens,
                n_local, tp_size, page_size,
            )
            out = jnp.where((s == S - 1) & (t == S - 1), x_out, out)
            state = jax.lax.ppermute(x_out, "pp", perm)
        out = jax.lax.psum(
            jnp.where(s == S - 1, out, jnp.zeros_like(out)), "pp"
        )
        last = jnp.clip(num_tokens - 1, 0, T - 1)
        lg = _logits_local(spec, {"final_norm": fnorm, "embed": head,
                                  "lm_head": head}, out[last], tp_size)
        return lg, k_l, v_l

    layer_specs = {
        "attn_norm": P("pp", None),
        "wq": P("pp", None, "tp"),
        "wk": P("pp", None, "tp"),
        "wv": P("pp", None, "tp"),
        "wo": P("pp", "tp", None),
        "mlp_norm": P("pp", None),
        "w_gate": P("pp", None, "tp"),
        "w_up": P("pp", None, "tp"),
        "w_down": P("pp", "tp", None),
    }
    shard = compat_shard_map(
        body,
        mesh=mesh,
        in_specs=(
            P(), P(), P(), layer_specs, P(),
            P(None, "tp") if not spec.tie_embeddings else P(None, None),
            P("pp", None, "tp", None, None),
            P("pp", None, "tp", None, None),
        ),
        out_specs=(
            P(),
            P("pp", None, "tp", None, None),
            P("pp", None, "tp", None, None),
        ),
        check_vma=False,
    )
    return shard(
        emb, base_pg, num_tokens, pp_params["layers"],
        pp_params["final_norm"], head, k_pages, v_pages,
    )
