"""Device mesh construction for the engine.

Axes convention (used by all shardings in models/ and engine/):
  dp - data parallel (engine-level replica within one worker)
  pp - pipeline parallel (layer stages; parallel/pipeline.py)
  tp - tensor parallel (attention heads / MLP columns)
  ep - expert parallel (MoE experts; aliases tp devices unless distinct)
  sp - sequence/context parallel (ring attention)

On a TPU slice the default device order already follows the physical torus;
we fold it into the requested logical shape. Multi-host: every host calls
this with the same shape over jax.devices() (the global device list).
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh


def make_mesh(
    tp: int = 1,
    dp: int = 1,
    sp: int = 1,
    ep: int = 1,
    pp: int = 1,
    devices: list | None = None,
) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    need = tp * dp * sp * ep * pp
    if need > len(devices):
        raise ValueError(
            f"mesh needs {need} devices (dp={dp} pp={pp} sp={sp} ep={ep} "
            f"tp={tp}), have {len(devices)}"
        )
    # pp outermost after dp: stage boundaries land on the coarsest
    # interconnect hops; tp innermost rides the fastest ICI links
    arr = np.array(devices[:need]).reshape(dp, pp, sp, ep, tp)
    return Mesh(arr, ("dp", "pp", "sp", "ep", "tp"))
