"""Ring attention: causal self-attention with the sequence sharded over a
mesh axis (context/sequence parallelism for long prompts).

Net-new relative to the reference — it has no sequence parallelism anywhere
(SURVEY.md §2.3, grep-verified); long-context prefill on TPU needs it so
one prompt's attention can use a whole slice's HBM and FLOPs.

Design (the TPU-idiomatic form of Ring Attention, Liu et al. 2023): each of
the ``sp`` devices holds a contiguous chunk of Q/K/V along the token axis.
Every device computes blockwise attention of its local queries against the
K/V chunk it currently holds, accumulating with an online (flash-style)
softmax, while `jax.lax.ppermute` rotates the K/V chunks one hop around the
ring — ``sp`` steps total, each overlapping ICI transfer with compute.
Chunks are identified by origin, so absolute positions (and the causal
mask) stay exact. The output is bit-stable under resharding because the
accumulation order per query is fixed by origin index, not arrival time.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from jax.sharding import Mesh, PartitionSpec as P

from dynamo_tpu.ops.shard import shard_map as compat_shard_map

NEG_INF = -1e30


def _repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    return x if n_rep == 1 else jnp.repeat(x, n_rep, axis=-2)


def _ring_chunk(
    q: jax.Array,  # [Tl, H, D] local query chunk
    k: jax.Array,  # [Tl, KH, D] local key chunk
    v: jax.Array,  # [Tl, KH, D]
    *,
    sp: int,
    axis: str,
) -> jax.Array:
    Tl, H, D = q.shape
    n_rep = H // k.shape[1]
    idx = jax.lax.axis_index(axis)
    q_pos = idx * Tl + jnp.arange(Tl)  # absolute positions of local queries

    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    qf = q.astype(jnp.float32)
    acc = jnp.zeros((Tl, H, D), jnp.float32)
    m = jnp.full((H, Tl), NEG_INF, jnp.float32)  # running row max
    l = jnp.zeros((H, Tl), jnp.float32)  # running row sum

    perm = [(i, (i + 1) % sp) for i in range(sp)]
    kc, vc = k, v
    for step in range(sp):
        # after `step` rotations we hold the chunk originally on idx - step
        src = (idx - step) % sp
        k_pos = src * Tl + jnp.arange(Tl)
        kr = _repeat_kv(kc, n_rep).astype(jnp.float32)
        vr = _repeat_kv(vc, n_rep).astype(jnp.float32)
        logits = jnp.einsum("thd,shd->hts", qf, kr) * scale  # [H, Tl, Sl]
        mask = k_pos[None, :] <= q_pos[:, None]  # [Tl, Sl] causal
        logits = jnp.where(mask[None, :, :], logits, NEG_INF)
        # online softmax update (step 0 always contains the self-visible
        # diagonal, so m is finite from the first update onward)
        new_m = jnp.maximum(m, logits.max(axis=-1))
        corr = jnp.exp(m - new_m)  # [H, Tl]
        p = jnp.exp(logits - new_m[:, :, None])  # [H, Tl, Sl]
        l = l * corr + p.sum(axis=-1)
        acc = acc * corr.T[:, :, None] + jnp.einsum("hts,shd->thd", p, vr)
        m = new_m
        if step < sp - 1:
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)

    out = acc / jnp.maximum(l.T[:, :, None], 1e-30)
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,  # [T, H, D] (T divisible by mesh.shape[axis])
    k: jax.Array,  # [T, KH, D]
    v: jax.Array,  # [T, KH, D]
    *,
    mesh: Mesh,
    axis: str = "sp",
) -> jax.Array:
    """Causal self-attention, sequence sharded over ``mesh.shape[axis]``.

    Heads stay whole per device (compose with tp by head-sharding q/k/v
    outside). Padding must sit at the END of the sequence: padded keys have
    positions greater than every real query, so causality masks them.
    """
    sp = mesh.shape[axis]
    if sp == 1:
        from dynamo_tpu.ops.attention import causal_attention

        T = q.shape[0]
        return causal_attention(
            q, k, v, jnp.arange(T), jnp.asarray(T, jnp.int32)
        )
    if q.shape[0] % sp:
        raise ValueError(f"T={q.shape[0]} not divisible by {axis}={sp}")
    # compose with tensor parallelism: heads shard over "tp" (each GQA
    # group stays local), sequence over the ring axis
    tp = mesh.shape.get("tp", 1)
    head_axis = "tp" if tp > 1 and k.shape[1] % tp == 0 else None
    fn = partial(_ring_chunk, sp=sp, axis=axis)
    return compat_shard_map(
        fn,
        mesh=mesh,
        in_specs=(P(axis, head_axis, None),) * 3,
        out_specs=P(axis, head_axis, None),
        check_vma=False,
    )(q, k, v)
