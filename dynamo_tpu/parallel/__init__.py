"""Parallelism: mesh construction, ring attention, KV transfer.

Unlike the reference - where TP/PP/EP live inside third-party engines and
Dynamo only orchestrates (SURVEY.md section 2.3) - parallelism here is
first-class: the engine shards its own weights/caches over a
jax.sharding.Mesh, and sequence/context parallelism (ring attention, absent
from the reference entirely) is native.
"""

from dynamo_tpu.parallel.mesh import make_mesh

__all__ = ["make_mesh"]
