"""Multi-host bootstrap: one logical worker spanning several TPU hosts.

Role of the reference's engine multinode bootstrap (MPI world for TRT-LLM,
--dist-init-addr for SGLang; SURVEY §2.4 maps these to "JAX distributed
init (coordinator)"): every host of a multi-host slice runs the same
worker process, calls ``initialize_multihost`` before any jax use, and
jax.distributed wires the hosts into one runtime whose ``jax.devices()``
spans the full slice. Meshes built afterwards (parallel/mesh.py) then
shard across hosts over ICI/DCN automatically.

Leader identity (SURVEY §7 hard part (d)): only process 0 registers the
endpoint/model card — followers compute in the same SPMD programs but are
invisible to routers, mirroring KvbmLeader/Worker's single-identity model.
Env fallbacks: DYN_COORDINATOR, DYN_NUM_PROCESSES, DYN_PROCESS_ID (set by
the launcher / K8s indexed job).
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("dynamo.multihost")


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join the multi-host JAX runtime; no-op single-process when unset.

    Returns True when distributed init ran. Must be called before the
    first jax computation in the process.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "DYN_COORDINATOR"
    )
    if num_processes is None and os.environ.get("DYN_NUM_PROCESSES"):
        num_processes = int(os.environ["DYN_NUM_PROCESSES"])
    if process_id is None and os.environ.get("DYN_PROCESS_ID"):
        process_id = int(os.environ["DYN_PROCESS_ID"])

    if not coordinator_address or not num_processes or num_processes <= 1:
        return False

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info(
        "joined multi-host runtime: process %d/%d via %s (%d devices total)",
        jax.process_index(), num_processes, coordinator_address,
        jax.device_count(),
    )
    return True


def is_leader() -> bool:
    """Process 0 owns registration/serving; followers only compute."""
    import jax

    return jax.process_index() == 0
