"""Multi-head Latent Attention (MLA): the DeepSeek-V2/V3/R1 attention.

The reference serves DeepSeek-R1 through engine configs
(recipes/deepseek-r1/sglang-wideep/tep16p-dep16d-disagg.yaml) and leaves
MLA to the engine; here the engine is ours, so MLA is implemented
TPU-natively. What makes MLA special for serving:

- The KV cache stores ONE latent vector per token — ``kv_lora_rank``
  compressed dims plus a small decoupled-RoPE key (``qk_rope_head_dim``)
  SHARED across heads — instead of per-head K and V. For R1
  (128 heads, d_c=512, d_r=64) that is ~14x less KV memory than GQA at
  the same head count, which is why wide-EP decode fits at all.
- Decode runs in the ABSORBED form: q_nope folds through W_uk so scores
  are taken directly against cached latents, and the attention output is
  re-expanded through W_uv afterwards — per step the cache traffic is
  the latent stream, never materialized per-head K/V.

Paged cache layout: ``[L, num_pages, page_size, d_c + d_r]`` — no head
axis (the latent is shared), page-major like the GQA pool, and
compatible with the engine's page/block bookkeeping. Rows gather by
block table with plain XLA ops; MLA decode is far less gather-bound
than GQA (one row per token, not KH) so the Pallas treatment is not the
first bottleneck here.

The DeepSeek block composes MLA with the MoE FFN (models/moe.py) plus
``n_shared_experts`` always-on dense experts; the first
``first_k_dense`` layers use a plain dense MLP (DeepSeek's
first_k_dense_replace). RoPE is the standard half-split form, with YaRN
frequency correction when the spec configures it (DeepSeek-R1 ships
factor 40 / mscale 1 — llama.yarn_freqs, HF-parity semantics).

Parity contract: ``reference_forward`` computes the plain non-absorbed
attention; the paged prefill/decode must match it (tests/test_mla.py).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import ModelSpec
from dynamo_tpu.models.llama import (
    TRASH_PAGE, _logits, _replicate, rms_norm, rope_spec,
)
from dynamo_tpu.ops.quant import (
    QuantPool,
    gather_dequant_rows,
    init_quant_pool,
    is_quant,
    quant_append_rows,
    quant_page_tiles,
)

Params = dict[str, Any]

NEG_INF = -1e30


def latent_dim(spec: ModelSpec) -> int:
    return spec.kv_lora_rank + spec.qk_rope_head_dim


def softmax_scale(spec: ModelSpec) -> float:
    """MLA attention scale: 1/sqrt(dn+dr), times the YaRN mscale^2
    correction when the checkpoint ships mscale_all_dim (HF
    DeepseekV3Attention multiplies its scaling by
    yarn_get_mscale(factor, mscale_all_dim)^2 — R1: (0.1*ln(40)+1)^2)."""
    import math

    from dynamo_tpu.models.llama import yarn_get_mscale

    base = 1.0 / math.sqrt(spec.qk_nope_head_dim + spec.qk_rope_head_dim)
    if spec.rope_scaling_factor and spec.rope_mscale_all_dim:
        m = yarn_get_mscale(spec.rope_scaling_factor, spec.rope_mscale_all_dim)
        base *= m * m
    return base


# ---------------------------------------------------------------- init


def init_params(spec: ModelSpec, key: jax.Array) -> Params:
    """Random-init DeepSeek-family params (MLA + MoE/dense FFN)."""
    assert spec.kv_lora_rank > 0, "not an MLA spec"
    dtype = jnp.dtype(spec.dtype)
    d = spec.hidden_size
    H = spec.num_heads
    dn, dr, dv = spec.qk_nope_head_dim, spec.qk_rope_head_dim, spec.v_head_dim
    dc = spec.kv_lora_rank
    keys = iter(jax.random.split(key, 8 + spec.num_layers * 12))

    def dense(k, shape, scale=None):
        scale = scale or (1.0 / jnp.sqrt(shape[0]))
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    params: Params = {
        "embed": dense(next(keys), (spec.vocab_size, d), scale=0.02),
        "final_norm": jnp.ones((d,), dtype),
        "layers": [],
    }
    if not spec.tie_embeddings:
        params["lm_head"] = dense(next(keys), (d, spec.vocab_size))
    for li in range(spec.num_layers):
        layer: Params = {
            "attn_norm": jnp.ones((d,), dtype),
            "mlp_norm": jnp.ones((d,), dtype),
            "w_kv_a": dense(next(keys), (d, dc + dr)),
            "kv_norm": jnp.ones((dc,), dtype),
            "w_uk": dense(next(keys), (H, dc, dn), scale=1.0 / jnp.sqrt(dc)),
            "w_uv": dense(next(keys), (H, dc, dv), scale=1.0 / jnp.sqrt(dc)),
            "wo": dense(next(keys), (H * dv, d)),
        }
        if spec.q_lora_rank:
            layer["wq_a"] = dense(next(keys), (d, spec.q_lora_rank))
            layer["q_norm"] = jnp.ones((spec.q_lora_rank,), dtype)
            layer["wq_b"] = dense(
                next(keys), (spec.q_lora_rank, H * (dn + dr))
            )
        else:
            layer["wq"] = dense(next(keys), (d, H * (dn + dr)))
        if spec.num_experts and li >= spec.first_k_dense:
            from dynamo_tpu.models import moe

            layer["moe"] = moe.init_moe_layer(spec, next(keys))
            if spec.n_shared_experts:
                f = spec.moe_intermediate_size * spec.n_shared_experts
                layer["shared"] = {
                    "w_gate": dense(next(keys), (d, f)),
                    "w_up": dense(next(keys), (d, f)),
                    "w_down": dense(next(keys), (f, d)),
                }
        else:
            layer["w_gate"] = dense(next(keys), (d, spec.intermediate_size))
            layer["w_up"] = dense(next(keys), (d, spec.intermediate_size))
            layer["w_down"] = dense(next(keys), (spec.intermediate_size, d))
        params["layers"].append(layer)
    return params


def init_cache(
    spec: ModelSpec, num_pages: int, page_size: int, dtype=None,
    kv_dtype: str = "bf16",
) -> jax.Array:
    """Latent cache [L, num_pages, page_size, d_c + d_r] (page 0 = trash).
    ONE array — MLA has no separate K and V pools. ``kv_dtype="fp8"``
    allocates a QuantPool (ops/quant.py) with one bf16 scale per
    (layer, page, ROW): with no head axis the row is the natural scale
    unit, appends never requantize their neighbors, and the finer
    granularity keeps the absorbed-attention drift inside the tolerance
    goldens (a single per-page scale measured ~2x the greedy-token
    disagreement on CPU)."""
    dtype = dtype or jnp.dtype(spec.dtype)
    shape = (spec.num_layers, num_pages, page_size, latent_dim(spec))
    if kv_dtype == "fp8":
        return init_quant_pool(shape, 3)
    return jnp.zeros(shape, dtype)


def _set_latent_tiles(
    cache, li: int, safe_pg: jax.Array, tiles: jax.Array,
    valid_tok: jax.Array,  # [n_tiles, page] bool
):
    """Prefill latent page write for either cache form (the MLA analogue
    of llama._set_page_tiles; one scale per row, amax over the latent
    dim)."""
    if is_quant(cache):
        vals, s = quant_page_tiles(tiles, valid_tok[:, :, None], (2,))
        return QuantPool(
            cache.vals.at[li, safe_pg].set(vals),
            cache.scale.at[li, safe_pg].set(s),
        )
    return cache.at[li, safe_pg].set(tiles.astype(cache.dtype))


def _gather_rows_any(cache, li: int, block_table: jax.Array) -> jax.Array:
    """[num_pages, page, D] + [P] -> [P*page, D], dequantized when fp8."""
    if is_quant(cache):
        return gather_dequant_rows(cache.layer(li), block_table)
    return _gather_rows(cache[li], block_table)


def param_shardings(spec: ModelSpec, mesh: Mesh) -> Params:
    """TP shardings for MLA: the head axis is the parallel axis.

    The latent path (w_kv_a, kv_norm) is REPLICATED — the whole point of
    MLA is that the per-token latent is tiny and shared across heads, so
    every tp rank computes the full latent row locally (no collective)
    and per-head work (q projection, absorbed w_uk/w_uv, wo) shards over
    "tp". Experts shard over "ep" via moe_layer_shardings, matching the
    wide-EP layout the reference deploys DeepSeek-R1 with
    (recipes/deepseek-r1/sglang-wideep/tep16p-dep16d-disagg.yaml:63)."""

    def ns(*axes):
        return NamedSharding(mesh, P(*axes))

    layers = []
    for li in range(spec.num_layers):
        layer: Params = {
            "attn_norm": ns(),
            "mlp_norm": ns(),
            "w_kv_a": ns(),
            "kv_norm": ns(),
            "w_uk": ns("tp", None, None),  # heads
            "w_uv": ns("tp", None, None),
            "wo": ns("tp", None),  # row-parallel over flattened heads
        }
        if spec.q_lora_rank:
            layer["wq_a"] = ns()
            layer["q_norm"] = ns()
            layer["wq_b"] = ns(None, "tp")  # column (heads major)
        else:
            layer["wq"] = ns(None, "tp")
        if spec.num_experts and li >= spec.first_k_dense:
            from dynamo_tpu.models import moe

            layer["moe"] = moe.moe_layer_shardings(mesh, spec)
            if spec.n_shared_experts:
                layer["shared"] = {
                    "w_gate": ns(None, "tp"),
                    "w_up": ns(None, "tp"),
                    "w_down": ns("tp", None),
                }
        else:
            layer["w_gate"] = ns(None, "tp")
            layer["w_up"] = ns(None, "tp")
            layer["w_down"] = ns("tp", None)
        layers.append(layer)
    out = {
        "embed": ns(None, "tp"),
        "final_norm": ns(),
        "layers": layers,
    }
    if not spec.tie_embeddings:
        out["lm_head"] = ns(None, "tp")
    return out


def cache_shardings(mesh: Mesh, kv_dtype: str = "bf16"):
    """Latent cache [L, pages, page, d_c + d_r]: REPLICATED across the
    mesh. There is no head axis to split — the latent row is shared by
    every head — and at ~14x compression vs GQA the duplication is the
    cheap side of the trade (each rank attends against its local copy
    with zero gather collectives in the decode hot loop). Quantized
    caches replicate both leaves."""
    s = NamedSharding(mesh, P())
    return QuantPool(s, s) if kv_dtype == "fp8" else s


# --------------------------------------------------------------- pieces


def _q_heads(spec: ModelSpec, lp: Params, h: jax.Array, positions) -> tuple:
    """-> (q_nope [T, H, dn], q_rope [T, H, dr]) with RoPE applied."""
    T = h.shape[0]
    H, dn, dr = spec.num_heads, spec.qk_nope_head_dim, spec.qk_rope_head_dim
    if spec.q_lora_rank:
        q = rms_norm(h @ lp["wq_a"], lp["q_norm"], spec.rms_eps) @ lp["wq_b"]
    else:
        q = h @ lp["wq"]
    q = q.reshape(T, H, dn + dr)
    q_nope, q_rope = q[..., :dn], q[..., dn:]
    return q_nope, rope_spec(spec, q_rope, positions)


def _latent_row(spec: ModelSpec, lp: Params, h: jax.Array, positions):
    """-> cache rows [T, d_c + d_r]: normalized latent + roped shared key."""
    dc = spec.kv_lora_rank
    kv_a = h @ lp["w_kv_a"]
    c = rms_norm(kv_a[:, :dc], lp["kv_norm"], spec.rms_eps)
    k_r = rope_spec(spec, kv_a[:, None, dc:], positions)[:, 0]
    return jnp.concatenate([c, k_r], axis=-1)


def _absorbed_attention(
    spec: ModelSpec,
    lp: Params,
    q_nope: jax.Array,  # [T, H, dn]
    q_rope: jax.Array,  # [T, H, dr]
    rows: jax.Array,  # [S, d_c + d_r] cached latents (+ self rows)
    mask: jax.Array,  # [T, S] bool
) -> jax.Array:
    """Latent-space attention -> per-head outputs [T, H, dv]."""
    dc = spec.kv_lora_rank
    scale = jnp.asarray(softmax_scale(spec), jnp.float32)
    c, k_r = rows[:, :dc], rows[:, dc:]
    # absorb W_uk: q_lat[t,h,:] = q_nope[t,h,:] @ w_uk[h].T  -> [T, H, dc]
    q_lat = jnp.einsum("thn,hcn->thc", q_nope.astype(jnp.float32),
                       lp["w_uk"].astype(jnp.float32))
    scores = (
        jnp.einsum("thc,sc->ths", q_lat, c.astype(jnp.float32))
        + jnp.einsum("thr,sr->ths", q_rope.astype(jnp.float32),
                     k_r.astype(jnp.float32))
    ) * scale
    scores = jnp.where(mask[:, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    o_lat = jnp.einsum("ths,sc->thc", probs, c.astype(jnp.float32))
    return jnp.einsum("thc,hcv->thv", o_lat,
                      lp["w_uv"].astype(jnp.float32))


def _ffn(spec: ModelSpec, li: int, lp: Params, x: jax.Array) -> jax.Array:
    if "moe" in lp:
        from dynamo_tpu.models import moe

        out = moe.moe_mlp(spec, lp["moe"], x)
        if "shared" in lp:
            sh = lp["shared"]
            out = out + (
                jax.nn.silu(x @ sh["w_gate"]) * (x @ sh["w_up"])
            ) @ sh["w_down"]
        return out
    return (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]


# ------------------------------------------------------------- reference


def reference_forward(
    spec: ModelSpec, params: Params, tokens: jax.Array
) -> jax.Array:
    """Plain NON-absorbed MLA forward (per-head K/V materialized) — the
    numerical ground truth the paged/absorbed paths must match."""
    T = tokens.shape[0]
    positions = jnp.arange(T)
    x = params["embed"][tokens]
    dn = spec.qk_nope_head_dim
    scale = jnp.asarray(softmax_scale(spec), jnp.float32)
    mask = positions[:, None] >= positions[None, :]
    for li, lp in enumerate(params["layers"]):
        h = rms_norm(x, lp["attn_norm"], spec.rms_eps)
        q_nope, q_rope = _q_heads(spec, lp, h, positions)
        rows = _latent_row(spec, lp, h, positions)
        c, k_r = rows[:, : spec.kv_lora_rank], rows[:, spec.kv_lora_rank:]
        k_nope = jnp.einsum("sc,hcn->shn", c.astype(jnp.float32),
                            lp["w_uk"].astype(jnp.float32))
        v = jnp.einsum("sc,hcv->shv", c.astype(jnp.float32),
                       lp["w_uv"].astype(jnp.float32))
        scores = (
            jnp.einsum("thn,shn->ths", q_nope.astype(jnp.float32), k_nope)
            + jnp.einsum("thr,sr->ths", q_rope.astype(jnp.float32),
                         k_r.astype(jnp.float32))
        ) * scale
        scores = jnp.where(mask[:, None, :], scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("ths,shv->thv", probs, v)
        x = x + attn.reshape(T, -1).astype(x.dtype) @ lp["wo"]
        hh = rms_norm(x, lp["mlp_norm"], spec.rms_eps)
        x = x + _ffn(spec, li, lp, hh)
    return _logits_all(spec, params, x)


def _logits_all(spec, params, x):
    xn = rms_norm(x, params["final_norm"], spec.rms_eps)
    head = params["embed"].T if spec.tie_embeddings else params["lm_head"]
    return (xn @ head).astype(jnp.float32)


# ----------------------------------------------------------------- paged


def _gather_rows(cache_l: jax.Array, block_table: jax.Array) -> jax.Array:
    """[num_pages, page, D] + [P] -> [P*page, D]."""
    rows = cache_l[block_table]  # [P, page, D]
    P, page, D = rows.shape
    return rows.reshape(P * page, D)


def prefill_forward_impl(
    spec: ModelSpec,
    params: Params,
    tokens: jax.Array,  # [T_pad]
    block_table: jax.Array,  # [max_pages_per_seq]
    start_pos: jax.Array,  # scalar (page-aligned)
    cache: jax.Array,  # [L, pages, page, D] (donated)
    num_tokens: jax.Array,  # scalar
    mesh: Mesh | None = None,  # static: replicate logits across the mesh
) -> tuple[jax.Array, jax.Array]:
    """One prompt; writes latent rows page-granularly; returns
    (last_logits, cache). Mirrors llama.prefill_forward_impl."""
    T = tokens.shape[0]
    idx = jnp.arange(T)
    positions = start_pos + idx
    page_size = cache.shape[2]
    n_pg = T // page_size
    page_starts = start_pos + jnp.arange(n_pg) * page_size
    pg_idx = block_table[page_starts // page_size]
    safe_pg = jnp.where(
        page_starts < start_pos + num_tokens, pg_idx, TRASH_PAGE
    )
    valid_tok = (idx < num_tokens).reshape(n_pg, page_size)
    x = params["embed"][tokens]
    kv_len = start_pos + num_tokens
    max_ctx = block_table.shape[0] * page_size
    ctx_pos = jnp.arange(max_ctx)
    for li, lp in enumerate(params["layers"]):
        h = rms_norm(x, lp["attn_norm"], spec.rms_eps)
        q_nope, q_rope = _q_heads(spec, lp, h, positions)
        new_rows = _latent_row(spec, lp, h, positions)
        cache = _set_latent_tiles(
            cache, li, safe_pg,
            new_rows.reshape(n_pg, page_size, -1), valid_tok,
        )
        rows = _gather_rows_any(cache, li, block_table)  # [max_ctx, D]
        if is_quant(cache):
            # exact in-flight rows over the quantized read-back (the XLA
            # mirror of the fused GQA kernel's analytic new-token merge)
            rows = rows.at[positions].set(
                new_rows.astype(rows.dtype), mode="drop"
            )
        mask = (ctx_pos[None, :] <= positions[:, None]) & (
            ctx_pos[None, :] < kv_len
        )
        attn = _absorbed_attention(spec, lp, q_nope, q_rope, rows, mask)
        x = x + attn.reshape(T, -1).astype(x.dtype) @ lp["wo"]
        hh = rms_norm(x, lp["mlp_norm"], spec.rms_eps)
        x = x + _ffn(spec, li, lp, hh)
    last = jnp.clip(num_tokens - 1, 0, T - 1)
    return _replicate(_logits_all(spec, params, x)[last], mesh), cache


prefill_forward = jax.jit(
    prefill_forward_impl, static_argnums=(0,),
    static_argnames=("mesh",), donate_argnums=(5,)
)


def prefill_forward_batch_impl(
    spec: ModelSpec,
    params: Params,
    tokens: jax.Array,  # [N, T_pad]
    block_tables: jax.Array,  # [N, max_pages_per_seq]
    start_pos: jax.Array,  # [N] (page-aligned)
    cache: jax.Array,  # donated
    num_tokens: jax.Array,  # [N]
    mesh: Mesh | None = None,  # static
) -> tuple[jax.Array, jax.Array]:
    """N prompts in ONE dispatch — MLA's packed-prefill admission path
    (mirrors llama.prefill_forward_batch_impl: matmuls batch over
    [N, T, d], the latent write is one page-tile scatter, absorbed
    attention runs per prompt over its own table). Returns
    (last_logits [N, V], cache)."""
    N, T = tokens.shape
    page_size = cache.shape[2]
    idx = jnp.arange(T)
    positions = start_pos[:, None] + idx[None, :]  # [N, T]
    n_pg = T // page_size
    page_starts = start_pos[:, None] + (
        jnp.arange(n_pg) * page_size
    )[None, :]  # [N, n_pg]
    pg_idx_raw = jnp.take_along_axis(
        block_tables, page_starts // page_size, axis=1
    )
    valid_pg = page_starts < (start_pos + num_tokens)[:, None]
    safe_pg = jnp.where(valid_pg, pg_idx_raw, TRASH_PAGE).reshape(N * n_pg)

    x = params["embed"][tokens]  # [N, T, d]
    kv_len = start_pos + num_tokens  # [N]
    max_ctx = block_tables.shape[1] * page_size
    ctx_pos = jnp.arange(max_ctx)
    for li, lp in enumerate(params["layers"]):
        h = rms_norm(x, lp["attn_norm"], spec.rms_eps)
        q_nope, q_rope = jax.vmap(
            lambda hh, pos: _q_heads(spec, lp, hh, pos)
        )(h, positions)  # [N, T, H, dn] / [N, T, H, dr]
        new_rows = jax.vmap(
            lambda hh, pos: _latent_row(spec, lp, hh, pos)
        )(h, positions)  # [N, T, D]
        cache = _set_latent_tiles(
            cache, li, safe_pg,
            new_rows.reshape(N * n_pg, page_size, -1),
            (idx[None, :] < num_tokens[:, None]).reshape(
                N * n_pg, page_size
            ),
        )

        def one_attn(qn, qr, bt, pos, kvl, nr, cache=cache, li=li, lp=lp):
            rows = _gather_rows_any(cache, li, bt)  # [max_ctx, D]
            if is_quant(cache):
                rows = rows.at[pos].set(nr.astype(rows.dtype), mode="drop")
            mask = (ctx_pos[None, :] <= pos[:, None]) & (
                ctx_pos[None, :] < kvl
            )
            return _absorbed_attention(spec, lp, qn, qr, rows, mask)

        attn = jax.vmap(one_attn)(
            q_nope, q_rope, block_tables, positions, kv_len, new_rows
        )  # [N, T, H, dv]
        x = x + attn.reshape(N, T, -1).astype(x.dtype) @ lp["wo"]
        hh = rms_norm(x, lp["mlp_norm"], spec.rms_eps)
        x = x + _ffn(spec, li, lp, hh.reshape(N * T, -1)).reshape(N, T, -1)

    last = jnp.clip(num_tokens - 1, 0, T - 1)  # [N]
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
    return _replicate(_logits_all(spec, params, x_last), mesh), cache


prefill_forward_batch = jax.jit(
    prefill_forward_batch_impl, static_argnums=(0,),
    static_argnames=("mesh",), donate_argnums=(5,)
)


def verify_forward_impl(
    spec: ModelSpec,
    params: Params,
    tokens: jax.Array,  # [N, W] int32: [fed_token, draft...] per row
    block_tables: jax.Array,  # [N, max_pages_per_seq]
    start_pos: jax.Array,  # [N]: cache length before the fed token
    cache: jax.Array,  # donated
    num_tokens: jax.Array,  # [N] valid tokens per row (0 = padded row)
    mesh: Mesh | None = None,  # static
    allowed: jax.Array | None = None,  # [N, W, V] bool: guided masks
) -> tuple[jax.Array, jax.Array]:
    """Speculative-verify forward for MLA (mirrors llama.verify_forward):
    token-granular latent writes — a verify starts mid-page, so the
    page-tile invariant of prefill does not hold — and the target's
    greedy argmax at all W positions, returned as [N, W] int32 so only
    token ids cross to the host. Returns (targets, cache)."""
    N, W = tokens.shape
    page_size = cache.shape[2]
    idx = jnp.arange(W)
    positions = start_pos[:, None] + idx[None, :]  # [N, W]
    valid = idx[None, :] < num_tokens[:, None]
    pg_idx_raw = jnp.take_along_axis(
        block_tables, positions // page_size, axis=1
    )
    safe_pg = jnp.where(valid, pg_idx_raw, TRASH_PAGE).reshape(N * W)
    offs = (positions % page_size).reshape(N * W)

    x = params["embed"][tokens]  # [N, W, d]
    kv_len = start_pos + num_tokens  # [N]
    max_ctx = block_tables.shape[1] * page_size
    ctx_pos = jnp.arange(max_ctx)
    for li, lp in enumerate(params["layers"]):
        h = rms_norm(x, lp["attn_norm"], spec.rms_eps)
        q_nope, q_rope = jax.vmap(
            lambda hh, pos: _q_heads(spec, lp, hh, pos)
        )(h, positions)  # [N, W, H, dn] / [N, W, H, dr]
        new_rows = jax.vmap(
            lambda hh, pos: _latent_row(spec, lp, hh, pos)
        )(h, positions)  # [N, W, D]
        if is_quant(cache):
            # per-row scales make this a plain scatter: every (page,
            # offset) slot owns its scale, so same-page siblings never
            # clash (unlike the GQA page RMW)
            cache = quant_append_rows(
                cache, new_rows.reshape(N * W, -1), safe_pg, offs, li
            )
        else:
            cache = cache.at[li, safe_pg, offs].set(
                new_rows.reshape(N * W, -1).astype(cache.dtype)
            )

        def one_attn(qn, qr, bt, pos, kvl, nr, cache=cache, li=li, lp=lp):
            rows = _gather_rows_any(cache, li, bt)  # [max_ctx, D]
            if is_quant(cache):
                # exact verify-window rows (llama mirror)
                rows = rows.at[pos].set(nr.astype(rows.dtype), mode="drop")
            mask = (ctx_pos[None, :] <= pos[:, None]) & (
                ctx_pos[None, :] < kvl
            )
            return _absorbed_attention(spec, lp, qn, qr, rows, mask)

        attn = jax.vmap(one_attn)(
            q_nope, q_rope, block_tables, positions, kv_len, new_rows
        )  # [N, W, H, dv]
        x = x + attn.reshape(N, W, -1).astype(x.dtype) @ lp["wo"]
        hh = rms_norm(x, lp["mlp_norm"], spec.rms_eps)
        x = x + _ffn(spec, li, lp, hh.reshape(N * W, -1)).reshape(N, W, -1)

    logits = _logits_all(spec, params, x)  # [N, W, V]
    if allowed is not None:
        # guided x spec: masked verify logits keep the correction token
        # on-grammar even when every draft is rejected (llama mirror)
        logits = jnp.where(allowed, logits, NEG_INF)
    targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return _replicate(targets, mesh), cache


verify_forward = jax.jit(
    verify_forward_impl, static_argnums=(0,),
    static_argnames=("mesh",), donate_argnums=(5,)
)


def decode_forward_impl(
    spec: ModelSpec,
    params: Params,
    tokens: jax.Array,  # [B]
    block_tables: jax.Array,  # [B, P]
    seq_lens: jax.Array,  # [B] incl. the new token
    cache: jax.Array,  # donated
    active: jax.Array,  # [B] bool
    mesh: Mesh | None = None,  # static
) -> tuple[jax.Array, jax.Array]:
    """One decode step (absorbed latent attention); returns (logits, cache)."""
    B = tokens.shape[0]
    page_size = cache.shape[2]
    positions = seq_lens - 1
    page_idx = jnp.take_along_axis(
        block_tables, (positions // page_size)[:, None], axis=1
    )[:, 0]
    safe_page = jnp.where(active, page_idx, TRASH_PAGE)
    offset = positions % page_size
    max_ctx = block_tables.shape[1] * page_size
    ctx_pos = jnp.arange(max_ctx)
    x = params["embed"][tokens]
    for li, lp in enumerate(params["layers"]):
        h = rms_norm(x, lp["attn_norm"], spec.rms_eps)
        q_nope, q_rope = _q_heads(spec, lp, h, positions)
        new_rows = _latent_row(spec, lp, h, positions)  # [B, D]
        if is_quant(cache):
            cache = quant_append_rows(
                cache, new_rows, safe_page, offset, li
            )
        else:
            cache = cache.at[li, safe_page, offset].set(
                new_rows.astype(cache.dtype)
            )
        rows = jax.vmap(
            lambda bt, cache=cache, li=li: _gather_rows_any(cache, li, bt)
        )(block_tables)  # [B, max_ctx, D]
        if is_quant(cache):
            # exact new-token overlay: the decode query's own latent row
            # (its strongest attention target) never pays fp8 error
            max_ctx_i = rows.shape[1]
            rows = rows.at[
                jnp.arange(B), jnp.clip(positions, 0, max_ctx_i - 1)
            ].set(new_rows.astype(rows.dtype))
        mask = ctx_pos[None, :] < seq_lens[:, None]  # [B, max_ctx]
        attn = jax.vmap(
            lambda qn, qr, r, m: _absorbed_attention(
                spec, lp, qn[None], qr[None], r, m[None]
            )[0]
        )(q_nope, q_rope, rows, mask)
        x = x + attn.reshape(B, -1).astype(x.dtype) @ lp["wo"]
        hh = rms_norm(x, lp["mlp_norm"], spec.rms_eps)
        x = x + _ffn(spec, li, lp, hh)
    return _replicate(_logits_all(spec, params, x), mesh), cache


decode_forward = jax.jit(
    decode_forward_impl, static_argnums=(0,),
    static_argnames=("mesh",), donate_argnums=(5,)
)


def decode_steps_impl(
    spec: ModelSpec,
    params: Params,
    tokens: jax.Array,
    block_tables: jax.Array,
    seq_lens: jax.Array,
    cache: jax.Array,
    active: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
    seeds: jax.Array,
    steps: jax.Array,
    n_steps: int = 1,
    n_logprobs: int = 0,  # static: 0=off, N=sampled+top-N logprobs
    mesh: Mesh | None = None,  # static
    allowed: jax.Array | None = None,  # [B, V] bool: guided token masks
):
    """Fused multi-step MLA decode + on-device sampling (the serving hot
    loop; mirrors llama.decode_steps for the GQA family, including the
    logprob surface)."""
    from dynamo_tpu.engine.sampling import sample_tokens, token_logprobs

    B = tokens.shape[0]
    out0 = jnp.zeros((B, n_steps), jnp.int32)
    lp0 = jnp.zeros((B, n_steps), jnp.float32)
    ti0 = jnp.zeros((B, n_steps, max(n_logprobs, 1)), jnp.int32)
    tv0 = jnp.zeros((B, n_steps, max(n_logprobs, 1)), jnp.float32)

    def body(i, carry):
        toks, lens, cache, out, lp, ti, tv = carry
        logits, cache = decode_forward_impl(
            spec, params, toks, block_tables, lens, cache, active,
            mesh=mesh,
        )
        if allowed is not None:
            logits = jnp.where(allowed, logits, NEG_INF)
        nxt = sample_tokens(logits, temperature, top_k, top_p, seeds,
                            steps + i)
        nxt = jnp.where(active, nxt, toks)
        out = out.at[:, i].set(nxt)
        if n_logprobs > 0:
            picked, top_i, top_v = token_logprobs(logits, nxt, n_logprobs)
            lp = lp.at[:, i].set(picked)
            ti = ti.at[:, i].set(top_i)
            tv = tv.at[:, i].set(top_v)
        return (nxt, lens + active.astype(jnp.int32), cache, out, lp, ti, tv)

    _t, _l, cache, out, lp, ti, tv = jax.lax.fori_loop(
        0, n_steps, body,
        (tokens, seq_lens, cache, out0, lp0, ti0, tv0),
    )
    out = _replicate(out, mesh)
    if n_logprobs > 0:
        return (out, _replicate(lp, mesh), _replicate(ti, mesh),
                _replicate(tv, mesh), cache)
    return out, cache


decode_steps = jax.jit(
    decode_steps_impl, static_argnums=(0,),
    static_argnames=("n_steps", "n_logprobs", "mesh"),
    # donate the latent cache: without this every MLA decode burst
    # COPIED the whole cache for its in-place page writes (the donation
    # audit in tests/test_donation.py caught exactly this)
    donate_argnums=(5,),
)


# ------------------------------------------------------------- embeddings


def embed_forward_impl(
    spec: ModelSpec,
    params: Params,
    tokens: jax.Array,  # [T_pad] int32 (padded)
    num_tokens: jax.Array,  # scalar: real token count
) -> jax.Array:
    """Sequence embedding for the MLA family: mean-pooled final-norm
    hidden states over the real tokens, L2-normalized (mirrors
    llama.embed_forward_impl — the /v1/embeddings surface)."""
    T = tokens.shape[0]
    positions = jnp.arange(T)
    x = params["embed"][tokens]
    mask2d = (positions[:, None] >= positions[None, :]) & (
        positions[None, :] < num_tokens
    )
    for li, lp in enumerate(params["layers"]):
        h = rms_norm(x, lp["attn_norm"], spec.rms_eps)
        q_nope, q_rope = _q_heads(spec, lp, h, positions)
        rows = _latent_row(spec, lp, h, positions)
        attn = _absorbed_attention(spec, lp, q_nope, q_rope, rows, mask2d)
        x = x + attn.reshape(T, -1).astype(x.dtype) @ lp["wo"]
        hh = rms_norm(x, lp["mlp_norm"], spec.rms_eps)
        x = x + _ffn(spec, li, lp, hh)
    xn = rms_norm(x, params["final_norm"], spec.rms_eps).astype(jnp.float32)
    valid = (positions < num_tokens)[:, None].astype(jnp.float32)
    pooled = (xn * valid).sum(axis=0) / jnp.maximum(valid.sum(), 1.0)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled), 1e-9)


embed_forward = jax.jit(embed_forward_impl, static_argnums=(0,))
