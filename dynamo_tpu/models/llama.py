"""Llama-family transformer in pure JAX with paged KV cache + TP shardings.

Functional core: ``init_params`` builds the weight pytree (randomly - this
environment has no model downloads; loading real safetensors goes through
``load_params`` when files are present), ``prefill_forward`` and
``decode_forward`` are the two jitted entry points. Tensor parallelism is
megatron-style, expressed as NamedShardings on the weights (attention heads
and MLP hidden column-sharded, output projections row-sharded) so XLA's SPMD
partitioner inserts the collectives; activations get light
``with_sharding_constraint`` guidance.

Page 0 of the KV cache is the trash page: padded token positions scatter
there, so static-shape prefill never corrupts live pages.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import ModelSpec
from dynamo_tpu.ops.attention import (
    causal_attention,
    decode_update_attention,
    gather_ctx,
    gather_pages,
    page_tiles,
)
from dynamo_tpu.ops.quant import (
    QuantPool,
    init_quant_pool,
    is_quant,
    pack_pages,
    quant_page_tiles,
    unpack_pages,
)

TRASH_PAGE = 0  # reserved page index for padded-position scatters

Params = dict[str, Any]


# ---------------------------------------------------------------- init


def init_params(spec: ModelSpec, key: jax.Array) -> Params:
    """Random init (serving-scale weights come from load_params)."""
    dtype = jnp.dtype(spec.dtype)
    d, hd = spec.hidden_size, spec.head_dim
    nh, nkv = spec.num_heads, spec.num_kv_heads
    keys = iter(jax.random.split(key, 4 + spec.num_layers * 8))

    def dense(k, shape, scale=None):
        scale = scale or (1.0 / jnp.sqrt(shape[0]))
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    params: Params = {
        "embed": dense(next(keys), (spec.vocab_size, d), scale=0.02),
        "final_norm": jnp.ones((d,), dtype),
        "layers": [],
    }
    if not spec.tie_embeddings:
        params["lm_head"] = dense(next(keys), (d, spec.vocab_size))
    for _ in range(spec.num_layers):
        layer = {
            "attn_norm": jnp.ones((d,), dtype),
            "wq": dense(next(keys), (d, nh * hd)),
            "wk": dense(next(keys), (d, nkv * hd)),
            "wv": dense(next(keys), (d, nkv * hd)),
            "wo": dense(next(keys), (nh * hd, d)),
            "mlp_norm": jnp.ones((d,), dtype),
        }
        if spec.attn_bias:
            layer.update(
                bq=jnp.zeros((nh * hd,), dtype),
                bk=jnp.zeros((nkv * hd,), dtype),
                bv=jnp.zeros((nkv * hd,), dtype),
                bo=jnp.zeros((d,), dtype),
            )
        if spec.attn_sinks:
            layer["sinks"] = jnp.zeros((nh,), dtype)
        if spec.num_experts:
            from dynamo_tpu.models import moe

            layer["moe"] = moe.init_moe_layer(spec, next(keys))
        else:
            layer.update(
                w_gate=dense(next(keys), (d, spec.intermediate_size)),
                w_up=dense(next(keys), (d, spec.intermediate_size)),
                w_down=dense(next(keys), (spec.intermediate_size, d)),
            )
        params["layers"].append(layer)
    return params


def param_shardings(spec: ModelSpec, mesh: Mesh) -> Params:
    """Megatron TP shardings over mesh axis "tp"."""

    def ns(*axes):
        return NamedSharding(mesh, P(*axes))

    layer = {
        "attn_norm": ns(),
        "wq": ns(None, "tp"),  # column (heads)
        "wk": ns(None, "tp"),
        "wv": ns(None, "tp"),
        "wo": ns("tp", None),  # row
        "mlp_norm": ns(),
    }
    if spec.attn_bias:
        layer.update(bq=ns("tp"), bk=ns("tp"), bv=ns("tp"), bo=ns())
    if spec.attn_sinks:
        layer["sinks"] = ns("tp")  # per-query-head, rides the head shards
    if spec.num_experts:
        from dynamo_tpu.models import moe

        layer["moe"] = moe.moe_layer_shardings(mesh, spec)
    else:
        layer.update(
            w_gate=ns(None, "tp"),
            w_up=ns(None, "tp"),
            w_down=ns("tp", None),
        )
    out = {
        "embed": ns(None, "tp"),
        "final_norm": ns(),
        "layers": [dict(layer) for _ in range(spec.num_layers)],
    }
    if not spec.tie_embeddings:
        out["lm_head"] = ns(None, "tp")
    return out


def cache_shardings(
    mesh: Mesh, kv_dtype: str = "bf16"
) -> tuple[Any, Any]:
    """KV pages [L, pages, kv_heads, page_size, D]: shard kv_heads on tp.
    Quantized pools shard the scale leaf [L, pages, KH] on the same head
    axis, so device_put with the QuantPool of shardings keeps values and
    scales co-located per shard."""
    s = NamedSharding(mesh, P(None, None, "tp", None, None))
    if kv_dtype == "fp8":
        qs = QuantPool(s, NamedSharding(mesh, P(None, None, "tp")))
        return qs, qs
    return s, s


def init_cache(
    spec: ModelSpec, num_pages: int, page_size: int, dtype=None,
    kv_dtype: str = "bf16",
) -> tuple[jax.Array, jax.Array]:
    """K and V page arrays [L, num_pages, kv_heads, page_size, head_dim].

    PAGE-MAJOR layout: one page's KV for ALL heads is a single contiguous
    [kv_heads, page_size, head_dim] block, so the decode kernels move a
    page with ONE DMA descriptor. (The previous head-major layout made the
    same slice a strided copy that expands to kv_heads descriptors — and
    decode attention is DMA-descriptor-bound, not bandwidth-bound: see
    ops/pallas/paged_attention_v3.py.) ``num_pages`` must already include
    the trash page (index 0).

    ``kv_dtype="fp8"`` allocates QuantPools instead (ops/quant.py): fp8
    values + bf16 per-page/head scales — half the HBM footprint and half
    the decode read traffic; every writer quantizes, every reader
    dequantizes, and the tolerance goldens (tests/test_quant_goldens.py)
    bound the numeric drift.
    """
    from dynamo_tpu.ops.attention import pool_head_dim

    # The pool head dim may exceed spec.head_dim (pool_head_dim: zero-pad
    # to the 128-lane tile so lane-misaligned heads like gpt-oss D=64
    # keep the Mosaic DMA kernels). Writers pad rows, readers slice —
    # exact for attention; see ops/attention.pool_head_dim.
    dtype = dtype or jnp.dtype(spec.dtype)
    pool_d = pool_head_dim(spec.head_dim)
    shape = (spec.num_layers, num_pages, spec.num_kv_heads, page_size,
             pool_d)
    if pool_d != spec.head_dim:
        import logging
        import math

        mib = 2 * math.prod(shape) * jnp.dtype(dtype).itemsize / 2**20
        logging.getLogger(__name__).info(
            "KV pool lane-padded for Mosaic DMA: head_dim %d -> %d "
            "(%.0f MiB total, %.2fx the unpadded pool; DYNAMO_POOL_PAD=0 "
            "to disable)", spec.head_dim, pool_d, mib,
            pool_d / spec.head_dim,
        )
    if kv_dtype == "fp8":
        # scale per (layer, page, kv_head): the append-time amax rides
        # the same page granularity every kernel DMAs at
        return init_quant_pool(shape, 3), init_quant_pool(shape, 3)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def _set_page_tiles(
    pool, li: int, safe_pg: jax.Array, arr: jax.Array, page_size: int,
    valid_tok: jax.Array,  # [n_tiles, page] bool (True = real token)
):
    """Prefill page write for either pool form: plain pools scatter the
    tiles as-is; QuantPools zero the padded rows, take one amax scale per
    (page, head), and scatter fp8 values + scales. ``valid_tok`` marks
    real tokens — garbage in a partial tail page must not inflate the
    page scale (it is masked from attention and requantized over as
    decode appends land)."""
    tiles = page_tiles(arr, page_size, pool.shape[-1])
    if is_quant(pool):
        vals, s = quant_page_tiles(
            tiles, valid_tok[:, None, :, None], (2, 3)
        )
        return QuantPool(
            pool.vals.at[li, safe_pg].set(vals),
            pool.scale.at[li, safe_pg].set(s),
        )
    return pool.at[li, safe_pg].set(tiles)


# ---------------------------------------------------------------- layers


def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def yarn_get_mscale(scale: float, m: float = 1.0) -> float:
    """HF yarn_get_mscale: the single source for the YaRN attention
    temperature formula (shared by yarn_freqs and mla.softmax_scale)."""
    import math

    return 0.1 * m * math.log(scale) + 1.0 if scale > 1 else 1.0


def yarn_freqs(spec: ModelSpec, dim: int):
    """YaRN-corrected inverse frequencies + cos/sin attention factor.

    Returns ``(inv_freq [dim//2] | None, attention_factor)``; None = no
    scaling configured. Semantics match HF ``_compute_yarn_parameters``
    (transformers modeling_rope_utils) so checkpoints that ship YaRN
    configs — gpt-oss (factor 32, truncate off) and DeepSeek-R1 (factor
    40, mscale 1) — reproduce HF numerics exactly."""
    import math

    import numpy as np

    if not spec.rope_scaling_factor:
        return None, 1.0
    base, factor = spec.rope_theta, spec.rope_scaling_factor
    orig = spec.rope_orig_max_pos
    half = dim // 2
    pos_freqs = base ** (np.arange(0, half, dtype=np.float64) * 2 / dim)
    inv_extra = 1.0 / pos_freqs
    inv_inter = 1.0 / (factor * pos_freqs)

    def corr_dim(n_rot: float) -> float:
        return (dim * math.log(orig / (n_rot * 2 * math.pi))) / (
            2 * math.log(base)
        )

    low = corr_dim(spec.rope_beta_fast)
    high = corr_dim(spec.rope_beta_slow)
    if spec.rope_truncate:
        low, high = math.floor(low), math.ceil(high)
    low, high = max(low, 0), min(high, dim - 1)
    if low == high:
        high += 0.001
    ramp = np.clip(
        (np.arange(half, dtype=np.float64) - low) / (high - low), 0, 1
    )
    ext_factor = 1.0 - ramp
    inv = inv_inter * (1 - ext_factor) + inv_extra * ext_factor
    if spec.rope_mscale and spec.rope_mscale_all_dim:
        att = yarn_get_mscale(factor, spec.rope_mscale) / yarn_get_mscale(
            factor, spec.rope_mscale_all_dim
        )
    else:
        att = yarn_get_mscale(factor)
    return inv.astype(np.float32), float(att)


def rope(
    x: jax.Array, positions: jax.Array, theta: float,
    *, inv_freq=None, scale: float = 1.0,
) -> jax.Array:
    """Rotary embedding. x: [T, heads, D], positions: [T]. ``inv_freq``
    overrides the plain theta schedule (YaRN); ``scale`` multiplies the
    rotated output (YaRN attention factor — HF folds it into cos/sin,
    which is the same linear map)."""
    D = x.shape[-1]
    half = D // 2
    if inv_freq is None:
        freqs = 1.0 / (
            theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
        )
    else:
        freqs = jnp.asarray(inv_freq, jnp.float32)
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [T, half]
    cos = jnp.cos(angles)[:, None, :] * scale  # [T, 1, half]
    sin = jnp.sin(angles)[:, None, :] * scale
    x1, x2 = x[..., :half], x[..., half:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1
    ).astype(x.dtype)


def rope_spec(spec: ModelSpec, x: jax.Array, positions: jax.Array) -> jax.Array:
    """spec-driven rope: plain theta schedule, or YaRN when configured."""
    inv, att = yarn_freqs(spec, x.shape[-1])
    return rope(x, positions, spec.rope_theta, inv_freq=inv, scale=att)


def _attn_qkv(spec: ModelSpec, lp: Params, x: jax.Array, positions: jax.Array):
    """x: [T, d] -> q [T, nh, hd], k/v [T, nkv, hd] with rope applied."""
    T = x.shape[0]
    q = x @ lp["wq"]
    k = x @ lp["wk"]
    v = x @ lp["wv"]
    if spec.attn_bias:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(T, spec.num_heads, spec.head_dim)
    k = k.reshape(T, spec.num_kv_heads, spec.head_dim)
    v = v.reshape(T, spec.num_kv_heads, spec.head_dim)
    q = rope_spec(spec, q, positions)
    k = rope_spec(spec, k, positions)
    return q, k, v


def _o_proj(spec: ModelSpec, lp: Params, attn: jax.Array) -> jax.Array:
    out = attn @ lp["wo"]
    return out + lp["bo"] if spec.attn_bias else out


def _mlp(lp: Params, x: jax.Array) -> jax.Array:
    return (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]


def _ffn(spec: ModelSpec, lp: Params, x: jax.Array) -> jax.Array:
    """Dense MLP or routed MoE depending on the spec."""
    if spec.num_experts:
        from dynamo_tpu.models import moe

        return moe.moe_mlp(spec, lp["moe"], x)
    return _mlp(lp, x)


def _ffn_counted(spec: ModelSpec, lp: Params, x: jax.Array):
    """_ffn + dropped-slot count (0 for dense layers)."""
    if spec.num_experts:
        from dynamo_tpu.models import moe

        return moe.moe_mlp(spec, lp["moe"], x, return_dropped=True)
    return _mlp(lp, x), jnp.zeros((), jnp.int32)


def _logits(spec: ModelSpec, params: Params, x: jax.Array) -> jax.Array:
    x = rms_norm(x, params["final_norm"], spec.rms_eps)
    head = params["embed"].T if spec.tie_embeddings else params["lm_head"]
    return (x @ head).astype(jnp.float32)


# ---------------------------------------------------------------- prefill


def prefill_forward_impl(
    spec: ModelSpec,
    params: Params,
    tokens: jax.Array,  # [T_pad] int32 (padded)
    block_table: jax.Array,  # [max_pages_per_seq] int32
    start_pos: jax.Array,  # scalar: cached-prefix length (tokens)
    k_pages: jax.Array,  # [L, num_pages, kvh, page, D] (donated)
    v_pages: jax.Array,
    num_tokens: jax.Array,  # scalar: real token count in ``tokens``
    mesh: Mesh | None = None,  # static: replicate logits across the mesh
    mm_embeds: jax.Array | None = None,  # [M, d] multimodal embedding rows
    mm_pos: jax.Array | None = None,  # [M] window-relative positions (pad >= T)
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Process one prompt; writes KV pages; returns (last_logits, k, v).

    Attention runs over the gathered paged context (cached prefix + newly
    written tokens), so prefix-cache hits skip recompute of cached tokens.
    ``mm_embeds``/``mm_pos``: encoder rows overwrite the placeholder
    tokens' embeddings (multimodal EPD injection — one masked scatter;
    padded positions >= T drop).
    """
    T = tokens.shape[0]
    idx = jnp.arange(T)
    positions = start_pos + idx  # absolute positions of new tokens
    page_size = k_pages.shape[3]

    # Page-granular KV write: prefix-cache hits and chunk boundaries are
    # page-aligned (engine invariant), so the T new tokens start at a page
    # boundary and land as whole [page_size, D] tiles — one scatter over
    # T/page indices instead of T token rows (XLA lowers tile scatters an
    # order of magnitude faster on TPU; the trailing tile stays
    # contiguous). Garbage in a partial tail page sits beyond num_tokens:
    # masked in attention, overwritten as decode appends. Fully-padded
    # pages go to the trash page (duplicate trash indices are fine).
    n_pg = T // page_size
    page_starts = start_pos + jnp.arange(n_pg) * page_size
    pg_idx_raw = block_table[page_starts // page_size]
    safe_pg = jnp.where(
        page_starts < start_pos + num_tokens, pg_idx_raw, TRASH_PAGE
    )
    valid_tok = (idx < num_tokens).reshape(n_pg, page_size)

    x = params["embed"][tokens]  # [T, d]
    if mm_embeds is not None:
        x = x.at[mm_pos].set(mm_embeds.astype(x.dtype), mode="drop")
    kv_len = start_pos + num_tokens
    moe_dropped = jnp.zeros((), jnp.int32)

    for li, lp in enumerate(params["layers"]):
        h = rms_norm(x, lp["attn_norm"], spec.rms_eps)
        q, k, v = _attn_qkv(spec, lp, h, positions)
        k_pages = _set_page_tiles(k_pages, li, safe_pg, k, page_size,
                                  valid_tok)
        v_pages = _set_page_tiles(v_pages, li, safe_pg, v, page_size,
                                  valid_tok)
        # [max_ctx, kvh, D] — sliced back to the model dim when padded,
        # dequantized when the pool is fp8
        k_ctx = gather_ctx(k_pages, li, block_table, spec.head_dim)
        v_ctx = gather_ctx(v_pages, li, block_table, spec.head_dim)
        if is_quant(k_pages):
            # overlay the EXACT in-flight rows over the quantized
            # read-back (the XLA mirror of the fused kernel's analytic
            # new-token merge): this prefill's own tokens attend to each
            # other at full precision; only the cached prefix pays fp8
            k_ctx = k_ctx.at[positions].set(
                k.astype(k_ctx.dtype), mode="drop"
            )
            v_ctx = v_ctx.at[positions].set(
                v.astype(v_ctx.dtype), mode="drop"
            )
        attn = causal_attention(
            q, k_ctx, v_ctx, positions, kv_len,
            window=spec.attn_window(li), sinks=lp.get("sinks"),
        )
        attn = attn.reshape(T, spec.num_heads * spec.head_dim)
        x = x + _o_proj(spec, lp, attn)
        h = rms_norm(x, lp["mlp_norm"], spec.rms_eps)
        f, d = _ffn_counted(spec, lp, h)
        x = x + f
        moe_dropped = moe_dropped + d

    last = jnp.clip(num_tokens - 1, 0, T - 1)
    logits = _logits(spec, params, x[last])  # [V]
    logits = _replicate(logits, mesh)
    return logits, k_pages, v_pages, _replicate(moe_dropped, mesh)


def _replicate(x: jax.Array, mesh: Mesh | None) -> jax.Array:
    """Pin an output to fully-replicated across the mesh. Sampling runs on
    the leader's host (multi-host) or outside the SPMD program, so every
    process must hold an addressable full copy — without the constraint
    GSPMD may leave e.g. tp-sharded logits that only exist shard-wise."""
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P()))


prefill_forward = jax.jit(
    prefill_forward_impl, static_argnums=(0,), static_argnames=("mesh",),
    donate_argnums=(5, 6),
)


def prefill_forward_batch_impl(
    spec: ModelSpec,
    params: Params,
    tokens: jax.Array,  # [N, T_pad] int32 (padded)
    block_tables: jax.Array,  # [N, max_pages_per_seq] int32
    start_pos: jax.Array,  # [N] cached-prefix lengths (page-aligned)
    k_pages: jax.Array,  # donated
    v_pages: jax.Array,
    num_tokens: jax.Array,  # [N] real token counts
    mesh: Mesh | None = None,  # static
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """N prompts in ONE dispatch — the packed-prefill admission path.

    A queue of same-bucket prompts lands as one jit call instead of N:
    matmuls batch over [N, T, d] (the MXU sees N*T rows), the per-layer
    KV write is ONE page-tile scatter over all N*T/page pages, and
    attention runs per prompt over its own table. This is what takes
    admission TTFT from O(N * dispatch) to O(dispatch): dispatch and
    host<->device round-trips dominate short prefills, especially when
    the host is far from the chip.

    Returns (last_logits [N, V], k_pages, v_pages, moe_dropped).
    """
    N, T = tokens.shape
    page_size = k_pages.shape[3]
    idx = jnp.arange(T)
    positions = start_pos[:, None] + idx[None, :]  # [N, T]
    n_pg = T // page_size
    page_starts = start_pos[:, None] + (
        jnp.arange(n_pg) * page_size
    )[None, :]  # [N, n_pg]
    pg_idx_raw = jnp.take_along_axis(
        block_tables, page_starts // page_size, axis=1
    )
    valid_pg = page_starts < (start_pos + num_tokens)[:, None]
    safe_pg = jnp.where(valid_pg, pg_idx_raw, TRASH_PAGE).reshape(N * n_pg)
    valid_tok = (idx[None, :] < num_tokens[:, None]).reshape(
        N * n_pg, page_size
    )

    x = params["embed"][tokens]  # [N, T, d]
    kv_len = start_pos + num_tokens  # [N]
    moe_dropped = jnp.zeros((), jnp.int32)

    for li, lp in enumerate(params["layers"]):
        h = rms_norm(x, lp["attn_norm"], spec.rms_eps)
        q = h @ lp["wq"]
        k = h @ lp["wk"]
        v = h @ lp["wv"]
        if spec.attn_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = q.reshape(N, T, spec.num_heads, spec.head_dim)
        k = k.reshape(N, T, spec.num_kv_heads, spec.head_dim)
        v = v.reshape(N, T, spec.num_kv_heads, spec.head_dim)
        q = jax.vmap(lambda a, p: rope_spec(spec, a, p))(q, positions)
        k = jax.vmap(lambda a, p: rope_spec(spec, a, p))(k, positions)
        k_pages = _set_page_tiles(k_pages, li, safe_pg, k, page_size,
                                  valid_tok)
        v_pages = _set_page_tiles(v_pages, li, safe_pg, v, page_size,
                                  valid_tok)

        def one_attn(q_i, bt_i, pos_i, kvl_i, k_i, v_i, kp=k_pages,
                     vp=v_pages, li=li, lp=lp):
            k_ctx = gather_ctx(kp, li, bt_i, spec.head_dim)
            v_ctx = gather_ctx(vp, li, bt_i, spec.head_dim)
            if is_quant(kp):
                # exact in-flight rows over the quantized read-back
                # (see prefill_forward_impl)
                k_ctx = k_ctx.at[pos_i].set(
                    k_i.astype(k_ctx.dtype), mode="drop"
                )
                v_ctx = v_ctx.at[pos_i].set(
                    v_i.astype(v_ctx.dtype), mode="drop"
                )
            return causal_attention(
                q_i, k_ctx, v_ctx, pos_i, kvl_i,
                window=spec.attn_window(li), sinks=lp.get("sinks"),
            )

        attn = jax.vmap(one_attn)(q, block_tables, positions, kv_len, k, v)
        x = x + _o_proj(spec, lp, attn.reshape(N, T, -1))
        h = rms_norm(x, lp["mlp_norm"], spec.rms_eps)
        f, d = _ffn_counted(spec, lp, h.reshape(N * T, -1))
        x = x + f.reshape(N, T, -1)
        moe_dropped = moe_dropped + d

    last = jnp.clip(num_tokens - 1, 0, T - 1)  # [N]
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]
    logits = _logits(spec, params, x_last)  # [N, V]
    logits = _replicate(logits, mesh)
    return logits, k_pages, v_pages, _replicate(moe_dropped, mesh)


prefill_forward_batch = jax.jit(
    prefill_forward_batch_impl, static_argnums=(0,),
    static_argnames=("mesh",), donate_argnums=(5, 6),
)


def prefill_forward_ring_impl(
    spec: ModelSpec,
    params: Params,
    tokens: jax.Array,  # [T_pad] int32, T_pad divisible by mesh sp
    block_table: jax.Array,  # [max_pages_per_seq] int32
    k_pages: jax.Array,  # donated
    v_pages: jax.Array,
    num_tokens: jax.Array,  # scalar: real token count
    mesh: Mesh,  # static
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Long-context prefill with sequence-parallel ring attention.

    Token activations shard over the "sp" mesh axis (sharding constraints
    guide GSPMD; only the attention itself is an explicit shard_map ring —
    see parallel/ring.py). No cached-prefix support: ring prefill serves
    cold ultra-long prompts; warm prefixes take the paged path. Padding at
    the tail is masked by causality (padded positions exceed every real
    query) and scatters to the trash page.
    """
    from dynamo_tpu.parallel.ring import ring_attention

    T = tokens.shape[0]
    idx = jnp.arange(T)
    page_size = k_pages.shape[3]
    # page-granular tile writes (see prefill_forward_impl): ring prefill is
    # cold (start 0), so the prompt starts page-aligned by construction
    n_pg = T // page_size
    page_starts = jnp.arange(n_pg) * page_size
    pg_idx_raw = block_table[page_starts // page_size]
    safe_pg = jnp.where(page_starts < num_tokens, pg_idx_raw, TRASH_PAGE)
    valid_tok = (idx < num_tokens).reshape(n_pg, page_size)

    sp_spec = NamedSharding(mesh, P("sp", None))
    x = params["embed"][tokens]
    x = jax.lax.with_sharding_constraint(x, sp_spec)

    moe_dropped = jnp.zeros((), jnp.int32)
    for li, lp in enumerate(params["layers"]):
        h = rms_norm(x, lp["attn_norm"], spec.rms_eps)
        q, k, v = _attn_qkv(spec, lp, h, idx)
        k_pages = _set_page_tiles(k_pages, li, safe_pg, k, page_size,
                                  valid_tok)
        v_pages = _set_page_tiles(v_pages, li, safe_pg, v, page_size,
                                  valid_tok)
        attn = ring_attention(q, k, v, mesh=mesh)
        x = x + _o_proj(
            spec, lp, attn.reshape(T, spec.num_heads * spec.head_dim)
        )
        h = rms_norm(x, lp["mlp_norm"], spec.rms_eps)
        f, d = _ffn_counted(spec, lp, h)
        x = x + f
        moe_dropped = moe_dropped + d
        x = jax.lax.with_sharding_constraint(x, sp_spec)

    last = jnp.clip(num_tokens - 1, 0, T - 1)
    logits = _logits(spec, params, x[last])
    logits = _replicate(logits, mesh)
    return logits, k_pages, v_pages, _replicate(moe_dropped, mesh)


prefill_forward_ring = jax.jit(
    prefill_forward_ring_impl,
    static_argnums=(0,),
    static_argnames=("mesh",),
    donate_argnums=(4, 5),
)


# ----------------------------------------------------------------- verify


def verify_forward_impl(
    spec: ModelSpec,
    params: Params,
    tokens: jax.Array,  # [N, W] int32: [fed_token, draft...] per row
    block_tables: jax.Array,  # [N, max_pages_per_seq]
    start_pos: jax.Array,  # [N]: cache length before the fed token
    k_pages: jax.Array,  # donated
    v_pages: jax.Array,
    num_tokens: jax.Array,  # [N] valid tokens per row (0 = padded row)
    mesh: Mesh | None = None,  # static
    allowed: jax.Array | None = None,  # [N, W, V] bool: guided masks
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Speculative-verify forward: N slots' (fed token + k drafts) in
    ONE short-prefill dispatch, with the target's greedy choice at EVERY
    position (engine/core.py _spec_phase).

    Differs from prefill in exactly two ways. (1) KV writes are
    TOKEN-granular (write_new_kv — the decode-path scatter/DMA kernel):
    a verify starts wherever decode left off, mid-page, so the
    page-tile scatter's page-aligned-start invariant does not hold.
    (2) Logits are computed for all W positions and argmax'd ON DEVICE —
    the host needs only the [N, W] int32 target tokens to run
    accept-longest-prefix, not a [N, W, V] logits download.

    Rejected-draft KV rows are garbage beyond the accepted prefix: they
    sit past the slot's post-verify seq_len, masked from attention, and
    are overwritten by the next real write at that position (the
    engine's page rollback handles the allocator side).

    Returns (targets [N, W] int32, k_pages, v_pages, moe_dropped).
    """
    from dynamo_tpu.ops.pallas.kv_write import write_new_kv

    N, W = tokens.shape
    page_size = k_pages.shape[3]
    idx = jnp.arange(W)
    positions = start_pos[:, None] + idx[None, :]  # [N, W]
    valid = idx[None, :] < num_tokens[:, None]
    pg_idx_raw = jnp.take_along_axis(
        block_tables, positions // page_size, axis=1
    )
    safe_pg2 = jnp.where(valid, pg_idx_raw, TRASH_PAGE)  # [N, W]
    offs2 = positions % page_size
    safe_pg = safe_pg2.reshape(N * W)
    offs = offs2.reshape(N * W)

    x = params["embed"][tokens]  # [N, W, d]
    kv_len = start_pos + num_tokens  # [N]
    moe_dropped = jnp.zeros((), jnp.int32)

    for li, lp in enumerate(params["layers"]):
        h = rms_norm(x, lp["attn_norm"], spec.rms_eps)
        q = h @ lp["wq"]
        k = h @ lp["wk"]
        v = h @ lp["wv"]
        if spec.attn_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = q.reshape(N, W, spec.num_heads, spec.head_dim)
        k = k.reshape(N, W, spec.num_kv_heads, spec.head_dim)
        v = v.reshape(N, W, spec.num_kv_heads, spec.head_dim)
        q = jax.vmap(lambda a, p: rope_spec(spec, a, p))(q, positions)
        k = jax.vmap(lambda a, p: rope_spec(spec, a, p))(k, positions)
        if is_quant(k_pages):
            # quantized append is a page-granular RMW: a verify's W
            # tokens often share a page, so land them one POSITION at a
            # time (static W loop, distinct pages within each call) —
            # the one-scatter fast path would lose same-page siblings
            for w in range(W):
                k_pages, v_pages = write_new_kv(
                    k_pages, v_pages, k[:, w], v[:, w],
                    safe_pg2[:, w], offs2[:, w], layer=li, mesh=mesh,
                )
        else:
            k_pages, v_pages = write_new_kv(
                k_pages, v_pages,
                k.reshape(N * W, spec.num_kv_heads, spec.head_dim),
                v.reshape(N * W, spec.num_kv_heads, spec.head_dim),
                safe_pg, offs, layer=li, mesh=mesh,
            )

        def one_attn(q_i, bt_i, pos_i, kvl_i, k_i, v_i, kp=k_pages,
                     vp=v_pages, li=li, lp=lp):
            k_ctx = gather_ctx(kp, li, bt_i, spec.head_dim)
            v_ctx = gather_ctx(vp, li, bt_i, spec.head_dim)
            if is_quant(kp):
                # exact verify-window rows over the quantized read-back:
                # the fed token + drafts judge each other at full
                # precision, like the fused decode path's analytic merge
                k_ctx = k_ctx.at[pos_i].set(
                    k_i.astype(k_ctx.dtype), mode="drop"
                )
                v_ctx = v_ctx.at[pos_i].set(
                    v_i.astype(v_ctx.dtype), mode="drop"
                )
            return causal_attention(
                q_i, k_ctx, v_ctx, pos_i, kvl_i,
                window=spec.attn_window(li), sinks=lp.get("sinks"),
            )

        attn = jax.vmap(one_attn)(q, block_tables, positions, kv_len, k, v)
        x = x + _o_proj(spec, lp, attn.reshape(N, W, -1))
        h = rms_norm(x, lp["mlp_norm"], spec.rms_eps)
        f, d = _ffn_counted(spec, lp, h.reshape(N * W, -1))
        x = x + f.reshape(N, W, -1)
        moe_dropped = moe_dropped + d

    logits = _logits(spec, params, x)  # [N, W, V]
    if allowed is not None:
        # guided decoding composes with speculation here: masking the
        # VERIFY logits per position means a rejected draft's correction
        # token is itself grammar-legal — conformance survives rejection
        logits = jnp.where(allowed, logits, -1e30)
    targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return (
        _replicate(targets, mesh), k_pages, v_pages,
        _replicate(moe_dropped, mesh),
    )


verify_forward = jax.jit(
    verify_forward_impl, static_argnums=(0,), static_argnames=("mesh",),
    donate_argnums=(5, 6),
)


# ---------------------------------------------------------------- decode


def decode_forward_impl(
    spec: ModelSpec,
    params: Params,
    tokens: jax.Array,  # [B] int32: last sampled token per slot
    block_tables: jax.Array,  # [B, max_pages_per_seq]
    seq_lens: jax.Array,  # [B] length INCLUDING the new token
    k_pages: jax.Array,  # donated
    v_pages: jax.Array,
    active: jax.Array,  # [B] bool: slot has a live request
    mesh: Mesh | None = None,  # static: routes attention through shard_map
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step for the whole slot batch; returns (logits[B,V], k, v)."""
    B = tokens.shape[0]
    page_size = k_pages.shape[3]
    positions = seq_lens - 1  # position of the new token

    page_idx_raw = jnp.take_along_axis(
        block_tables, (positions // page_size)[:, None], axis=1
    )[:, 0]
    safe_page = jnp.where(active, page_idx_raw, TRASH_PAGE)
    offset = positions % page_size

    x = params["embed"][tokens]  # [B, d]

    for li, lp in enumerate(params["layers"]):
        h = rms_norm(x, lp["attn_norm"], spec.rms_eps)
        # per-slot single-token qkv: vmap the [T=1] path
        q = h @ lp["wq"]
        k = h @ lp["wk"]
        v = h @ lp["wv"]
        if spec.attn_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = q.reshape(B, spec.num_heads, spec.head_dim)
        k = k.reshape(B, spec.num_kv_heads, spec.head_dim)
        v = v.reshape(B, spec.num_kv_heads, spec.head_dim)
        q = rope_spec(spec, q, positions)
        k = rope_spec(spec, k, positions)
        # KV append + paged attention in ONE kernel per layer on the
        # Pallas path (ops/pallas/fused_decode.py — halves the decode
        # program's kernel-launch count); scatter + gather attention
        # elsewhere (ops/attention.decode_update_attention dispatch)
        attn, k_pages, v_pages = decode_update_attention(
            q, k_pages, v_pages, k, v, block_tables, seq_lens,
            safe_page, offset, layer=li, mesh=mesh,
            window=spec.attn_window(li), sinks=lp.get("sinks"),
        )
        attn = attn.reshape(B, spec.num_heads * spec.head_dim)
        x = x + _o_proj(spec, lp, attn)
        h = rms_norm(x, lp["mlp_norm"], spec.rms_eps)
        x = x + _ffn(spec, lp, h)

    logits = _logits(spec, params, x)  # [B, V]
    return logits, k_pages, v_pages


decode_forward = jax.jit(
    decode_forward_impl, static_argnums=(0,), static_argnames=("mesh",),
    donate_argnums=(5, 6),
)


def decode_steps_impl(
    spec: ModelSpec,
    params: Params,
    tokens: jax.Array,  # [B] last sampled token per slot
    block_tables: jax.Array,  # [B, max_pages_per_seq]
    seq_lens: jax.Array,  # [B] length INCLUDING the first new token
    k_pages: jax.Array,  # donated
    v_pages: jax.Array,
    active: jax.Array,  # [B] bool
    temperature: jax.Array,  # [B] f32
    top_k: jax.Array,  # [B] int32
    top_p: jax.Array,  # [B] f32
    seeds: jax.Array,  # [B] uint32
    steps: jax.Array,  # [B] int32: tokens generated so far per slot
    n_steps: int = 1,  # static: decode steps per dispatch
    n_logprobs: int = 0,  # static: 0=off, N=sampled+top-N logprobs
    mesh: Mesh | None = None,  # static
    allowed: jax.Array | None = None,  # [B, V] bool: guided token masks
):
    """``n_steps`` decode iterations + on-device sampling in ONE dispatch.

    Returns (sampled [B, n_steps], k_pages, v_pages) — plus, when
    ``n_logprobs`` > 0, (sampled_logprobs [B, n], top_ids [B, n, N],
    top_logprobs [B, n, N]) between sampled and the caches. Amortizes host
    dispatch and device-sync cost over n steps (the same reason vLLM grew
    multi-step scheduling): only small arrays cross to the host per
    dispatch. Callers must pre-extend block tables so every active slot
    has page room for n more tokens; EOS inside a burst is handled
    host-side by discarding the tail. Sampling keys fold in the per-slot
    generated-count so bursts reproduce the per-request RNG stream exactly
    (engine/sampling.py contract).

    ``allowed`` is the guided-decoding constraint mask: the host-side
    automaton only advances as sampled tokens LAND, so the engine
    dispatches masked bursts at n_steps=1 (the mask is per-position) —
    a batch with no constrained slot passes None and compiles/runs the
    unmasked program unchanged.
    """
    from dynamo_tpu.engine.sampling import sample_tokens, token_logprobs

    B = tokens.shape[0]
    out0 = jnp.zeros((B, n_steps), jnp.int32)
    lp0 = jnp.zeros((B, n_steps), jnp.float32)
    ti0 = jnp.zeros((B, n_steps, max(n_logprobs, 1)), jnp.int32)
    tv0 = jnp.zeros((B, n_steps, max(n_logprobs, 1)), jnp.float32)

    def body(i, carry):
        toks, lens, kp, vp, out, lp, ti, tv = carry
        logits, kp, vp = decode_forward_impl(
            spec, params, toks, block_tables, lens, kp, vp, active, mesh=mesh
        )
        if allowed is not None:
            logits = jnp.where(allowed, logits, -1e30)
        nxt = sample_tokens(
            logits, temperature, top_k, top_p, seeds, steps + i
        )
        nxt = jnp.where(active, nxt, toks)
        out = out.at[:, i].set(nxt)
        if n_logprobs > 0:
            picked, top_i, top_v = token_logprobs(logits, nxt, n_logprobs)
            lp = lp.at[:, i].set(picked)
            ti = ti.at[:, i].set(top_i)
            tv = tv.at[:, i].set(top_v)
        return nxt, lens + active.astype(jnp.int32), kp, vp, out, lp, ti, tv

    _toks, _lens, k_pages, v_pages, out, lp, ti, tv = jax.lax.fori_loop(
        0, n_steps, body,
        (tokens, seq_lens, k_pages, v_pages, out0, lp0, ti0, tv0),
        unroll=False,
    )
    out = _replicate(out, mesh)
    if n_logprobs > 0:
        return (out, _replicate(lp, mesh), _replicate(ti, mesh),
                _replicate(tv, mesh), k_pages, v_pages)
    return out, k_pages, v_pages


decode_steps = jax.jit(
    decode_steps_impl,
    static_argnums=(0,),
    static_argnames=("n_steps", "n_logprobs", "mesh"),
    donate_argnums=(5, 6),
)


# ------------------------------------------------------- kv page movement


def _extract_kv_pages_impl(k_pages, v_pages, page_ids):
    """Gather whole pages for transfer: -> [L, n, kvh, page, D] x2.

    QuantPool pools pack fp8 values + bf16 scales into ONE uint8 payload
    per (layer, page) (ops/quant.pack_pages): KVBM tiers and the disagg
    wire then carry exactly those bytes — half the footprint, no silent
    upcast possible, and onboard re-materializes fp8 by bitcast."""
    if is_quant(k_pages):
        return pack_pages(k_pages, page_ids), pack_pages(v_pages, page_ids)
    return k_pages[:, page_ids], v_pages[:, page_ids]


# dynalint: disable=DL012 -- read-only gather: the live pools must
# survive the call (the extracted pages ship over the disagg wire while
# the source engine keeps serving from the same pools)
extract_kv_pages = jax.jit(_extract_kv_pages_impl)


def _insert_kv_pages_impl(k_pages, v_pages, page_ids, k_blocks, v_blocks):
    """Scatter transferred pages into the local pools (donated).
    Blocks are page-major stacks [L, n, kvh, page, D] — or packed uint8
    [L, n, X] payloads when the pool is quantized (both engines of a
    disagg pair must run the same kv_dtype)."""
    if is_quant(k_pages):
        kv_, ks_ = unpack_pages(
            k_blocks, k_pages.vals.shape[2:], k_pages.scale.shape[2:]
        )
        vv_, vs_ = unpack_pages(
            v_blocks, v_pages.vals.shape[2:], v_pages.scale.shape[2:]
        )
        return (
            QuantPool(
                k_pages.vals.at[:, page_ids].set(kv_),
                k_pages.scale.at[:, page_ids].set(ks_),
            ),
            QuantPool(
                v_pages.vals.at[:, page_ids].set(vv_),
                v_pages.scale.at[:, page_ids].set(vs_),
            ),
        )
    return (
        k_pages.at[:, page_ids].set(k_blocks),
        v_pages.at[:, page_ids].set(v_blocks),
    )


insert_kv_pages = jax.jit(_insert_kv_pages_impl, donate_argnums=(0, 1))


# ------------------------------------------------------------- embeddings


def embed_forward_impl(
    spec: ModelSpec,
    params: Params,
    tokens: jax.Array,  # [T_pad] int32 (padded)
    num_tokens: jax.Array,  # scalar: real token count
) -> jax.Array:
    """Sequence embedding: mean-pool the final-norm hidden states over the
    real tokens, L2-normalized — the serving surface behind /v1/embeddings
    (ref: the embeddings path of the HTTP service, http/service/openai.rs
    /v1/embeddings; engine side delegated in the reference, native here).
    Returns [hidden_size] float32."""
    T = tokens.shape[0]
    positions = jnp.arange(T)
    x = params["embed"][tokens]
    for li, lp in enumerate(params["layers"]):
        h = rms_norm(x, lp["attn_norm"], spec.rms_eps)
        q, k, v = _attn_qkv(spec, lp, h, positions)
        attn = causal_attention(
            q, k, v, positions, num_tokens,
            window=spec.attn_window(li), sinks=lp.get("sinks"),
        )
        x = x + _o_proj(spec, lp, attn.reshape(T, -1))
        h = rms_norm(x, lp["mlp_norm"], spec.rms_eps)
        x = x + _ffn(spec, lp, h)
    xn = rms_norm(x, params["final_norm"], spec.rms_eps).astype(jnp.float32)
    mask = (positions < num_tokens)[:, None].astype(jnp.float32)
    pooled = (xn * mask).sum(axis=0) / jnp.maximum(mask.sum(), 1.0)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled), 1e-9)


embed_forward = jax.jit(embed_forward_impl, static_argnums=(0,))


# -------------------------------------------------------------- reference


def reference_forward(
    spec: ModelSpec, params: Params, tokens: jax.Array
) -> jax.Array:
    """Plain full-attention forward (no paging) - numerical ground truth for
    tests. tokens: [T] -> logits [T, V]."""
    T = tokens.shape[0]
    positions = jnp.arange(T)
    x = params["embed"][tokens]
    for li, lp in enumerate(params["layers"]):
        h = rms_norm(x, lp["attn_norm"], spec.rms_eps)
        q, k, v = _attn_qkv(spec, lp, h, positions)
        attn = causal_attention(
            q, k, v, positions, jnp.asarray(T),
            window=spec.attn_window(li), sinks=lp.get("sinks"),
        )
        x = x + _o_proj(spec, lp, attn.reshape(T, -1))
        h = rms_norm(x, lp["mlp_norm"], spec.rms_eps)
        x = x + _ffn(spec, lp, h)
    xn = rms_norm(x, params["final_norm"], spec.rms_eps)
    head = params["embed"].T if spec.tie_embeddings else params["lm_head"]
    return (xn @ head).astype(jnp.float32)
