"""Mixture-of-experts FFN with expert-parallel sharding.

Covers the reference's MoE model families (gpt-oss-120b EP configs,
deepseek-r1 wide-EP — engine_configs/deepseek_r1/wide_ep/wide_ep_agg.yaml
``moe_expert_parallel_size``, recipes/deepseek-r1/sglang-wideep) the
TPU-first way: experts are a leading array axis sharded over the mesh's
"ep" axis, routing is a dense one-hot combine, and XLA's SPMD partitioner
turns the expert-contraction einsum into the EP all-to-all/psum. Dense
dispatch (every expert sees every token, combine weights zero out the
rest) keeps shapes static and the MXU busy; at very large expert counts a
ragged shard_map dispatch becomes worthwhile — the layer boundary here is
where it would slot in.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import ModelSpec

Params = dict


def init_moe_layer(spec: ModelSpec, key: jax.Array) -> Params:
    """Router + stacked expert weights for one layer."""
    dtype = jnp.dtype(spec.dtype)
    d, e, f = spec.hidden_size, spec.num_experts, spec.moe_intermediate_size
    k1, k2, k3, k4 = jax.random.split(key, 4)

    def dense(k, shape, scale=None):
        scale = scale or (1.0 / jnp.sqrt(shape[-2]))
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    return {
        "router": dense(k1, (d, e), scale=0.02).astype(jnp.float32),
        "w_gate": dense(k2, (e, d, f)),
        "w_up": dense(k3, (e, d, f)),
        "w_down": dense(k4, (e, f, d)),
    }


def moe_layer_shardings(mesh: Mesh) -> Params:
    """Experts sharded over "ep", expert-FFN columns over "tp"."""

    def ns(*axes):
        return NamedSharding(mesh, P(*axes))

    return {
        "router": ns(),
        "w_gate": ns("ep", None, "tp"),
        "w_up": ns("ep", None, "tp"),
        "w_down": ns("ep", "tp", None),
    }


def moe_mlp(spec: ModelSpec, lp: Params, x: jax.Array) -> jax.Array:
    """x: [T, d] -> [T, d] through top-k routed experts.

    Routing softmax in f32; top-k weights renormalized (mixtral-style).
    """
    T = x.shape[0]
    probs = jax.nn.softmax(
        x.astype(jnp.float32) @ lp["router"], axis=-1
    )  # [T, E]
    topv, topi = jax.lax.top_k(probs, spec.num_experts_per_token)
    topv = topv / jnp.maximum(topv.sum(axis=-1, keepdims=True), 1e-9)
    # dense combine weights [T, E]: zero for unrouted experts
    combine = jnp.zeros_like(probs)
    combine = jax.vmap(lambda c, i, v: c.at[i].set(v))(combine, topi, topv)

    # every expert computes every token; combine zeroes the unrouted ones.
    # XLA partitions the e-axis over "ep" and psums the final contraction.
    h_gate = jnp.einsum("td,edf->tef", x, lp["w_gate"])
    h_up = jnp.einsum("td,edf->tef", x, lp["w_up"])
    h = jax.nn.silu(h_gate) * h_up
    out = jnp.einsum("tef,efd->ted", h, lp["w_down"])  # [T, E, d]
    return jnp.einsum(
        "ted,te->td", out.astype(jnp.float32), combine
    ).astype(x.dtype)
