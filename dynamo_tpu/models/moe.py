"""Mixture-of-experts FFN with expert-parallel sharding.

Covers the reference's MoE model families (gpt-oss-120b EP configs,
deepseek-r1 wide-EP — engine_configs/deepseek_r1/wide_ep/wide_ep_agg.yaml
``moe_expert_parallel_size``, recipes/deepseek-r1/sglang-wideep) the
TPU-first way: experts are a leading array axis sharded over the mesh's
"ep" axis and dispatch is GShard/Switch capacity-based — static-shape
one-hot dispatch/combine einsums (MXU) around a batched [E, C, d] expert
compute, with XLA's SPMD partitioner inserting the EP all-to-alls. Total
expert work scales with tokens x top_k, not with E, so E=128 presets are
servable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import ModelSpec

Params = dict


def init_moe_layer(spec: ModelSpec, key: jax.Array) -> Params:
    """Router + stacked expert weights for one layer."""
    dtype = jnp.dtype(spec.dtype)
    d, e, f = spec.hidden_size, spec.num_experts, spec.moe_intermediate_size
    k1, k2, k3, k4 = jax.random.split(key, 4)

    def dense(k, shape, scale=None):
        scale = scale or (1.0 / jnp.sqrt(shape[-2]))
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    out = {
        "router": dense(k1, (d, e), scale=0.02).astype(jnp.float32),
        "w_gate": dense(k2, (e, d, f)),
        "w_up": dense(k3, (e, d, f)),
        "w_down": dense(k4, (e, f, d)),
    }
    if spec.moe_bias:  # gpt-oss: router + expert biases
        out["router_bias"] = jnp.zeros((e,), jnp.float32)
        out["b_gate"] = jnp.zeros((e, f), dtype)
        out["b_up"] = jnp.zeros((e, f), dtype)
        out["b_down"] = jnp.zeros((e, d), dtype)
    if spec.moe_scoring == "sigmoid":
        # DeepSeek-V3 aux-free load balancing: learned per-expert
        # correction bias shifts SELECTION only, never the weights
        out["score_bias"] = jnp.zeros((e,), jnp.float32)
    return out


def moe_layer_shardings(mesh: Mesh, spec: ModelSpec | None = None) -> Params:
    """Experts sharded over "ep", expert-FFN columns over "tp"."""

    def ns(*axes):
        return NamedSharding(mesh, P(*axes))

    out = {
        "router": ns(),
        "w_gate": ns("ep", None, "tp"),
        "w_up": ns("ep", None, "tp"),
        "w_down": ns("ep", "tp", None),
    }
    if spec is not None and spec.moe_bias:
        out.update(
            router_bias=ns(),
            b_gate=ns("ep", "tp"),
            b_up=ns("ep", "tp"),
            b_down=ns("ep", None),
        )
    if spec is not None and spec.moe_scoring == "sigmoid":
        out["score_bias"] = ns()
    return out


def expert_capacity(
    T: int, E: int, k: int, capacity_factor: float = 1.25
) -> int:
    """Per-expert token-slot budget: total slots E*C ~= T*k*cf regardless
    of E — the property that makes E=128 presets servable (the old dense
    combine computed every expert for every token: E/k times the FLOPs).

    Floor: C >= min(T, 16). Small batches (decode steps) route
    correlatedly, and a drop there silently degrades live outputs — at
    C = T drops are impossible, and for T <= 16 the dispatch tensors are
    tiny anyway. Large prefills keep the throughput-oriented budget
    (inference routing is balanced enough at cf 1.25; overflow drops an
    expert's contribution without renormalizing the rest)."""
    import math

    cap = math.ceil(T * k / E * capacity_factor)
    return max(1, min(T, max(cap, 16)))


def moe_mlp(
    spec: ModelSpec, lp: Params, x: jax.Array, *,
    capacity_factor: float = 1.25,
    return_dropped: bool = False,
):
    """x: [T, d] -> [T, d] through top-k routed experts (sparse dispatch).

    GShard/Switch-style capacity-based dispatch, the canonical TPU MoE:
    static shapes throughout (XLA-friendly), one-hot dispatch/combine
    einsums on the MXU, experts batched as one [E, C, d] tensor. Tokens
    overflowing an expert's capacity drop that expert's contribution
    (standard capacity semantics; renormalized top-k weights mean the
    remaining experts still cover the token). Routing softmax in f32;
    top-k weights renormalized (mixtral-style). Under an "ep" mesh the
    [E, ...] axes shard and XLA inserts the all-to-alls.
    """
    T = x.shape[0]
    E, k = spec.num_experts, spec.num_experts_per_token
    C = expert_capacity(T, E, k, capacity_factor)

    router_logits = x.astype(jnp.float32) @ lp["router"]
    if "router_bias" in lp:
        router_logits = router_logits + lp["router_bias"]
    if spec.moe_scoring == "sigmoid":
        # DeepSeek-V3 noaux_tc routing (HF DeepseekV3TopkRouter): sigmoid
        # scores; the learned correction bias + group-limited top-k pick
        # the experts, but the combine WEIGHTS come from the unbiased
        # scores, renormalized and scaled by routed_scaling_factor
        scores = jax.nn.sigmoid(router_logits)  # [T, E]
        choice = scores + lp["score_bias"]
        if spec.n_group > 1:
            gsz = E // spec.n_group
            grouped = choice.reshape(T, spec.n_group, gsz)
            group_scores = jax.lax.top_k(grouped, 2)[0].sum(-1)  # [T, G]
            _gv, gidx = jax.lax.top_k(group_scores, spec.topk_group)
            gmask = jax.nn.one_hot(
                gidx, spec.n_group, dtype=jnp.float32
            ).sum(axis=1)  # [T, G]
            choice = jnp.where(
                jnp.repeat(gmask, gsz, axis=-1) > 0, choice, 0.0
            )
        _cv, topi = jax.lax.top_k(choice, k)  # [T, k]
        topv = jnp.take_along_axis(scores, topi, axis=1)
        if spec.norm_topk_prob:
            topv = topv / (topv.sum(axis=-1, keepdims=True) + 1e-20)
        topv = topv * spec.routed_scaling_factor
    else:
        # softmax-all + top-k renormalize == softmax over the top-k
        # logits (HF gpt-oss GptOssTopKRouter): same selection/weights
        probs = jax.nn.softmax(router_logits, axis=-1)  # [T, E]
        topv, topi = jax.lax.top_k(probs, k)  # [T, k]
        topv = topv / jnp.maximum(topv.sum(axis=-1, keepdims=True), 1e-9)

    # position of each (token, choice) within its expert's capacity:
    # running count of prior assignments to the same expert, in flattened
    # (t, j) order
    oh = jax.nn.one_hot(topi.reshape(T * k), E, dtype=jnp.int32)  # [T*k, E]
    pos_in_expert = jnp.cumsum(oh, axis=0) - oh  # [T*k, E]
    pos = jnp.take_along_axis(
        pos_in_expert, topi.reshape(T * k)[:, None], axis=1
    )[:, 0].reshape(T, k)
    keep = pos < C  # overflow drops

    # combine[t, e, c] = weight of token t's slot c in expert e
    e_oh = jax.nn.one_hot(topi, E, dtype=jnp.float32)  # [T, k, E]
    c_oh = jax.nn.one_hot(pos, C, dtype=jnp.float32)  # [T, k, C]
    w = topv * keep.astype(jnp.float32)  # [T, k]
    combine = jnp.einsum("tke,tkc,tk->tec", e_oh, c_oh, w)  # [T, E, C]
    dispatch = (combine > 0.0).astype(x.dtype)

    xe = jnp.einsum("td,tec->ecd", x, dispatch)  # [E, C, d]
    g = jnp.einsum("ecd,edf->ecf", xe, lp["w_gate"])
    u = jnp.einsum("ecd,edf->ecf", xe, lp["w_up"])
    if "b_gate" in lp:
        g = g + lp["b_gate"][:, None, :]
        u = u + lp["b_up"][:, None, :]
    if spec.swiglu_limit:
        # gpt-oss clamped swiglu (HF GptOssExperts.forward): gate capped
        # above, linear clamped both ways, swish slope alpha, (up + 1)
        g = jnp.minimum(g, spec.swiglu_limit)
        u = jnp.clip(u, -spec.swiglu_limit, spec.swiglu_limit)
        h = g * jax.nn.sigmoid(spec.swiglu_alpha * g) * (u + 1.0)
    else:
        h = jax.nn.silu(g) * u
    out_e = jnp.einsum("ecf,efd->ecd", h, lp["w_down"])  # [E, C, d]
    if "b_down" in lp:
        out_e = out_e + lp["b_down"][:, None, :]
    out = jnp.einsum(
        "ecd,tec->td", out_e.astype(jnp.float32), combine
    ).astype(x.dtype)
    if return_dropped:
        # slots past capacity whose expert contribution was dropped —
        # the silent-quality-degradation signal (VERDICT r2 weak #7);
        # surfaced through ForwardPassMetrics by the engine
        return out, jnp.sum(~keep).astype(jnp.int32)
    return out
