"""Model-family dispatch: one engine, multiple attention architectures.

The engine's hot loop (engine/core.py) is family-agnostic: it drives a
small adapter surface — params/cache init, prefill, fused decode, page
extract/insert — and the adapter maps it onto the family's functional
core. Two families today:

- ``GqaFamily``: llama/mistral/mixtral/qwen/gpt-oss (models/llama.py) —
  paged K and V pools, GQA attention, the full feature matrix (packed
  prefill, ring prefill, meshes, logprobs, embeddings).
- ``MlaFamily``: DeepSeek-V2/V3/R1 (models/mla.py) — ONE latent cache
  array. The engine's (k_pages, v_pages) plumbing carries the latent
  cache as ``k_pages`` and a tiny inert placeholder as ``v_pages`` so
  page bookkeeping, KVBM tier blocks, and transfer metadata flow
  unchanged. Supports meshes (tp over heads, ep over experts,
  replicated latent cache), packed prefill, logprobs, and embeddings;
  ring prefill (long MLA prompts chunk instead) and multimodal stay
  gated off.

Ref: the reference delegates this dispatch to its engines (vLLM model
registry); here it is explicit and small.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import ModelSpec

__all__ = ["get_family", "GqaFamily", "MlaFamily"]


class GqaFamily:
    """llama-family adapter: thin passthrough to models/llama.py."""

    supports_packed_prefill = True
    supports_ring_prefill = True
    supports_mesh = True
    supports_logprobs = True
    supports_embeddings = True
    supports_multimodal = True  # prefill embedding injection (EPD)
    supports_spec_decode = True  # prompt-lookup verify (engine/spec.py)

    def __init__(self, spec: Any | None = None):
        from dynamo_tpu.models import llama

        self.m = llama
        # ring attention has no sink/sliding-window support: gpt-oss-like
        # specs fall back to chunked prefill for long prompts
        if spec is not None and spec.has_attn_extras:
            self.supports_ring_prefill = False

    def init_params(self, spec, key):
        return self.m.init_params(spec, key)

    def param_shardings(self, spec, mesh):
        return self.m.param_shardings(spec, mesh)

    def cache_shardings(self, mesh, kv_dtype="bf16"):
        return self.m.cache_shardings(mesh, kv_dtype)

    def init_cache(self, spec, num_pages, page_size, kv_dtype="bf16"):
        return self.m.init_cache(
            spec, num_pages, page_size, kv_dtype=kv_dtype
        )

    def prefill(self, spec, params, tokens, bt, start, k, v, n, mesh=None,
                mm_embeds=None, mm_pos=None):
        return self.m.prefill_forward(
            spec, params, tokens, bt, start, k, v, n, mesh=mesh,
            mm_embeds=mm_embeds, mm_pos=mm_pos,
        )

    def prefill_batch(self, spec, params, tokens, bts, starts, k, v, ns,
                      mesh=None):
        return self.m.prefill_forward_batch(
            spec, params, tokens, bts, starts, k, v, ns, mesh=mesh
        )

    def prefill_ring(self, spec, params, tokens, bt, k, v, n, mesh):
        return self.m.prefill_forward_ring(
            spec, params, tokens, bt, k, v, n, mesh=mesh
        )

    def verify(self, spec, params, tokens, bts, starts, k, v, ns,
               mesh=None, allowed=None):
        return self.m.verify_forward(
            spec, params, tokens, bts, starts, k, v, ns, mesh=mesh,
            allowed=allowed,
        )

    def decode_steps(self, spec, params, tokens, bts, lens, k, v, active,
                     temps, topk, topp, seeds, steps, *, n_steps, n_logprobs,
                     mesh=None, allowed=None):
        return self.m.decode_steps(
            spec, params, tokens, bts, lens, k, v, active, temps, topk,
            topp, seeds, steps, n_steps=n_steps, n_logprobs=n_logprobs,
            mesh=mesh, allowed=allowed,
        )

    def extract_pages(self, k, v, page_ids):
        return self.m.extract_kv_pages(k, v, page_ids)

    def insert_pages(self, k, v, page_ids, kb, vb):
        return self.m.insert_kv_pages(k, v, page_ids, kb, vb)

    def embed_forward(self, spec, params, tokens, num_tokens):
        return self.m.embed_forward(spec, params, tokens, num_tokens)


class MlaFamily:
    """DeepSeek MLA adapter: latent cache rides the k_pages slot; the
    v_pages slot carries an inert [1] placeholder everywhere.

    Mesh story (deepseek-r1-class serving): per-head work shards over
    "tp", experts over "ep" (mla.param_shardings), and the latent cache
    replicates — it has no head axis and is ~14x smaller than GQA KV, so
    every rank decodes against a local copy with no gather collective.
    Ref topology: recipes/deepseek-r1/sglang-wideep/
    tep16p-dep16d-disagg.yaml:63 (--ep-size 16)."""

    supports_packed_prefill = True
    supports_ring_prefill = False  # long MLA prompts take the chunked path
    supports_mesh = True
    supports_logprobs = True
    supports_embeddings = True
    supports_multimodal = False
    supports_spec_decode = True  # prompt-lookup verify (engine/spec.py)

    def __init__(self):
        from dynamo_tpu.models import mla

        self.m = mla

    def init_params(self, spec, key):
        return self.m.init_params(spec, key)

    def param_shardings(self, spec, mesh):
        return self.m.param_shardings(spec, mesh)

    def cache_shardings(self, mesh, kv_dtype="bf16"):
        s = self.m.cache_shardings(mesh, kv_dtype)
        from jax.sharding import NamedSharding, PartitionSpec as P

        # placeholder v_pages is a single replicated leaf either way
        return s, NamedSharding(mesh, P())

    def init_cache(self, spec, num_pages, page_size, kv_dtype="bf16"):
        cache = self.m.init_cache(
            spec, num_pages, page_size, kv_dtype=kv_dtype
        )
        return cache, jnp.zeros((1,), jnp.int8)  # inert v_pages placeholder

    def prefill(self, spec, params, tokens, bt, start, k, v, n, mesh=None):
        logits, cache = self.m.prefill_forward(
            spec, params, tokens, bt, start, k, n, mesh=mesh
        )
        # engine contract: (logits, k, v, moe_dropped)
        return logits, cache, v, jnp.zeros((), jnp.int32)

    def prefill_batch(self, spec, params, tokens, bts, starts, k, v, ns,
                      mesh=None):
        logits, cache = self.m.prefill_forward_batch(
            spec, params, tokens, bts, starts, k, ns, mesh=mesh
        )
        return logits, cache, v, jnp.zeros((), jnp.int32)

    def verify(self, spec, params, tokens, bts, starts, k, v, ns,
               mesh=None, allowed=None):
        targets, cache = self.m.verify_forward(
            spec, params, tokens, bts, starts, k, ns, mesh=mesh,
            allowed=allowed,
        )
        return targets, cache, v, jnp.zeros((), jnp.int32)

    def decode_steps(self, spec, params, tokens, bts, lens, k, v, active,
                     temps, topk, topp, seeds, steps, *, n_steps, n_logprobs,
                     mesh=None, allowed=None):
        result = self.m.decode_steps(
            spec, params, tokens, bts, lens, k, active, temps, topk, topp,
            seeds, steps, n_steps=n_steps, n_logprobs=n_logprobs, mesh=mesh,
            allowed=allowed,
        )
        if n_logprobs > 0:
            out, lp, ti, tv, cache = result
            return out, lp, ti, tv, cache, v
        out, cache = result
        return out, cache, v

    def extract_pages(self, k, v, page_ids):
        # latent blocks [L, n, page, D]; the v slot stays inert (kept in
        # kvbm/transfer payloads so block plumbing is shape-agnostic)
        blocks = _extract_latent(k, page_ids)
        n = page_ids.shape[0]
        return blocks, jnp.zeros((1, n), jnp.int8)

    def insert_pages(self, k, v, page_ids, kb, vb):
        return _insert_latent(k, page_ids, kb), v

    def embed_forward(self, spec, params, tokens, num_tokens):
        return self.m.embed_forward(spec, params, tokens, num_tokens)


@jax.jit
def _extract_latent(cache, page_ids):
    from dynamo_tpu.ops.quant import is_quant, pack_pages

    if is_quant(cache):
        # fp8 cache: values + scales leave as ONE packed uint8 payload
        # per (layer, page) — KVBM tiers/transfer carry exactly those
        # bytes (see llama._extract_kv_pages_impl)
        return pack_pages(cache, page_ids)
    return cache[:, page_ids]


# donated: the latent cache updates in place (disagg resume / KVBM
# onboard install whole pages into the live pool — a copy here doubles
# the cache's HBM footprint for the duration of the insert)
@partial(jax.jit, donate_argnums=(0,))
def _insert_latent_impl(cache, page_ids, blocks):
    from dynamo_tpu.ops.quant import QuantPool, is_quant, unpack_pages

    if is_quant(cache):
        vals, scale = unpack_pages(
            blocks, cache.vals.shape[2:], cache.scale.shape[2:]
        )
        return QuantPool(
            cache.vals.at[:, page_ids].set(vals),
            cache.scale.at[:, page_ids].set(scale),
        )
    return cache.at[:, page_ids].set(blocks)


def _insert_latent(cache, page_ids, blocks):
    return _insert_latent_impl(cache, page_ids, jnp.asarray(blocks))


def get_family(spec: ModelSpec) -> Any:
    return MlaFamily() if spec.is_mla else GqaFamily(spec)
