"""Model definitions (pure JAX, mesh-shardable).

llama.py covers the llama family (llama-2/3 dense: the reference's
recipes/llama-3-70b target); moe.py adds mixture-of-experts layers with
expert parallelism (gpt-oss-120b / deepseek-r1-class configs).
"""
