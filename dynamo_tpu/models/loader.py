"""Checkpoint loading: HF-format safetensors -> the functional param pytree.

TPU-native counterpart of the reference's LocalModel build path
(lib/llm/src/local_model.rs:323 ``build``, hub.rs model fetch): given a
local model directory containing ``config.json`` + ``*.safetensors``, derive
the ModelSpec and materialize ``models/llama.py``-shaped params, cast to the
serving dtype and (optionally) placed with tensor-parallel shardings in one
pass — each tensor is read from the memory-mapped safetensors file, mapped,
and ``jax.device_put`` straight to its sharding, so host RAM never holds a
second full copy of the checkpoint.

Also provides ``save_params`` (params -> HF-format safetensors) so tests can
round-trip a generated checkpoint hermetically (no downloads in this
environment), and so converted checkpoints can be re-exported.

Weight-name mapping (HF LlamaForCausalLM / MixtralForCausalLM):

    model.embed_tokens.weight            -> embed            [V, d]
    model.norm.weight                    -> final_norm       [d]
    lm_head.weight                       -> lm_head (T)      [d, V]
    ...layers.{i}.input_layernorm        -> attn_norm        [d]
    ...layers.{i}.self_attn.{q,k,v,o}_proj.weight -> wq/wk/wv/wo (T)
    ...layers.{i}.post_attention_layernorm -> mlp_norm       [d]
    ...layers.{i}.mlp.{gate,up,down}_proj.weight -> w_gate/w_up/w_down (T)
    ...layers.{i}.block_sparse_moe.gate.weight -> moe.router (T, f32)
    ...layers.{i}.block_sparse_moe.experts.{e}.w{1,3,2}.weight
                                         -> moe.w_gate/w_up/w_down[e] (T)

HF stores linear weights as [out_features, in_features]; our forward is
``x @ W`` so every projection transposes on load. The RoPE convention
(half-split rotate, not interleaved) matches HF's exported llama weights,
so no permutation is needed.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import ModelSpec

Params = dict[str, Any]

__all__ = [
    "spec_from_hf_config",
    "load_params",
    "save_params",
    "load_model_dir",
]


# ------------------------------------------------------------- spec <-> config


def spec_from_hf_config(cfg: dict, name: str | None = None) -> ModelSpec:
    """Map an HF ``config.json`` dict to a ModelSpec (llama/mixtral family)."""
    model_type = cfg.get("model_type", "llama")
    heads = int(cfg["num_attention_heads"])
    hidden = int(cfg["hidden_size"])
    moe = {}
    n_experts = int(
        cfg.get("num_local_experts") or cfg.get("num_experts")
        or cfg.get("n_routed_experts") or 0
    )
    if model_type in ("mixtral", "qwen2_moe", "qwen3_moe", "gpt_oss") or n_experts:
        moe = dict(
            num_experts=n_experts,
            num_experts_per_token=int(
                cfg.get("num_experts_per_tok")
                or cfg.get("experts_per_token") or 2
            ),
            moe_intermediate_size=int(
                cfg.get("moe_intermediate_size") or cfg["intermediate_size"]
            ),
        )
    # gpt-oss attention extras: sinks + per-layer sliding windows +
    # projection/expert biases + clamped swiglu (HF GptOssConfig)
    extras: dict = {}
    if model_type == "gpt_oss":
        n_layers = int(cfg["num_hidden_layers"])
        extras = dict(
            sliding_window=int(cfg.get("sliding_window") or 0),
            # HF GptOssConfig defaults to alternating sliding/full when
            # layer_types is absent — mirror that, not all-sliding
            layer_types=tuple(
                cfg.get("layer_types")
                or ("sliding_attention" if i % 2 == 0 else "full_attention"
                    for i in range(n_layers))
            ),
            attn_sinks=True,
            attn_bias=bool(cfg.get("attention_bias", True)),
            moe_bias=True,
            swiglu_limit=float(cfg.get("swiglu_limit") or 7.0),
            swiglu_alpha=1.702,
        )
    if model_type in ("deepseek_v2", "deepseek_v3"):
        # DeepSeek MLA checkpoints store rope dims pair-interleaved
        # (HF DeepseekV3Config.rope_interleave defaults True)
        extras["rope_interleave"] = bool(cfg.get("rope_interleave", True))
        if n_experts:
            # V3 noaux_tc routing (HF DeepseekV3TopkRouter defaults)
            # fallbacks = the HF DeepseekV3Config class defaults, so a
            # minimal config.json routes exactly as transformers would
            extras.update(
                moe_scoring=str(cfg.get("scoring_func") or "sigmoid"),
                n_group=int(cfg.get("n_group") or 8),
                topk_group=int(cfg.get("topk_group") or 4),
                routed_scaling_factor=float(
                    cfg.get("routed_scaling_factor") or 2.5
                ),
                norm_topk_prob=bool(cfg.get("norm_topk_prob", True)),
            )
    # YaRN rope scaling (gpt-oss, DeepSeek-R1)
    rs = cfg.get("rope_scaling") or {}
    if (rs.get("rope_type") or rs.get("type")) == "yarn":
        extras.update(
            rope_scaling_factor=float(rs["factor"]),
            rope_orig_max_pos=int(
                rs.get("original_max_position_embeddings")
                or cfg.get("max_position_embeddings") or 4096
            ),
            rope_beta_fast=float(rs.get("beta_fast") or 32),
            rope_beta_slow=float(rs.get("beta_slow") or 1),
            rope_mscale=float(rs.get("mscale") or 0),
            rope_mscale_all_dim=float(rs.get("mscale_all_dim") or 0),
            rope_truncate=bool(rs.get("truncate", True)),
        )
    return ModelSpec(
        name=name or cfg.get("_name_or_path") or model_type,
        vocab_size=int(cfg["vocab_size"]),
        hidden_size=hidden,
        intermediate_size=int(cfg["intermediate_size"]),
        num_layers=int(cfg["num_hidden_layers"]),
        num_heads=heads,
        num_kv_heads=int(cfg.get("num_key_value_heads", heads)),
        head_dim=int(cfg.get("head_dim") or hidden // heads),
        rope_theta=float(cfg.get("rope_theta", 500000.0)),
        rms_eps=float(cfg.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(cfg.get("tie_word_embeddings", False)),
        # transformers >= 4.56 writes "dtype"; older wrote "torch_dtype"
        dtype=(
            ckpt_dtype
            if (ckpt_dtype := cfg.get("dtype") or cfg.get("torch_dtype"))
            in ("bfloat16", "float32", "float16")
            else "bfloat16"
        ),
        # DeepSeek-family extras (0/absent on other models)
        n_shared_experts=int(cfg.get("n_shared_experts") or 0),
        first_k_dense=int(cfg.get("first_k_dense_replace") or 0),
        kv_lora_rank=int(cfg.get("kv_lora_rank") or 0),
        qk_nope_head_dim=int(cfg.get("qk_nope_head_dim") or 0),
        qk_rope_head_dim=int(cfg.get("qk_rope_head_dim") or 0),
        v_head_dim=int(cfg.get("v_head_dim") or 0),
        q_lora_rank=int(cfg.get("q_lora_rank") or 0),
        **moe,
        **extras,
    )


def hf_config_from_spec(spec: ModelSpec) -> dict:
    """Inverse of spec_from_hf_config (save_params / re-export): every
    architecture field the loader reads must round-trip, or an exported
    checkpoint silently loses features on reload."""
    if spec.kv_lora_rank:
        model_type = "deepseek_v3"
    elif spec.attn_sinks:
        model_type = "gpt_oss"
    elif spec.num_experts:
        model_type = "mixtral"
    else:
        model_type = "llama"
    cfg = {
        "model_type": model_type,
        "vocab_size": spec.vocab_size,
        "hidden_size": spec.hidden_size,
        "intermediate_size": (
            spec.moe_intermediate_size
            if spec.num_experts and not spec.kv_lora_rank
            else spec.intermediate_size
        ),
        "num_hidden_layers": spec.num_layers,
        "num_attention_heads": spec.num_heads,
        "num_key_value_heads": spec.num_kv_heads,
        "head_dim": spec.head_dim,
        "rope_theta": spec.rope_theta,
        "rms_norm_eps": spec.rms_eps,
        "tie_word_embeddings": spec.tie_embeddings,
        "dtype": spec.dtype,  # transformers >= 4.56 key (loader reads both)
        "torch_dtype": spec.dtype,
    }
    if spec.num_experts:
        cfg["num_local_experts"] = spec.num_experts
        cfg["num_experts_per_tok"] = spec.num_experts_per_token
        cfg["moe_intermediate_size"] = spec.moe_intermediate_size
    if model_type == "gpt_oss":
        cfg.update(
            sliding_window=spec.sliding_window,
            layer_types=list(spec.layer_types),
            attention_bias=spec.attn_bias,
            swiglu_limit=spec.swiglu_limit,
        )
    if spec.kv_lora_rank:
        cfg.update(
            n_routed_experts=spec.num_experts,
            n_shared_experts=spec.n_shared_experts,
            first_k_dense_replace=spec.first_k_dense,
            kv_lora_rank=spec.kv_lora_rank,
            q_lora_rank=spec.q_lora_rank or None,
            qk_nope_head_dim=spec.qk_nope_head_dim,
            qk_rope_head_dim=spec.qk_rope_head_dim,
            v_head_dim=spec.v_head_dim,
            scoring_func=spec.moe_scoring,
            n_group=spec.n_group,
            topk_group=spec.topk_group,
            routed_scaling_factor=spec.routed_scaling_factor,
            norm_topk_prob=spec.norm_topk_prob,
            # our in-memory params are HALF-SPLIT (load_params permutes
            # interleaved checkpoints on the way in) — an exported
            # checkpoint must say so, or reload would de-interleave twice
            rope_interleave=False,
        )
    if spec.rope_scaling_factor:
        cfg["rope_scaling"] = {
            "rope_type": "yarn",
            "factor": spec.rope_scaling_factor,
            "original_max_position_embeddings": spec.rope_orig_max_pos,
            "beta_fast": spec.rope_beta_fast,
            "beta_slow": spec.rope_beta_slow,
            "truncate": spec.rope_truncate,
            **(
                {"mscale": spec.rope_mscale,
                 "mscale_all_dim": spec.rope_mscale_all_dim}
                if spec.rope_mscale or spec.rope_mscale_all_dim
                else {}
            ),
        }
        # HF convention: the POST-scaling context window (the original
        # lives inside rope_scaling)
        cfg["max_position_embeddings"] = int(
            spec.rope_orig_max_pos * spec.rope_scaling_factor
        )
    return cfg


# ------------------------------------------------------------------- name map


def _moe_scheme(names: set[str] | None) -> str:
    """Which MoE tensor-naming convention a checkpoint uses.

    mixtral:  model.layers.N.block_sparse_moe.gate.weight + experts.E.w{1,2,3}
    qwen_moe: model.layers.N.mlp.gate.weight + experts.E.{gate,up,down}_proj
    gpt_oss:  model.layers.N.mlp.router.weight + FUSED 3D
              experts.gate_up_proj [E, d, 2f] (gate/up interleaved on the
              last axis) and experts.down_proj [E, f, d]
    """
    if not names:
        return "mixtral"
    for n in names:
        if ".block_sparse_moe." in n:
            return "mixtral"
        if ".mlp.experts.gate_up_proj" in n:
            return "gpt_oss"
        if ".mlp.experts.0." in n:
            return "qwen_moe"
    return "mixtral"


def _dest_map_mla(
    spec: ModelSpec,
) -> dict[str, tuple[tuple, bool, str | None]]:
    """DeepSeek-family (MLA) tensor names -> models/mla.py tree paths.
    ``kv_b_proj`` (the fused per-head W_uk/W_uv) splits in load_params."""
    m: dict[str, tuple[tuple, bool, str | None]] = {
        "model.embed_tokens.weight": (("embed",), False, None),
        "model.norm.weight": (("final_norm",), False, None),
    }
    if not spec.tie_embeddings:
        m["lm_head.weight"] = (("lm_head",), True, None)
    for i in range(spec.num_layers):
        p = f"model.layers.{i}."
        li = ("layers", i)
        m[p + "input_layernorm.weight"] = (li + ("attn_norm",), False, None)
        m[p + "post_attention_layernorm.weight"] = (li + ("mlp_norm",), False, None)
        m[p + "self_attn.o_proj.weight"] = (li + ("wo",), True, None)
        m[p + "self_attn.kv_a_proj_with_mqa.weight"] = (
            li + ("w_kv_a",), True, None
        )
        m[p + "self_attn.kv_a_layernorm.weight"] = (li + ("kv_norm",), False, None)
        if spec.q_lora_rank:
            m[p + "self_attn.q_a_proj.weight"] = (li + ("wq_a",), True, None)
            m[p + "self_attn.q_a_layernorm.weight"] = (li + ("q_norm",), False, None)
            m[p + "self_attn.q_b_proj.weight"] = (li + ("wq_b",), True, None)
        else:
            m[p + "self_attn.q_proj.weight"] = (li + ("wq",), True, None)
        if spec.num_experts and i >= spec.first_k_dense:
            m[p + "mlp.gate.weight"] = (li + ("moe", "router"), True, "float32")
            if spec.moe_scoring == "sigmoid":
                m[p + "mlp.gate.e_score_correction_bias"] = (
                    li + ("moe", "score_bias"), False, "float32"
                )
            for e in range(spec.num_experts):
                ep = p + f"mlp.experts.{e}."
                m[ep + "gate_proj.weight"] = (li + ("moe", "w_gate", e), True, None)
                m[ep + "up_proj.weight"] = (li + ("moe", "w_up", e), True, None)
                m[ep + "down_proj.weight"] = (li + ("moe", "w_down", e), True, None)
            if spec.n_shared_experts:
                sp_ = p + "mlp.shared_experts."
                m[sp_ + "gate_proj.weight"] = (li + ("shared", "w_gate"), True, None)
                m[sp_ + "up_proj.weight"] = (li + ("shared", "w_up"), True, None)
                m[sp_ + "down_proj.weight"] = (li + ("shared", "w_down"), True, None)
        else:
            for hf, ours in (("gate_proj", "w_gate"), ("up_proj", "w_up"),
                             ("down_proj", "w_down")):
                m[p + f"mlp.{hf}.weight"] = (li + (ours,), True, None)
    return m


def _dest_map(
    spec: ModelSpec, names: set[str] | None = None
) -> dict[str, tuple[tuple, bool, str | None]]:
    """HF tensor name -> ((pytree path), transpose, dtype-override).

    ``names`` (the checkpoint's tensor set) selects the MoE naming scheme;
    gpt-oss fused expert tensors (weights AND biases) are handled
    separately in load_params (they split, which this map cannot
    express). gpt-oss attention sinks, projection biases, and router
    bias map here when the spec enables them.
    """
    m: dict[str, tuple[tuple, bool, str | None]] = {
        "model.embed_tokens.weight": (("embed",), False, None),
        "model.norm.weight": (("final_norm",), False, None),
    }
    if not spec.tie_embeddings:
        m["lm_head.weight"] = (("lm_head",), True, None)
    scheme = _moe_scheme(names) if spec.num_experts else None
    for i in range(spec.num_layers):
        p = f"model.layers.{i}."
        li = ("layers", i)
        m[p + "input_layernorm.weight"] = (li + ("attn_norm",), False, None)
        m[p + "post_attention_layernorm.weight"] = (li + ("mlp_norm",), False, None)
        for hf, ours in (("q_proj", "wq"), ("k_proj", "wk"),
                         ("v_proj", "wv"), ("o_proj", "wo")):
            m[p + f"self_attn.{hf}.weight"] = (li + (ours,), True, None)
        if spec.attn_bias:
            for hf, ours in (("q_proj", "bq"), ("k_proj", "bk"),
                             ("v_proj", "bv"), ("o_proj", "bo")):
                m[p + f"self_attn.{hf}.bias"] = (li + (ours,), False, None)
        if spec.attn_sinks:
            m[p + "self_attn.sinks"] = (li + ("sinks",), False, None)
        if spec.num_experts:
            if scheme == "mixtral":
                mp = p + "block_sparse_moe."
                m[mp + "gate.weight"] = (li + ("moe", "router"), True, "float32")
                for e in range(spec.num_experts):
                    ep = mp + f"experts.{e}."
                    m[ep + "w1.weight"] = (li + ("moe", "w_gate", e), True, None)
                    m[ep + "w3.weight"] = (li + ("moe", "w_up", e), True, None)
                    m[ep + "w2.weight"] = (li + ("moe", "w_down", e), True, None)
            elif scheme == "qwen_moe":
                mp = p + "mlp."
                m[mp + "gate.weight"] = (li + ("moe", "router"), True, "float32")
                for e in range(spec.num_experts):
                    ep = mp + f"experts.{e}."
                    m[ep + "gate_proj.weight"] = (li + ("moe", "w_gate", e), True, None)
                    m[ep + "up_proj.weight"] = (li + ("moe", "w_up", e), True, None)
                    m[ep + "down_proj.weight"] = (li + ("moe", "w_down", e), True, None)
            else:  # gpt_oss: router here; fused experts in load_params
                m[p + "mlp.router.weight"] = (li + ("moe", "router"), True, "float32")
                if spec.moe_bias:
                    m[p + "mlp.router.bias"] = (
                        li + ("moe", "router_bias"), False, "float32"
                    )
        else:
            for hf, ours in (("gate_proj", "w_gate"), ("up_proj", "w_up"),
                             ("down_proj", "w_down")):
                m[p + f"mlp.{hf}.weight"] = (li + (ours,), True, None)
    return m


def _tree_set(tree: Params, path: tuple, value) -> None:
    node = tree
    for key in path[:-1]:
        if isinstance(key, int):
            while len(node) <= key:
                node.append({})
            node = node[key]
        else:
            node = node.setdefault(key, [] if key in ("layers",) else {})
    node[path[-1]] = value


def _tree_get(tree: Params, path: tuple):
    node = tree
    for key in path:
        node = node[key]
    return node


# ------------------------------------------------------------------ load/save


def load_params(
    spec: ModelSpec,
    model_dir: str,
    *,
    mesh=None,
    dtype: str | None = None,
) -> Params:
    """Read ``*.safetensors`` under ``model_dir`` into the llama param tree.

    Tensors stream one at a time: mmap-read -> transpose/cast -> device_put
    (with the TP sharding when ``mesh`` is given). MoE expert tensors
    (stored per-expert in HF checkpoints) are stacked onto the leading
    expert axis our layer expects.
    """
    from safetensors import safe_open

    dtype = dtype or spec.dtype
    files = sorted(
        os.path.join(model_dir, f)
        for f in os.listdir(model_dir)
        if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors files in {model_dir}")
    all_names: set[str] = set()
    for path_file in files:
        with safe_open(path_file, framework="numpy") as f:
            all_names.update(f.keys())
    if spec.kv_lora_rank:
        dest = _dest_map_mla(spec)
        fused_gpt_oss = False
    else:
        dest = _dest_map(spec, all_names)
        fused_gpt_oss = bool(
            spec.num_experts and _moe_scheme(all_names) == "gpt_oss"
        )

    params: Params = {}
    seen: set[str] = set()
    # MoE expert leaves accumulate per-expert then stack
    pending_experts: dict[tuple, dict[int, np.ndarray]] = {}

    shardings = None
    if mesh is not None:
        if spec.kv_lora_rank:
            raise NotImplementedError(
                "TP shardings for MLA checkpoints are not wired yet; "
                "load without a mesh"
            )
        from dynamo_tpu.models.llama import param_shardings

        shardings = param_shardings(spec, mesh)

    def place(path: tuple, arr: np.ndarray, dt: str):
        x = jnp.asarray(arr, dtype=jnp.dtype(dt))
        if shardings is not None:
            x = jax.device_put(x, _tree_get(shardings, path))
        _tree_set(params, path, x)

    skipped_extras: list[str] = []
    for path_file in files:
        with safe_open(path_file, framework="numpy") as f:
            for name in f.keys():
                if name not in dest:
                    if spec.kv_lora_rank and name.endswith(
                        "self_attn.kv_b_proj.weight"
                    ):
                        # fused per-head up-projections [H*(dn+dv), dc]:
                        # split into w_uk [H, dc, dn] / w_uv [H, dc, dv]
                        li = ("layers", int(name.split(".")[2]))
                        arr = f.get_tensor(name)
                        H, dn, dv = (spec.num_heads, spec.qk_nope_head_dim,
                                     spec.v_head_dim)
                        arr = arr.reshape(H, dn + dv, spec.kv_lora_rank)
                        place(li + ("w_uk",),
                              np.ascontiguousarray(
                                  arr[:, :dn].transpose(0, 2, 1)), dtype)
                        place(li + ("w_uv",),
                              np.ascontiguousarray(
                                  arr[:, dn:].transpose(0, 2, 1)), dtype)
                        seen.add(name)
                    elif fused_gpt_oss and name.endswith(
                        (".mlp.experts.gate_up_proj", ".mlp.experts.down_proj")
                    ):
                        # fused 3D expert tensors, already [in, out] per
                        # expert; gate/up interleave on the last axis
                        li = ("layers", int(name.split(".")[2]), "moe")
                        arr = f.get_tensor(name)
                        if name.endswith("gate_up_proj"):
                            place(li + ("w_gate",), arr[..., 0::2], dtype)
                            place(li + ("w_up",), arr[..., 1::2], dtype)
                        else:
                            place(li + ("w_down",), arr, dtype)
                        seen.add(name)
                    elif fused_gpt_oss and spec.moe_bias and name.endswith(
                        (".mlp.experts.gate_up_proj_bias",
                         ".mlp.experts.down_proj_bias")
                    ):
                        li = ("layers", int(name.split(".")[2]), "moe")
                        arr = f.get_tensor(name)
                        if name.endswith("gate_up_proj_bias"):
                            place(li + ("b_gate",), arr[..., 0::2], dtype)
                            place(li + ("b_up",), arr[..., 1::2], dtype)
                        else:
                            place(li + ("b_down",), arr, dtype)
                        seen.add(name)
                    elif name.endswith(("_bias", ".bias", ".sinks")):
                        skipped_extras.append(name)
                    continue
                path, transpose, dt_override = dest[name]
                arr = f.get_tensor(name)
                if transpose:
                    arr = np.ascontiguousarray(arr.T)
                if spec.kv_lora_rank and spec.rope_interleave:
                    arr = _deinterleave_rope_cols(spec, name, arr)
                seen.add(name)
                dt = dt_override or dtype
                if len(path) >= 2 and isinstance(path[-1], int) and path[-2] in (
                    "w_gate", "w_up", "w_down"
                ):
                    # per-expert tensor: buffer until all experts present
                    key = path[:-1]
                    pending_experts.setdefault(key, {})[path[-1]] = arr.astype(
                        _np_dtype(dt)
                    )
                    bucket = pending_experts[key]
                    if len(bucket) == spec.num_experts:
                        stacked = np.stack(
                            [bucket[e] for e in range(spec.num_experts)]
                        )
                        place(key, stacked, dt)
                        del pending_experts[key]
                else:
                    place(path, arr, dt)

    dest_expected = set(dest)
    if spec.kv_lora_rank:
        dest_expected |= {
            f"model.layers.{i}.self_attn.kv_b_proj.weight"
            for i in range(spec.num_layers)
        }
    if fused_gpt_oss:
        tails = ["gate_up_proj", "down_proj"]
        if spec.moe_bias:
            tails += ["gate_up_proj_bias", "down_proj_bias"]
        dest_expected |= {
            f"model.layers.{i}.mlp.experts.{t}"
            for i in range(spec.num_layers)
            for t in tails
        }
    if skipped_extras:
        import logging

        logging.getLogger("dynamo.loader").warning(
            "skipped %d tensors with no destination in this spec "
            "(unexpected for supported architectures), e.g. %s",
            len(skipped_extras), sorted(skipped_extras)[:3],
        )
    missing = dest_expected - seen
    if missing:
        raise ValueError(
            f"checkpoint {model_dir} missing {len(missing)} tensors, e.g. "
            f"{sorted(missing)[:4]}"
        )
    return params


def _deinterleave_rope_cols(
    spec: ModelSpec, name: str, arr: np.ndarray
) -> np.ndarray:
    """DeepSeek ``rope_interleave`` handling: checkpoint rope dims are
    pair-interleaved ([x0, y0, x1, y1, ...]); our rope is half-split
    ([x0, x1, ..., y0, y1, ...]). Permuting the q_rope and k_rope
    PROJECTION COLUMNS at load is exact — rope dims only ever meet in
    q.k dot products, and both sides get the same permutation (HF
    instead keeps the weights and swaps in apply_rotary_pos_emb_interleave).
    ``arr`` is already transposed to [in, out]."""
    dr = spec.qk_rope_head_dim
    perm = np.concatenate([np.arange(0, dr, 2), np.arange(1, dr, 2)])
    if name.endswith(("self_attn.q_b_proj.weight", "self_attn.q_proj.weight")):
        H, dn = spec.num_heads, spec.qk_nope_head_dim
        out = arr.reshape(arr.shape[0], H, dn + dr)
        out = np.concatenate([out[..., :dn], out[..., dn:][..., perm]], axis=-1)
        return np.ascontiguousarray(out.reshape(arr.shape))
    if name.endswith("self_attn.kv_a_proj_with_mqa.weight"):
        dc = spec.kv_lora_rank
        return np.ascontiguousarray(
            np.concatenate([arr[:, :dc], arr[:, dc:][:, perm]], axis=1)
        )
    return arr


def _np_dtype(dt: str):
    if dt == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(dt)


def save_params(
    spec: ModelSpec, params: Params, model_dir: str, *, shard_bytes: int = 2**31
) -> None:
    """Write params as HF-format safetensors + config.json (test round-trips
    and checkpoint re-export). Large trees split into multiple shard files."""
    from safetensors.numpy import save_file

    os.makedirs(model_dir, exist_ok=True)
    if spec.kv_lora_rank:
        dest = _dest_map_mla(spec)
    elif spec.moe_bias:
        # gpt-oss exports use the FUSED expert naming (synthesized
        # below); the name hint selects the gpt_oss scheme so the dest
        # map carries router(+bias) but not mixtral per-expert entries
        dest = _dest_map(
            spec, names={"model.layers.0.mlp.experts.gate_up_proj"}
        )
    else:
        dest = _dest_map(spec)
    tensors: dict[str, np.ndarray] = {}
    for name, (path, transpose, _dt) in dest.items():
        if len(path) >= 2 and isinstance(path[-1], int):
            arr = np.asarray(_tree_get(params, path[:-1])[path[-1]])
        else:
            arr = np.asarray(_tree_get(params, path))
        if transpose:
            arr = np.ascontiguousarray(arr.T)
        tensors[name] = arr
    if spec.moe_bias and not spec.kv_lora_rank:
        # gpt-oss fused expert tensors: re-interleave gate/up (weights
        # AND biases) the way load_params de-interleaves them
        for i, lp in enumerate(params["layers"]):
            moe = lp["moe"]
            wg = np.asarray(moe["w_gate"])
            wu = np.asarray(moe["w_up"])
            fused_w = np.empty(
                (wg.shape[0], wg.shape[1], 2 * wg.shape[2]), wg.dtype
            )
            fused_w[..., 0::2] = wg
            fused_w[..., 1::2] = wu
            bg = np.asarray(moe["b_gate"])
            bu = np.asarray(moe["b_up"])
            fused_b = np.empty((bg.shape[0], 2 * bg.shape[1]), bg.dtype)
            fused_b[..., 0::2] = bg
            fused_b[..., 1::2] = bu
            p = f"model.layers.{i}.mlp.experts."
            tensors[p + "gate_up_proj"] = fused_w
            tensors[p + "gate_up_proj_bias"] = fused_b
            tensors[p + "down_proj"] = np.asarray(moe["w_down"])
            tensors[p + "down_proj_bias"] = np.asarray(moe["b_down"])
    if spec.kv_lora_rank:
        # re-fuse the per-head up-projections into HF's kv_b_proj layout
        # (load_params splits them; see the kv_b_proj branch there)
        H, dn, dv, dc = (spec.num_heads, spec.qk_nope_head_dim,
                         spec.v_head_dim, spec.kv_lora_rank)
        for i, lp in enumerate(params["layers"]):
            fused = np.concatenate(
                [np.asarray(lp["w_uk"]).transpose(0, 2, 1),
                 np.asarray(lp["w_uv"]).transpose(0, 2, 1)], axis=1
            ).reshape(H * (dn + dv), dc)
            tensors[f"model.layers.{i}.self_attn.kv_b_proj.weight"] = (
                np.ascontiguousarray(fused)
            )

    shards: list[dict[str, np.ndarray]] = [{}]
    size = 0
    for name in sorted(tensors):
        nbytes = tensors[name].nbytes
        if size + nbytes > shard_bytes and shards[-1]:
            shards.append({})
            size = 0
        shards[-1][name] = tensors[name]
        size += nbytes
    n = len(shards)
    for i, shard in enumerate(shards):
        fname = (
            "model.safetensors" if n == 1
            else f"model-{i + 1:05d}-of-{n:05d}.safetensors"
        )
        save_file(shard, os.path.join(model_dir, fname))
    with open(os.path.join(model_dir, "config.json"), "w") as f:
        json.dump(hf_config_from_spec(spec), f, indent=2)


def load_model_dir(
    model_dir: str, *, mesh=None, dtype: str | None = None,
    name: str | None = None,
) -> tuple[ModelSpec, Params]:
    """One-call path: config.json -> spec, safetensors -> params."""
    with open(os.path.join(model_dir, "config.json")) as f:
        cfg = json.load(f)
    spec = spec_from_hf_config(cfg, name=name or os.path.basename(model_dir.rstrip("/")))
    return spec, load_params(spec, model_dir, mesh=mesh, dtype=dtype)
