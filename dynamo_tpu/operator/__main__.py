"""Operator process: ``python -m dynamo_tpu.operator --hub ... --name g``.

Reconciles the named DynamoGraphDeployment (hub key ``v1/dgd/{name}``)
with the chosen backend; ``--backend kubectl`` scales Kubernetes
deployments instead of local processes. Prints OPERATOR_READY.
"""

from __future__ import annotations

import argparse
import asyncio
import logging

from dynamo_tpu.operator.backends import make_backend
from dynamo_tpu.operator.controller import Reconciler
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.hub_client import connect_hub
from dynamo_tpu.runtime.logging_util import setup_logging


async def _amain(args: argparse.Namespace) -> None:
    rcfg = RuntimeConfig.from_env()
    if args.hub:
        rcfg.override_hub(args.hub)
    if not rcfg.hub_target():
        # an operator against a process-local in-memory hub reconciles
        # nothing anyone can see — fail loudly, not "successfully"
        raise SystemExit(
            "operator: --hub (or DYN_HUB_ADDRESSES / DYN_HUB_ADDRESS) "
            "is required"
        )
    hub = await connect_hub(rcfg.hub_target())
    backend = (
        make_backend(
            "kubectl", namespace=args.k8s_namespace, image=args.k8s_image,
            hub=rcfg.hub_target(), graph=args.name,
        )
        if args.backend == "kubectl"
        else make_backend("process")
    )
    rec = await Reconciler(
        hub, args.name, backend, interval_s=args.interval
    ).start()
    crd_sync = None
    if args.from_crd:
        from dynamo_tpu.operator.crd_sync import CrdSync

        crd_sync = await CrdSync(
            hub, args.name, namespace=args.k8s_namespace
        ).start()
    print("OPERATOR_READY", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        if crd_sync is not None:
            await crd_sync.close()
        await rec.close()
        await hub.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser("dynamo-tpu operator")
    p.add_argument("--hub", default="",
                   help="hub address or comma-separated replica list "
                   "(default: DYN_HUB_ADDRESSES / DYN_HUB_ADDRESS env)")
    p.add_argument("--name", default="default",
                   help="DynamoGraphDeployment name to reconcile")
    p.add_argument("--backend", default="process",
                   choices=("process", "kubectl"))
    p.add_argument("--k8s-namespace", default="default")
    p.add_argument("--k8s-image", default="",
                   help="container image for MANAGED mode: the operator "
                   "renders+applies full Deployment/Service objects; "
                   "empty = scale-only (Deployments created externally)")
    p.add_argument("--interval", type=float, default=1.0)
    p.add_argument("--from-crd", action="store_true",
                   help="watch the DynamoGraphDeployment CRD on the "
                   "apiserver (deploy/k8s/crd.yaml) and mirror it into "
                   "the hub resource + push status back")
    args = p.parse_args(argv)
    setup_logging()
    try:
        asyncio.run(_amain(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
