"""Deployment operator: reconcile a declared serving graph into reality.

Role of the reference's Go operator (deploy/cloud/operator/: CRDs
DynamoGraphDeployment/DynamoComponentDeployment, controllers, etcd
cleanup on scale-down) rebuilt for this stack: the GRAPH — services,
their launch commands, replica counts — is data in the hub KV; a
reconciler process watches desired vs. observed state and converges by
spawning/stopping worker processes (ProcessBackend) or scaling
Kubernetes deployments (KubectlBackend). The SLA planner closes its
loop through the same path the reference uses (KubernetesConnector
patches DGD replicas): its VirtualConnector writes desired counts to
the hub, and the operator applies them to the graph's prefill/decode
services.
"""

from dynamo_tpu.operator.graph import DynamoGraphDeployment, ServiceSpec
from dynamo_tpu.operator.controller import Reconciler

__all__ = ["DynamoGraphDeployment", "ServiceSpec", "Reconciler"]
