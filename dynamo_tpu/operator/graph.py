"""DynamoGraphDeployment: the serving-graph custom resource.

Mirror of the reference CRD
(deploy/cloud/operator/api/v1alpha1/dynamographdeployment_types.go:31-78
``DynamoGraphDeploymentSpec.services``) as plain data: each service has
a launch command (argv template), a replica count, and the component it
registers under (for observed-state matching). The resource lives in
the hub KV under ``v1/dgd/{name}``; edits there are the declarative
API the reconciler converges on.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Any

DGD_KEY = "v1/dgd/{name}"
# status write-back (the CRD status subresource equivalent): the
# reconciler publishes per-service desired/ready counts here each pass
DGD_STATUS_KEY = "v1/dgd-status/{name}"


@dataclass
class ServiceSpec:
    name: str
    replicas: int
    command: list[str]  # argv; must be self-disambiguating across
    # replicas (no fixed ports etc. — replicas launch identically)
    component: str = "backend"  # runtime component it registers under
    # planner wiring: "prefill"/"decode" services accept replica
    # overrides from the planner's desired-replicas key
    role: str = ""  # "", "prefill", "decode"
    # k8s rendering (operator/manifests.py): a port gets a containerPort
    # + ClusterIP Service; env vars are injected into the container
    port: int = 0
    env: dict[str, str] = field(default_factory=dict)
    # multihost (hosts > 1): ONE logical worker spanning N host pods —
    # rendered as an Indexed Job + headless coordinator Service instead
    # of a Deployment (deploy/k8s/worker-multihost.yaml is the golden
    # shape); each replica is its own Job. ProcessBackend treats the
    # service as single-host (the worker's --num-processes flag governs
    # local multi-process runs).
    hosts: int = 1


@dataclass
class DynamoGraphDeployment:
    name: str
    namespace: str = "dynamo"
    services: list[ServiceSpec] = field(default_factory=list)
    revision: int = 0

    @property
    def key(self) -> str:
        return DGD_KEY.format(name=self.name)

    def to_dict(self) -> dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "DynamoGraphDeployment":
        services = [ServiceSpec(**s) for s in d.get("services", [])]
        return cls(
            name=d["name"],
            namespace=d.get("namespace", "dynamo"),
            services=services,
            revision=int(d.get("revision", 0)),
        )

    async def apply(self, hub) -> None:
        """Publish (create or update) this resource."""
        self.revision += 1
        await hub.put(self.key, self.to_dict())

    @classmethod
    async def get(cls, hub, name: str) -> "DynamoGraphDeployment | None":
        raw = await hub.get(DGD_KEY.format(name=name))
        return cls.from_dict(raw) if raw else None
