"""Kubernetes manifest rendering for managed graph deployments.

The reference operator's controllers OWN the component Deployments and
Services — they render them from the DynamoGraphDeployment resource and
let the apiserver perform rolling updates when the pod template changes
(ref deploy/cloud/operator/internal/controller/
dynamocomponentdeployment_controller.go: generateDeployment/
generateService). This module is that rendering step as pure functions:
ServiceSpec -> Deployment (+ Service) dicts, consumed by KubectlBackend
via ``kubectl apply -f -``. The objects are emitted as JSON — valid
YAML, so no extra dependency — and ``apply`` makes create, update, and
scale the same idempotent verb.
"""

from __future__ import annotations

from typing import Any

from dynamo_tpu.operator.graph import ServiceSpec

GRAPH_LABEL = "dynamo-graph"
SERVICE_LABEL = "dynamo-service"


def deployment_name(svc_name: str, name_format: str = "dynamo-{service}") -> str:
    return name_format.format(service=svc_name)


def deployment_manifest(
    svc: ServiceSpec,
    replicas: int,
    *,
    graph: str,
    namespace: str,
    image: str,
    hub: str,
    name_format: str = "dynamo-{service}",
    python: str = "python",
) -> dict[str, Any]:
    """Render the Deployment that runs ``replicas`` copies of a service.

    The container command mirrors ProcessBackend's spawn line
    (``python *spec.command``); DYNAMO_HUB carries the coordination
    address the way the reference injects etcd/NATS endpoints into its
    component pods.
    """
    name = deployment_name(svc.name, name_format)
    labels = {
        "app": name,
        GRAPH_LABEL: graph,
        SERVICE_LABEL: svc.name,
    }
    if svc.role:
        labels["dynamo-role"] = svc.role
    env = [{"name": "DYNAMO_HUB", "value": hub}]
    env += [{"name": k, "value": v} for k, v in sorted(svc.env.items())]
    container: dict[str, Any] = {
        "name": "worker",
        "image": image,
        "command": [python, *svc.command],
        "env": env,
    }
    if svc.port:
        container["ports"] = [{"containerPort": svc.port}]
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace, "labels": labels},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {"containers": [container]},
            },
        },
    }


def service_manifest(
    svc: ServiceSpec,
    *,
    graph: str,
    namespace: str,
    name_format: str = "dynamo-{service}",
) -> dict[str, Any]:
    """ClusterIP Service in front of a port-bearing component (the
    frontend, typically). Only rendered when ``svc.port`` is set."""
    name = deployment_name(svc.name, name_format)
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": {GRAPH_LABEL: graph, SERVICE_LABEL: svc.name},
        },
        "spec": {
            "selector": {"app": name},
            "ports": [{"port": svc.port, "targetPort": svc.port}],
        },
    }


def render_bundle(
    svc: ServiceSpec,
    replicas: int,
    *,
    graph: str,
    namespace: str,
    image: str,
    hub: str,
    name_format: str = "dynamo-{service}",
    python: str = "python",
) -> dict[str, Any]:
    """Everything one service needs, as a single ``v1 List`` document
    (what ``kubectl apply -f -`` consumes in one pass)."""
    items: list[dict[str, Any]] = [
        deployment_manifest(
            svc, replicas, graph=graph, namespace=namespace, image=image,
            hub=hub, name_format=name_format, python=python,
        )
    ]
    if svc.port:
        items.append(
            service_manifest(
                svc, graph=graph, namespace=namespace,
                name_format=name_format,
            )
        )
    return {"apiVersion": "v1", "kind": "List", "items": items}
