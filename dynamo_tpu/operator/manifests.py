"""Kubernetes manifest rendering for managed graph deployments.

The reference operator's controllers OWN the component Deployments and
Services — they render them from the DynamoGraphDeployment resource and
let the apiserver perform rolling updates when the pod template changes
(ref deploy/cloud/operator/internal/controller/
dynamocomponentdeployment_controller.go: generateDeployment/
generateService). This module is that rendering step as pure functions:
ServiceSpec -> Deployment (+ Service) dicts, consumed by KubectlBackend
via ``kubectl apply -f -``. The objects are emitted as JSON — valid
YAML, so no extra dependency — and ``apply`` makes create, update, and
scale the same idempotent verb.
"""

from __future__ import annotations

from typing import Any

from dynamo_tpu.operator.graph import ServiceSpec

GRAPH_LABEL = "dynamo-graph"
SERVICE_LABEL = "dynamo-service"
# multihost: per-replica group index, stamped on the Job + headless
# Service so scale-down / prune can GC groups by label
HOST_INDEX_LABEL = "dynamo-host-index"
# jax.distributed coordinator port on pod 0 of every multihost group
# (deploy/k8s/worker-multihost.yaml)
COORDINATOR_PORT = 9876


def deployment_name(svc_name: str, name_format: str = "dynamo-{service}") -> str:
    return name_format.format(service=svc_name)


def probe_manifests(port: int) -> dict[str, Any]:
    """Kubelet probes against the worker's SystemStatusServer routes
    (runtime/health.py ``/live`` + ``/ready``), in the exact shape the
    hand-written deploy/k8s worker/prefill manifests carry: readiness
    gates traffic on the canary loop reporting every endpoint ready,
    liveness restarts a pod whose process (or engine watchdog) wedged.
    Gray failures are deliberately NOT a liveness matter — a degraded
    or quarantined worker still answers ``/live``; eviction is the
    control plane's quarantine path, not a kubelet restart loop."""
    return {
        "readinessProbe": {
            "httpGet": {"path": "/ready", "port": port},
            "initialDelaySeconds": 30,
            "periodSeconds": 10,
        },
        "livenessProbe": {
            "httpGet": {"path": "/live", "port": port},
            "periodSeconds": 15,
        },
    }


def multihost_group_name(
    svc_name: str, index: int, name_format: str = "dynamo-{service}"
) -> str:
    """Name of one multihost replica group (Indexed Job + headless
    Service). Each replica of a ``hosts > 1`` service is its own group:
    the coordinator DNS name is derived from the group name, so groups
    cannot share a Job."""
    return f"{deployment_name(svc_name, name_format)}-{index}"


def deployment_manifest(
    svc: ServiceSpec,
    replicas: int,
    *,
    graph: str,
    namespace: str,
    image: str,
    hub: str,
    name_format: str = "dynamo-{service}",
    python: str = "python",
) -> dict[str, Any]:
    """Render the Deployment that runs ``replicas`` copies of a service.

    The container command mirrors ProcessBackend's spawn line
    (``python *spec.command``); DYNAMO_HUB carries the coordination
    address the way the reference injects etcd/NATS endpoints into its
    component pods.
    """
    name = deployment_name(svc.name, name_format)
    labels = {
        "app": name,
        GRAPH_LABEL: graph,
        SERVICE_LABEL: svc.name,
    }
    if svc.role:
        labels["dynamo-role"] = svc.role
    env = [{"name": "DYNAMO_HUB", "value": hub}]
    env += [{"name": k, "value": v} for k, v in sorted(svc.env.items())]
    container: dict[str, Any] = {
        "name": "worker",
        "image": image,
        "command": [python, *svc.command],
        "env": env,
    }
    if svc.port:
        container["ports"] = [{"containerPort": svc.port}]
        container.update(probe_manifests(svc.port))
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace, "labels": labels},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": dict(labels)},
                "spec": {"containers": [container]},
            },
        },
    }


def service_manifest(
    svc: ServiceSpec,
    *,
    graph: str,
    namespace: str,
    name_format: str = "dynamo-{service}",
) -> dict[str, Any]:
    """ClusterIP Service in front of a port-bearing component (the
    frontend, typically). Only rendered when ``svc.port`` is set."""
    name = deployment_name(svc.name, name_format)
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": {GRAPH_LABEL: graph, SERVICE_LABEL: svc.name},
        },
        "spec": {
            "selector": {"app": name},
            "ports": [{"port": svc.port, "targetPort": svc.port}],
        },
    }


def multihost_manifests(
    svc: ServiceSpec,
    index: int,
    *,
    graph: str,
    namespace: str,
    image: str,
    hub: str,
    name_format: str = "dynamo-{service}",
    python: str = "python",
) -> list[dict[str, Any]]:
    """One multihost replica group: headless coordinator Service +
    Indexed Job spanning ``svc.hosts`` pods.

    Mirrors deploy/k8s/worker-multihost.yaml (the golden shape, asserted
    in tests/test_operator.py): pod 0 is the SPMD leader, the headless
    Service gives it the stable DNS name ``{group}-0.{group}`` the
    jax.distributed coordinator needs, and JOB_COMPLETION_INDEX (via the
    downward-API annotation) becomes ``--process-id``. Multihost flags
    are appended to the spec's own command so graph authors write the
    same argv they would for a single-host worker.
    """
    base = deployment_name(svc.name, name_format)
    name = multihost_group_name(svc.name, index, name_format)
    labels = {
        "app": base,  # shared across groups: a port Service (or operator
        # queries) can still select every pod of the service
        GRAPH_LABEL: graph,
        SERVICE_LABEL: svc.name,
        HOST_INDEX_LABEL: str(index),
    }
    if svc.role:
        labels["dynamo-role"] = svc.role
    coordinator = f"{name}-0.{name}:{COORDINATOR_PORT}"
    env = [{"name": "DYNAMO_HUB", "value": hub}]
    env += [{"name": k, "value": v} for k, v in sorted(svc.env.items())]
    env.append({
        "name": "JOB_COMPLETION_INDEX",
        "valueFrom": {"fieldRef": {
            "fieldPath":
                "metadata.annotations"
                "['batch.kubernetes.io/job-completion-index']",
        }},
    })
    container: dict[str, Any] = {
        "name": "worker",
        "image": image,
        "command": [
            python, *svc.command,
            "--coordinator-address", coordinator,
            "--num-processes", str(svc.hosts),
            # $(VAR) is expanded by the kubelet from the container env
            "--process-id", "$(JOB_COMPLETION_INDEX)",
        ],
        "env": env,
    }
    if svc.port:
        container["ports"] = [{"containerPort": svc.port}]
        container.update(probe_manifests(svc.port))
    headless: dict[str, Any] = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": dict(labels)},
        "spec": {
            "clusterIP": "None",  # headless: per-pod DNS for the coordinator
            "selector": {"job-name": name},
            "ports": [{"name": "coordinator", "port": COORDINATOR_PORT}],
        },
    }
    job: dict[str, Any] = {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": dict(labels)},
        "spec": {
            "completions": svc.hosts,
            "parallelism": svc.hosts,
            "completionMode": "Indexed",
            "template": {
                "metadata": {"labels": {**labels, "job-name": name}},
                "spec": {
                    "subdomain": name,  # pods resolvable via the headless svc
                    "restartPolicy": "Never",
                    "containers": [container],
                },
            },
        },
    }
    return [headless, job]


def render_multihost_bundle(
    svc: ServiceSpec,
    replicas: int,
    *,
    graph: str,
    namespace: str,
    image: str,
    hub: str,
    name_format: str = "dynamo-{service}",
    python: str = "python",
) -> dict[str, Any]:
    """All replica groups of a multihost service as one ``v1 List``.
    Scale-down GC (groups with index >= replicas) is the backend's job —
    apply does not prune."""
    items: list[dict[str, Any]] = []
    for i in range(replicas):
        items.extend(multihost_manifests(
            svc, i, graph=graph, namespace=namespace, image=image,
            hub=hub, name_format=name_format, python=python,
        ))
    if svc.port:
        items.append(
            service_manifest(
                svc, graph=graph, namespace=namespace,
                name_format=name_format,
            )
        )
    return {"apiVersion": "v1", "kind": "List", "items": items}


def render_bundle(
    svc: ServiceSpec,
    replicas: int,
    *,
    graph: str,
    namespace: str,
    image: str,
    hub: str,
    name_format: str = "dynamo-{service}",
    python: str = "python",
) -> dict[str, Any]:
    """Everything one service needs, as a single ``v1 List`` document
    (what ``kubectl apply -f -`` consumes in one pass). Multihost
    services (``hosts > 1``) render as Indexed Job groups instead of a
    Deployment."""
    if svc.hosts > 1:
        return render_multihost_bundle(
            svc, replicas, graph=graph, namespace=namespace, image=image,
            hub=hub, name_format=name_format, python=python,
        )
    items: list[dict[str, Any]] = [
        deployment_manifest(
            svc, replicas, graph=graph, namespace=namespace, image=image,
            hub=hub, name_format=name_format, python=python,
        )
    ]
    if svc.port:
        items.append(
            service_manifest(
                svc, graph=graph, namespace=namespace,
                name_format=name_format,
            )
        )
    return {"apiVersion": "v1", "kind": "List", "items": items}
