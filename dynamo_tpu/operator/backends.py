"""Reconciler backends: how desired replicas become running workers.

ProcessBackend supervises OS processes on this host (the test/CI and
single-host production path; the reference's operator manages pods the
same level-triggered way). KubectlBackend drives a cluster through
``kubectl``: in managed mode it renders and ``apply``s the full
Deployment/Service objects from the graph resource (the reference
controller's behavior); without an image it degrades to replica
patching of externally-created Deployments.
"""

from __future__ import annotations

import asyncio
import logging
import subprocess
import sys
from typing import Any

from dynamo_tpu.operator.graph import ServiceSpec

log = logging.getLogger("dynamo.operator")


class ProcessBackend:
    """One subprocess per (service, index) replica."""

    def __init__(self, extra_env: dict[str, str] | None = None):
        import os

        self.env = {**os.environ, **(extra_env or {})}
        self._procs: dict[tuple[str, int], subprocess.Popen] = {}

    def running(self, service: str) -> int:
        n = 0
        for (svc, _i), p in list(self._procs.items()):
            if svc != service:
                continue
            if p.poll() is None:
                n += 1
            else:  # crashed replica: forget it so reconcile respawns
                self._procs.pop((svc, _i))
        return n

    async def scale(self, spec: ServiceSpec, replicas: int) -> None:
        # spawn missing indices
        live = {
            i for (svc, i), p in self._procs.items()
            if svc == spec.name and p.poll() is None
        }
        for i in range(replicas):
            if i in live:
                continue
            argv = [sys.executable, *spec.command]
            p = subprocess.Popen(
                argv, env=self.env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            self._procs[(spec.name, i)] = p
            log.info("operator: spawned %s[%d] pid=%d", spec.name, i, p.pid)
        # stop extras: SIGTERM for graceful deregistration (lease revoke);
        # the hub reaper sweeps instance keys of anything that dies hard
        for (svc, i) in sorted(self._procs):
            if svc == spec.name and i >= replicas:
                p = self._procs.pop((svc, i))
                if p.poll() is None:
                    p.terminate()
                    log.info(
                        "operator: stopping %s[%d] pid=%d", svc, i, p.pid
                    )

    async def close(self) -> None:
        for p in self._procs.values():
            if p.poll() is None:
                p.terminate()
        deadline = asyncio.get_running_loop().time() + 10
        for p in self._procs.values():
            while p.poll() is None:
                if asyncio.get_running_loop().time() > deadline:
                    p.kill()
                    break
                await asyncio.sleep(0.1)
        self._procs.clear()


class KubectlBackend:
    """Converge Kubernetes Deployments named ``dynamo-{service}``.

    The cluster-side half of the reference's operator reconciliation
    (controllers owning component Deployments/Services, ref
    deploy/cloud/operator/internal/controller/). Two modes:

    - **managed** (``image`` set): render the full Deployment (+Service
      when the spec has a port) from the ServiceSpec
      (operator/manifests.py) and ``kubectl apply`` it — one idempotent
      verb for create, command/env/image rolling updates, AND scaling,
      exactly how the reference controller drives the apiserver. A
      service removed from the graph is ``kubectl delete``d (delete()).
    - **scale-only** (no ``image``): only patch replicas of Deployments
      someone else created (manifests under deploy/k8s/).
    """

    def __init__(self, namespace: str = "default",
                 name_format: str = "dynamo-{service}",
                 image: str = "", hub: str = "", graph: str = "dynamo",
                 python: str = "python"):
        self.namespace = namespace
        self.name_format = name_format
        self.image = image
        self.hub = hub
        self.graph = graph
        self.python = python

    def running(self, service: str) -> int:
        out = subprocess.run(
            ["kubectl", "-n", self.namespace, "get", "deployment",
             self.name_format.format(service=service),
             "-o", "jsonpath={.status.readyReplicas}"],
            capture_output=True, text=True,
        )
        try:
            return int(out.stdout.strip() or 0)
        except ValueError:
            return 0

    async def scale(self, spec: ServiceSpec, replicas: int) -> None:
        if self.image:
            import json

            from dynamo_tpu.operator.manifests import render_bundle

            bundle = render_bundle(
                spec, replicas, graph=self.graph, namespace=self.namespace,
                image=self.image, hub=self.hub,
                name_format=self.name_format, python=self.python,
            )
            subprocess.run(
                ["kubectl", "-n", self.namespace, "apply", "-f", "-"],
                input=json.dumps(bundle), text=True, check=False,
            )
            if not spec.port:
                # apply doesn't prune: a Service left over from when the
                # spec HAD a port must go explicitly
                subprocess.run(
                    ["kubectl", "-n", self.namespace, "delete", "service",
                     self.name_format.format(service=spec.name),
                     "--ignore-not-found"],
                    check=False,
                )
            return
        subprocess.run(
            ["kubectl", "-n", self.namespace, "scale", "deployment",
             self.name_format.format(service=spec.name),
             f"--replicas={replicas}"],
            check=False,
        )

    async def delete(self, spec: ServiceSpec) -> None:
        """Remove a service's objects (it left the graph resource).
        The Service is deleted unconditionally (--ignore-not-found):
        the current spec's port says nothing about whether an EARLIER
        revision created one."""
        name = self.name_format.format(service=spec.name)
        for kind in ("deployment", "service"):
            subprocess.run(
                ["kubectl", "-n", self.namespace, "delete", kind, name,
                 "--ignore-not-found"],
                check=False,
            )

    async def prune(self, current_services: set[str]) -> None:
        """Delete graph-labeled objects whose service left the resource
        while the operator was down — the in-memory last-seen diff in
        the reconciler can't see those; the GRAPH_LABEL stamped on every
        managed object makes them findable. Managed mode only."""
        if not self.image:
            return
        from dynamo_tpu.operator.manifests import GRAPH_LABEL, SERVICE_LABEL

        out = subprocess.run(
            ["kubectl", "-n", self.namespace, "get", "deployments",
             "-l", f"{GRAPH_LABEL}={self.graph}",
             "-o", f"jsonpath={{range .items[*]}}"
             f"{{.metadata.labels.{SERVICE_LABEL}}}{{\"\\n\"}}{{end}}"],
            capture_output=True, text=True,
        )
        for svc_name in out.stdout.split():
            if svc_name and svc_name not in current_services:
                log.info("operator: pruning orphaned service %r", svc_name)
                await self.delete(ServiceSpec(
                    name=svc_name, replicas=0, command=[]
                ))

    async def close(self) -> None:  # deployments outlive the operator
        return None


def make_backend(kind: str, **kwargs: Any):
    if kind == "process":
        return ProcessBackend(**kwargs)
    if kind == "kubectl":
        return KubectlBackend(**kwargs)
    raise ValueError(f"unknown operator backend {kind!r}")
