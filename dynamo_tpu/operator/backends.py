"""Reconciler backends: how desired replicas become running workers.

ProcessBackend supervises OS processes on this host (the test/CI and
single-host production path; the reference's operator manages pods the
same level-triggered way). KubectlBackend drives a cluster through
``kubectl``: in managed mode it renders and ``apply``s the full
Deployment/Service objects from the graph resource (the reference
controller's behavior); without an image it degrades to replica
patching of externally-created Deployments.
"""

from __future__ import annotations

import asyncio
import logging
import subprocess
import sys
from typing import Any

from dynamo_tpu.operator.graph import ServiceSpec

log = logging.getLogger("dynamo.operator")


class ProcessBackend:
    """One subprocess per (service, index) replica."""

    def __init__(self, extra_env: dict[str, str] | None = None):
        import os

        self.env = {**os.environ, **(extra_env or {})}
        self._procs: dict[tuple[str, int], subprocess.Popen] = {}

    def running(self, service: str) -> int:
        n = 0
        for (svc, _i), p in list(self._procs.items()):
            if svc != service:
                continue
            if p.poll() is None:
                n += 1
            else:  # crashed replica: forget it so reconcile respawns
                self._procs.pop((svc, _i))
        return n

    async def scale(self, spec: ServiceSpec, replicas: int) -> None:
        # spawn missing indices
        live = {
            i for (svc, i), p in self._procs.items()
            if svc == spec.name and p.poll() is None
        }
        for i in range(replicas):
            if i in live:
                continue
            argv = [sys.executable, *spec.command]
            p = subprocess.Popen(
                argv, env=self.env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            self._procs[(spec.name, i)] = p
            log.info("operator: spawned %s[%d] pid=%d", spec.name, i, p.pid)
        # stop extras: SIGTERM for graceful deregistration (lease revoke);
        # the hub reaper sweeps instance keys of anything that dies hard
        for (svc, i) in sorted(self._procs):
            if svc == spec.name and i >= replicas:
                p = self._procs.pop((svc, i))
                if p.poll() is None:
                    p.terminate()
                    log.info(
                        "operator: stopping %s[%d] pid=%d", svc, i, p.pid
                    )

    async def close(self) -> None:
        for p in self._procs.values():
            if p.poll() is None:
                p.terminate()
        deadline = asyncio.get_running_loop().time() + 10
        for p in self._procs.values():
            while p.poll() is None:
                if asyncio.get_running_loop().time() > deadline:
                    p.kill()
                    break
                await asyncio.sleep(0.1)
        self._procs.clear()


class KubectlBackend:
    """Converge Kubernetes Deployments named ``dynamo-{service}``.

    The cluster-side half of the reference's operator reconciliation
    (controllers owning component Deployments/Services, ref
    deploy/cloud/operator/internal/controller/). Two modes:

    - **managed** (``image`` set): render the full Deployment (+Service
      when the spec has a port) from the ServiceSpec
      (operator/manifests.py) and ``kubectl apply`` it — one idempotent
      verb for create, command/env/image rolling updates, AND scaling,
      exactly how the reference controller drives the apiserver. A
      service removed from the graph is ``kubectl delete``d (delete()).
    - **scale-only** (no ``image``): only patch replicas of Deployments
      someone else created (manifests under deploy/k8s/).
    """

    def __init__(self, namespace: str = "default",
                 name_format: str = "dynamo-{service}",
                 image: str = "", hub: str = "", graph: str = "dynamo",
                 python: str = "python"):
        self.namespace = namespace
        self.name_format = name_format
        self.image = image
        self.hub = hub
        self.graph = graph
        self.python = python
        # watch mode (start_watch): observed readyReplicas per service,
        # maintained by a single long-lived `kubectl get -w` stream
        self._observed: dict[str, int] | None = None
        self._watch_task: asyncio.Task | None = None
        self._watch_proc: asyncio.subprocess.Process | None = None
        self._on_change = None
        # multihost services seen by scale(): service -> hosts per group.
        # The deployment watch stream can't observe Indexed Jobs, so
        # running() takes the job-query path for these.
        self._multihost: dict[str, int] = {}

    async def start_watch(self, on_change) -> None:
        """Informer-style observation: ONE long-lived
        ``kubectl get -w --output-watch-events`` stream replaces the
        per-service ``kubectl get`` fork+exec storm (VERDICT r4 weak #4;
        ref controller-runtime informers in
        deploy/cloud/operator/internal/controller/). Each watch event
        updates the observed-replica cache and fires ``on_change`` so
        the reconciler reacts to CLUSTER-side edits (pod readiness,
        external scale/delete) event-driven instead of on its poll
        interval. The stream auto-restarts with backoff; the initial
        list arrives as ADDED events and re-seeds the cache.

        The cache is seeded only by the FIRST successful event: until
        then running() keeps the per-service ``kubectl get`` fallback,
        so a watch that can never be established (RBAC grants get but
        not watch, old kubectl without --output-watch-events) degrades
        to polling instead of reporting 0 forever."""
        self._on_change = on_change
        self._watch_task = asyncio.get_running_loop().create_task(
            self._watch_loop()
        )

    async def _watch_loop(self) -> None:
        from dynamo_tpu.operator.manifests import GRAPH_LABEL, SERVICE_LABEL

        argv = [
            "kubectl", "-n", self.namespace, "get", "deployments",
            "-l", f"{GRAPH_LABEL}={self.graph}",
            "-w", "--output-watch-events",
            "-o",
            "jsonpath={.type}{\" \"}"
            f"{{.object.metadata.labels['{SERVICE_LABEL}']}}{{\" \"}}"
            "{.object.status.readyReplicas}{\"\\n\"}",
        ]
        delay = 1.0
        while True:
            try:
                proc = await asyncio.create_subprocess_exec(
                    *argv,
                    stdout=asyncio.subprocess.PIPE,
                    stderr=asyncio.subprocess.DEVNULL,
                )
                self._watch_proc = proc
                assert proc.stdout is not None
                while True:
                    line = await proc.stdout.readline()
                    if not line:
                        break
                    parts = line.decode().split()
                    if len(parts) < 2:
                        continue
                    etype, svc = parts[0], parts[1]
                    ready = (
                        int(parts[2])
                        if len(parts) > 2 and parts[2].isdigit() else 0
                    )
                    if self._observed is None:
                        self._observed = {}  # first event: cache is live
                    if etype == "DELETED":
                        self._observed.pop(svc, None)
                    else:
                        self._observed[svc] = ready
                    delay = 1.0
                    if self._on_change is not None:
                        self._on_change()
                await proc.wait()
            except asyncio.CancelledError:
                if self._watch_proc and self._watch_proc.returncode is None:
                    self._watch_proc.kill()
                    # reap on the loop: GC-time transport finalization
                    # after loop close raises and leaves a zombie
                    try:
                        await self._watch_proc.wait()
                    # dynalint: disable=DL003 -- best-effort zombie reap on
                    # a process we just killed; shutdown must not fail here
                    except Exception:  # noqa: BLE001
                        pass
                raise
            except Exception:  # noqa: BLE001 — kubectl missing/apiserver gone
                log.warning("kubectl watch stream failed; retrying",
                            exc_info=True)
            await asyncio.sleep(delay)
            delay = min(delay * 2, 30.0)

    def running(self, service: str) -> int:
        hosts = self._multihost.get(service, 0)
        if hosts > 1:
            # one "replica" = one fully-ready group: count Jobs whose
            # ready pods reach the group size
            from dynamo_tpu.operator.manifests import (
                GRAPH_LABEL, SERVICE_LABEL,
            )

            out = subprocess.run(
                ["kubectl", "-n", self.namespace, "get", "jobs",
                 "-l", f"{SERVICE_LABEL}={service},{GRAPH_LABEL}={self.graph}",
                 "-o", "jsonpath={range .items[*]}{.status.ready}"
                 "{\"\\n\"}{end}"],
                capture_output=True, text=True,
            )
            return sum(
                1 for tok in out.stdout.split()
                if tok.isdigit() and int(tok) >= hosts
            )
        if self._observed is not None:
            # watch mode: cache read, no subprocess. A deployment deleted
            # during a watch-stream gap may linger until the stream's
            # next event re-syncs it — scale() stays idempotent either way
            # (informers accept the same staleness window).
            return self._observed.get(service, 0)
        out = subprocess.run(
            ["kubectl", "-n", self.namespace, "get", "deployment",
             self.name_format.format(service=service),
             "-o", "jsonpath={.status.readyReplicas}"],
            capture_output=True, text=True,
        )
        try:
            return int(out.stdout.strip() or 0)
        except ValueError:
            return 0

    @staticmethod
    async def _kubectl(argv: list[str], **kw) -> subprocess.CompletedProcess:
        """kubectl off the event loop: apiserver round-trips run 100ms+
        (or hang on a dead cluster), and the reconciler shares its loop
        with watch streams and the hub client — dynalint DL001."""
        return await asyncio.to_thread(subprocess.run, argv, **kw)

    async def scale(self, spec: ServiceSpec, replicas: int) -> None:
        if spec.hosts > 1:
            if not self.image:
                # scale-only mode can't patch Indexed Jobs (completions
                # are immutable); multihost requires managed mode
                log.warning(
                    "operator: cannot scale multihost service %r without "
                    "an image (managed mode required)", spec.name,
                )
                return
            self._multihost[spec.name] = spec.hosts
            await self._scale_multihost(spec, replicas)
            return
        self._multihost.pop(spec.name, None)
        if self.image:
            import json

            from dynamo_tpu.operator.manifests import render_bundle

            bundle = render_bundle(
                spec, replicas, graph=self.graph, namespace=self.namespace,
                image=self.image, hub=self.hub,
                name_format=self.name_format, python=self.python,
            )
            await self._kubectl(
                ["kubectl", "-n", self.namespace, "apply", "-f", "-"],
                input=json.dumps(bundle), text=True, check=False,
            )
            if not spec.port:
                # apply doesn't prune: a Service left over from when the
                # spec HAD a port must go explicitly
                await self._kubectl(
                    ["kubectl", "-n", self.namespace, "delete", "service",
                     self.name_format.format(service=spec.name),
                     "--ignore-not-found"],
                    check=False,
                )
            return
        await self._kubectl(
            ["kubectl", "-n", self.namespace, "scale", "deployment",
             self.name_format.format(service=spec.name),
             f"--replicas={replicas}"],
            check=False,
        )

    async def _scale_multihost(self, spec: ServiceSpec, replicas: int) -> None:
        """Converge the Indexed Job groups of a ``hosts > 1`` service.

        ``apply`` covers create and replica growth, but Job pod templates
        are immutable — a command/env/image change makes apply fail, and
        the roll is an explicit delete + re-apply of the service's
        groups (pods restart; the SPMD group must re-form anyway).
        Scale-down GC deletes groups with index >= replicas by their
        HOST_INDEX_LABEL, most-recent group names first being irrelevant
        here: group identity is the index, so the highest indices go.
        """
        import json

        from dynamo_tpu.operator.manifests import (
            GRAPH_LABEL, HOST_INDEX_LABEL, SERVICE_LABEL,
            multihost_group_name, render_multihost_bundle,
        )

        bundle = render_multihost_bundle(
            spec, replicas, graph=self.graph, namespace=self.namespace,
            image=self.image, hub=self.hub,
            name_format=self.name_format, python=self.python,
        )
        sel = f"{SERVICE_LABEL}={spec.name},{GRAPH_LABEL}={self.graph}"
        out = await self._kubectl(
            ["kubectl", "-n", self.namespace, "apply", "-f", "-"],
            input=json.dumps(bundle), text=True, check=False,
            capture_output=True,
        )
        if out.returncode != 0 and "immutable" in (out.stderr or ""):
            log.info("operator: rolling multihost service %r "
                     "(job template changed)", spec.name)
            await self._kubectl(
                ["kubectl", "-n", self.namespace, "delete", "jobs",
                 "-l", sel, "--ignore-not-found"],
                check=False,
            )
            await self._kubectl(
                ["kubectl", "-n", self.namespace, "apply", "-f", "-"],
                input=json.dumps(bundle), text=True, check=False,
            )
        # GC groups beyond the desired replica count (apply never prunes)
        out = await self._kubectl(
            ["kubectl", "-n", self.namespace, "get", "jobs", "-l", sel,
             "-o", "jsonpath={range .items[*]}"
             f"{{.metadata.labels['{HOST_INDEX_LABEL}']}}{{\"\\n\"}}{{end}}"],
            capture_output=True, text=True, check=False,
        )
        for tok in out.stdout.split():
            if tok.isdigit() and int(tok) >= replicas:
                name = multihost_group_name(
                    spec.name, int(tok), self.name_format
                )
                log.info("operator: GC multihost group %r", name)
                for kind in ("job", "service"):
                    await self._kubectl(
                        ["kubectl", "-n", self.namespace, "delete", kind,
                         name, "--ignore-not-found"],
                        check=False,
                    )

    async def delete(self, spec: ServiceSpec) -> None:
        """Remove a service's objects (it left the graph resource).
        The Service is deleted unconditionally (--ignore-not-found):
        the current spec's port says nothing about whether an EARLIER
        revision created one."""
        name = self.name_format.format(service=spec.name)
        for kind in ("deployment", "service"):
            await self._kubectl(
                ["kubectl", "-n", self.namespace, "delete", kind, name,
                 "--ignore-not-found"],
                check=False,
            )
        # multihost groups (Indexed Jobs + headless Services) carry the
        # service label — sweep them by selector; matches nothing for
        # single-host services
        self._multihost.pop(spec.name, None)
        if self.image:
            from dynamo_tpu.operator.manifests import (
                GRAPH_LABEL, SERVICE_LABEL,
            )

            sel = f"{SERVICE_LABEL}={spec.name},{GRAPH_LABEL}={self.graph}"
            for kind in ("jobs", "services"):
                await self._kubectl(
                    ["kubectl", "-n", self.namespace, "delete", kind,
                     "-l", sel, "--ignore-not-found"],
                    check=False,
                )

    async def prune(self, current_services: set[str]) -> None:
        """Delete graph-labeled objects whose service left the resource
        while the operator was down — the in-memory last-seen diff in
        the reconciler can't see those; the GRAPH_LABEL stamped on every
        managed object makes them findable. Managed mode only."""
        if not self.image:
            return
        from dynamo_tpu.operator.manifests import GRAPH_LABEL, SERVICE_LABEL

        found: set[str] = set()
        # multihost groups live as Jobs, not Deployments — sweep both
        for kind in ("deployments", "jobs"):
            out = await self._kubectl(
                ["kubectl", "-n", self.namespace, "get", kind,
                 "-l", f"{GRAPH_LABEL}={self.graph}",
                 "-o", f"jsonpath={{range .items[*]}}"
                 f"{{.metadata.labels.{SERVICE_LABEL}}}{{\"\\n\"}}{{end}}"],
                capture_output=True, text=True,
            )
            found.update(out.stdout.split())
        for svc_name in sorted(found):
            if svc_name and svc_name not in current_services:
                log.info("operator: pruning orphaned service %r", svc_name)
                await self.delete(ServiceSpec(
                    name=svc_name, replicas=0, command=[]
                ))

    async def close(self) -> None:  # deployments outlive the operator
        if self._watch_task is not None:
            self._watch_task.cancel()
            try:
                await self._watch_task
            except asyncio.CancelledError:
                pass
        if self._watch_proc is not None and self._watch_proc.returncode is None:
            self._watch_proc.kill()
            try:
                await asyncio.wait_for(self._watch_proc.wait(), timeout=5)
            except (asyncio.TimeoutError, ProcessLookupError):
                pass


def make_backend(kind: str, **kwargs: Any):
    if kind == "process":
        return ProcessBackend(**kwargs)
    if kind == "kubectl":
        return KubectlBackend(**kwargs)
    raise ValueError(f"unknown operator backend {kind!r}")
