"""Reconciler backends: how desired replicas become running workers.

ProcessBackend supervises OS processes on this host (the test/CI and
single-host production path; the reference's operator manages pods the
same level-triggered way). KubectlBackend shells out to ``kubectl
scale`` for cluster deployments — the thin path until a full
client-go-equivalent is warranted.
"""

from __future__ import annotations

import asyncio
import logging
import subprocess
import sys
from typing import Any

from dynamo_tpu.operator.graph import ServiceSpec

log = logging.getLogger("dynamo.operator")


class ProcessBackend:
    """One subprocess per (service, index) replica."""

    def __init__(self, extra_env: dict[str, str] | None = None):
        import os

        self.env = {**os.environ, **(extra_env or {})}
        self._procs: dict[tuple[str, int], subprocess.Popen] = {}

    def running(self, service: str) -> int:
        n = 0
        for (svc, _i), p in list(self._procs.items()):
            if svc != service:
                continue
            if p.poll() is None:
                n += 1
            else:  # crashed replica: forget it so reconcile respawns
                self._procs.pop((svc, _i))
        return n

    async def scale(self, spec: ServiceSpec, replicas: int) -> None:
        # spawn missing indices
        live = {
            i for (svc, i), p in self._procs.items()
            if svc == spec.name and p.poll() is None
        }
        for i in range(replicas):
            if i in live:
                continue
            argv = [sys.executable, *spec.command]
            p = subprocess.Popen(
                argv, env=self.env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            self._procs[(spec.name, i)] = p
            log.info("operator: spawned %s[%d] pid=%d", spec.name, i, p.pid)
        # stop extras: SIGTERM for graceful deregistration (lease revoke);
        # the hub reaper sweeps instance keys of anything that dies hard
        for (svc, i) in sorted(self._procs):
            if svc == spec.name and i >= replicas:
                p = self._procs.pop((svc, i))
                if p.poll() is None:
                    p.terminate()
                    log.info(
                        "operator: stopping %s[%d] pid=%d", svc, i, p.pid
                    )

    async def close(self) -> None:
        for p in self._procs.values():
            if p.poll() is None:
                p.terminate()
        deadline = asyncio.get_running_loop().time() + 10
        for p in self._procs.values():
            while p.poll() is None:
                if asyncio.get_running_loop().time() > deadline:
                    p.kill()
                    break
                await asyncio.sleep(0.1)
        self._procs.clear()


class KubectlBackend:
    """Scale Kubernetes deployments named ``dynamo-{service}``.

    The cluster-side half of the reference's operator reconciliation
    (controllers patching component Deployments); manifests under
    deploy/k8s/ create the Deployments this scales."""

    def __init__(self, namespace: str = "default",
                 name_format: str = "dynamo-{service}"):
        self.namespace = namespace
        self.name_format = name_format

    def running(self, service: str) -> int:
        out = subprocess.run(
            ["kubectl", "-n", self.namespace, "get", "deployment",
             self.name_format.format(service=service),
             "-o", "jsonpath={.status.readyReplicas}"],
            capture_output=True, text=True,
        )
        try:
            return int(out.stdout.strip() or 0)
        except ValueError:
            return 0

    async def scale(self, spec: ServiceSpec, replicas: int) -> None:
        subprocess.run(
            ["kubectl", "-n", self.namespace, "scale", "deployment",
             self.name_format.format(service=spec.name),
             f"--replicas={replicas}"],
            check=False,
        )

    async def close(self) -> None:  # deployments outlive the operator
        return None


def make_backend(kind: str, **kwargs: Any):
    if kind == "process":
        return ProcessBackend(**kwargs)
    if kind == "kubectl":
        return KubectlBackend(**kwargs)
    raise ValueError(f"unknown operator backend {kind!r}")
