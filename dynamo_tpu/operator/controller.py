"""The reconciler: level-triggered convergence of graph deployments.

Control loop shape of the reference's controllers
(deploy/cloud/operator/internal/controller/): every interval (and on
desired-state change) compare DESIRED — the DynamoGraphDeployment
resource, with the SLA planner's desired-replica counts overriding the
prefill/decode services (ref KubernetesConnector patching DGD replicas,
planner/kubernetes_connector.py) — against OBSERVED (backend-reported
running replicas) and converge via the backend. Scale-down sends
SIGTERM so workers deregister their leases gracefully; anything that
dies hard loses its lease at TTL and the hub reaper drops its instance
keys (this stack's equivalent of the reference operator's etcd cleanup
on scale-down — proven by the worker-kill fault-tolerance test).
"""

from __future__ import annotations

import asyncio
import logging

from dynamo_tpu.operator.graph import (
    DGD_KEY,
    DGD_STATUS_KEY,
    DynamoGraphDeployment,
    ServiceSpec,
)
from dynamo_tpu.planner.connector import read_desired_replicas

log = logging.getLogger("dynamo.operator")


class Reconciler:
    def __init__(
        self,
        hub,
        name: str,
        backend,
        *,
        interval_s: float = 1.0,
        apply_planner_desired: bool = True,
    ):
        self.hub = hub
        self.name = name
        self.backend = backend
        self.interval_s = interval_s
        self.apply_planner_desired = apply_planner_desired
        self._task: asyncio.Task | None = None
        self._watch_task: asyncio.Task | None = None
        self._wake = asyncio.Event()
        self.reconciles = 0
        # services seen last pass: a service dropped from the resource
        # must be torn down (backend.delete when it manages the objects,
        # else scale-to-zero). In-memory diffing misses edits made while
        # the operator was down — backend.prune (label-selected sweep)
        # covers those; together they are the reference controller's
        # owner-reference GC equivalent
        self._last_services: dict[str, ServiceSpec] = {}
        self._last_revision: int | None = None

    async def start(self) -> "Reconciler":
        loop = asyncio.get_running_loop()
        if hasattr(self.backend, "start_watch"):
            # observed-state watch (informer role): cluster-side changes
            # wake the loop immediately, and running() becomes a cache
            # read instead of a kubectl subprocess per service per pass
            try:
                await self.backend.start_watch(self._wake.set)
            except Exception:  # noqa: BLE001 — fall back to polling
                log.warning("observed-state watch unavailable; polling",
                            exc_info=True)
        self._task = loop.create_task(self._run())
        self._watch_task = loop.create_task(self._watch_desired())
        return self

    async def _watch_desired(self) -> None:
        """Edge trigger on top of the level loop: react immediately when
        the resource (or the planner's desired counts) changes."""
        try:
            async for _ev in self.hub.watch_prefix(
                DGD_KEY.format(name=self.name)
            ):
                self._wake.set()
        except (asyncio.CancelledError, ConnectionError):
            pass

    async def reconcile_once(self) -> DynamoGraphDeployment | None:
        dgd = await DynamoGraphDeployment.get(self.hub, self.name)
        if dgd is None:
            # resource deleted: tear down everything it owned and drop
            # the status key (else dynamo_check reports a ghost graph)
            if self._last_services:
                log.info("reconcile %s: resource deleted; tearing down",
                         self.name)
                for old in self._last_services.values():
                    if hasattr(self.backend, "delete"):
                        await self.backend.delete(old)
                    else:
                        await self.backend.scale(old, 0)
                self._last_services = {}
                self._last_revision = None
                try:
                    await self.hub.delete(
                        DGD_STATUS_KEY.format(name=self.name)
                    )
                except Exception:  # noqa: BLE001
                    log.warning("dgd status delete failed", exc_info=True)
            return None
        # a revision bump means the SPEC may have changed (command, env,
        # port), not just counts — managed backends must re-apply even
        # at matching replica counts for the rolling update to happen.
        # Also true on the first pass after (re)start: converge from
        # whatever state the cluster was left in.
        spec_changed = dgd.revision != self._last_revision
        desired_override = None
        if self.apply_planner_desired:
            try:
                desired_override = await read_desired_replicas(
                    self.hub, dgd.namespace
                )
            except Exception:  # noqa: BLE001
                log.warning("planner desired-replica read failed",
                            exc_info=True)
        status: dict[str, dict[str, int]] = {}
        for svc in dgd.services:
            replicas = svc.replicas
            if desired_override is not None and svc.role in (
                "prefill", "decode"
            ):
                replicas = getattr(desired_override, svc.role)
            have = self.backend.running(svc.name)
            if have != replicas or spec_changed:
                if have != replicas:
                    log.info(
                        "reconcile %s/%s: %d -> %d replicas",
                        self.name, svc.name, have, replicas,
                    )
                await self.backend.scale(svc, replicas)
            status[svc.name] = {"desired": replicas, "ready": have}
        # tear down services that left the resource
        current = {svc.name for svc in dgd.services}
        for name, old in self._last_services.items():
            if name in current:
                continue
            log.info("reconcile %s/%s: removed from graph", self.name, name)
            if hasattr(self.backend, "delete"):
                await self.backend.delete(old)
            else:
                await self.backend.scale(old, 0)
        # durable sweep: objects labeled for this graph but absent from
        # the resource (edits made while the operator was down)
        if spec_changed and hasattr(self.backend, "prune"):
            await self.backend.prune(current)
        self._last_services = {svc.name: svc for svc in dgd.services}
        self._last_revision = dgd.revision
        # status subresource equivalent: observed counts for dynamo_check
        # and dashboards ("ready" lags one pass after a scale by design —
        # it is the OBSERVED state this pass converged from)
        try:
            await self.hub.put(
                DGD_STATUS_KEY.format(name=self.name),
                {
                    "revision": dgd.revision,
                    "services": status,
                    "ready": all(
                        s["ready"] == s["desired"] for s in status.values()
                    ),
                },
            )
        except Exception:  # noqa: BLE001 - status is best-effort
            log.warning("dgd status write failed", exc_info=True)
        self.reconciles += 1
        return dgd

    async def _run(self) -> None:
        try:
            while True:
                try:
                    await self.reconcile_once()
                except Exception:  # noqa: BLE001
                    log.exception("reconcile failed; retrying")
                try:
                    await asyncio.wait_for(
                        self._wake.wait(), timeout=self.interval_s
                    )
                    self._wake.clear()
                except asyncio.TimeoutError:
                    pass
        except asyncio.CancelledError:
            pass

    async def close(self) -> None:
        for t in (self._task, self._watch_task):
            if t is not None:
                t.cancel()
                try:
                    await t
                except asyncio.CancelledError:
                    pass
                except Exception:  # noqa: BLE001 - already-dead task
                    log.exception("reconciler task died before close")
        await self.backend.close()
