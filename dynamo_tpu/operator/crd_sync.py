"""CRD <-> hub bridge: make the apiserver-native resource functional.

The reference operator reconciles DynamoGraphDeployment CRDs straight
off the apiserver through controller-runtime informers
(ref deploy/cloud/operator/internal/controller/
dynamographdeployment_controller.go). Here the Reconciler converges on
the HUB resource (``v1/dgd/{name}``); this module closes the loop for
cluster-native workflows:

- ``kubectl get dgd <name> -w -o json`` streams the CRD object; each
  change is translated (spec.services map -> ServiceSpec list) and
  applied to the hub resource, which wakes the Reconciler edge-
  triggered.
- the Reconciler's status write-back (``v1/dgd-status/{name}``) is
  patched onto the CRD's status subresource, so ``kubectl get dgd``
  shows State/Ready columns (deploy/k8s/crd.yaml printer columns).

A user then drives the whole stack with ``kubectl apply -f dgd.yaml``
exactly like the reference.
"""

from __future__ import annotations

import asyncio
import json
import logging

from dynamo_tpu.operator.graph import (
    DGD_STATUS_KEY,
    DynamoGraphDeployment,
    ServiceSpec,
)

log = logging.getLogger("dynamo.operator")


def services_from_crd(spec: dict) -> list[ServiceSpec]:
    """Translate the CRD's ``spec.services`` map (deploy/k8s/crd.yaml
    schema) into the hub resource's ServiceSpec list. Graph-wide
    ``spec.envs`` layer under per-service env."""
    base_env = dict(spec.get("envs") or {})
    out = []
    for name, svc in sorted((spec.get("services") or {}).items()):
        out.append(ServiceSpec(
            name=name,
            replicas=int(svc.get("replicas", 1)),
            command=list(svc.get("command") or []),
            component=svc.get("component", "backend"),
            role=svc.get("role", ""),
            port=int(svc.get("port", 0)),
            env={**base_env, **(svc.get("env") or {})},
            hosts=int(svc.get("hosts", 1)),
        ))
    return out


class CrdSync:
    """One task pair per graph: CRD spec -> hub, hub status -> CRD."""

    def __init__(
        self, hub, name: str, *, namespace: str = "dynamo",
        kubectl: str = "kubectl",
    ):
        self.hub = hub
        self.name = name
        self.namespace = namespace
        self.kubectl = kubectl
        self._tasks: list[asyncio.Task] = []
        self._proc: asyncio.subprocess.Process | None = None
        self.synced_revisions = 0  # observability + test hook

    async def start(self) -> "CrdSync":
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._spec_watch_loop()),
            loop.create_task(self._status_push_loop()),
        ]
        return self

    # -- CRD spec -> hub resource ------------------------------------------

    async def _spec_watch_loop(self) -> None:
        delay = 1.0
        while True:
            try:
                proc = await asyncio.create_subprocess_exec(
                    self.kubectl, "-n", self.namespace, "get",
                    "dynamographdeployments", self.name, "-w", "-o", "json",
                    stdout=asyncio.subprocess.PIPE,
                    stderr=asyncio.subprocess.DEVNULL,
                )
                self._proc = proc
                assert proc.stdout is not None
                # -w -o json emits CONCATENATED json documents; feed an
                # incremental decoder
                buf = ""
                decoder = json.JSONDecoder()
                while True:
                    chunk = await proc.stdout.read(65536)
                    if not chunk:
                        break
                    buf += chunk.decode()
                    while buf.lstrip():
                        try:
                            obj, end = decoder.raw_decode(buf.lstrip())
                        except json.JSONDecodeError:
                            break  # incomplete document: read more
                        buf = buf.lstrip()[end:]
                        await self._apply_crd_object(obj)
                        delay = 1.0
                await proc.wait()
            except asyncio.CancelledError:
                if self._proc and self._proc.returncode is None:
                    self._proc.kill()
                    try:
                        await self._proc.wait()  # reap on the loop
                    # dynalint: disable=DL003 -- best-effort zombie reap on
                    # a process we just killed; cancellation must proceed
                    except Exception:  # noqa: BLE001
                        pass
                raise
            except Exception:  # noqa: BLE001
                log.warning("dgd CRD watch failed; retrying", exc_info=True)
            await asyncio.sleep(delay)
            delay = min(delay * 2, 30.0)

    async def _apply_crd_object(self, obj: dict) -> None:
        spec = obj.get("spec") or {}
        services = services_from_crd(spec)
        current = await DynamoGraphDeployment.get(self.hub, self.name)
        if current is not None and [
            s.__dict__ for s in current.services
        ] == [s.__dict__ for s in services]:
            return  # no-op events (status-only updates) must not bump rev
        dgd = DynamoGraphDeployment(
            name=self.name,
            namespace=self.namespace,
            services=services,
            revision=current.revision if current is not None else 0,
        )
        await dgd.apply(self.hub)
        self.synced_revisions += 1
        log.info(
            "crd-sync %s: applied revision %d (%d services)",
            self.name, dgd.revision, len(services),
        )

    # -- hub status -> CRD status subresource ------------------------------

    async def _status_push_loop(self) -> None:
        key = DGD_STATUS_KEY.format(name=self.name)
        try:
            async for ev in self.hub.watch_prefix(key):
                if ev.kind != "put" or not ev.value:
                    continue
                status = {
                    "state": "successful" if ev.value.get("ready")
                    else "pending",
                    "ready": "True" if ev.value.get("ready") else "False",
                    "revision": ev.value.get("revision", 0),
                    "services": ev.value.get("services", {}),
                }
                proc = await asyncio.create_subprocess_exec(
                    self.kubectl, "-n", self.namespace, "patch",
                    "dynamographdeployments", self.name,
                    "--subresource=status", "--type=merge",
                    "-p", json.dumps({"status": status}),
                    stdout=asyncio.subprocess.DEVNULL,
                    stderr=asyncio.subprocess.DEVNULL,
                )
                await proc.wait()
        except (asyncio.CancelledError, ConnectionError):
            pass

    async def close(self) -> None:
        for t in self._tasks:
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                pass
        if self._proc is not None and self._proc.returncode is None:
            self._proc.kill()
            try:
                await asyncio.wait_for(self._proc.wait(), timeout=5)
            except (asyncio.TimeoutError, ProcessLookupError):
                pass
