"""Component model: Namespace -> Component -> Endpoint tree.

A deployment is a tree of named endpoints; each live worker process serving
an endpoint registers an ``Instance`` in the hub KV store under
``v1/instances/{ns}/{component}/{endpoint}/{instance_id}``, bound to its
lease - death (missed keepalives) drops the key, and every watcher (routers,
clients) sees the worker disappear. Ref: lib/runtime/src/component.rs
(Component :150, Endpoint :384, Namespace :549, Instance :97, etcd path
scheme :76-78) and component/client.rs (Client/InstanceSource).
"""

from __future__ import annotations

from contextlib import aclosing

import asyncio
import logging
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, AsyncIterator

from dynamo_tpu.runtime.context import Context, StreamError, spawn
from dynamo_tpu.runtime.transport import Handler, InstanceChannel, call_local

if TYPE_CHECKING:
    from dynamo_tpu.runtime.distributed import DistributedRuntime

log = logging.getLogger("dynamo.component")

INSTANCE_ROOT = "v1/instances"


@dataclass(frozen=True)
class Instance:
    """One live worker registration for an endpoint."""

    instance_id: int
    namespace: str
    component: str
    endpoint: str
    host: str
    port: int
    transport: str = "tcp"  # "tcp" | "local"
    metadata: dict[str, Any] = field(default_factory=dict)
    # unix-socket path of the worker's EndpointServer, "" if not listening
    # on one; co-located clients prefer it (transport.py InstanceChannel)
    uds: str = ""

    @property
    def path(self) -> str:
        return f"{INSTANCE_ROOT}/{self.namespace}/{self.component}/{self.endpoint}/{self.instance_id:x}"

    @property
    def endpoint_path(self) -> str:
        return f"{self.namespace}/{self.component}/{self.endpoint}"

    @property
    def wire_path(self) -> str:
        """Handler-registry key: instance-qualified so one process can serve
        several instances of the same endpoint without collision."""
        return f"{self.endpoint_path}@{self.instance_id:x}"

    def to_dict(self) -> dict[str, Any]:
        return {
            "instance_id": self.instance_id,
            "namespace": self.namespace,
            "component": self.component,
            "endpoint": self.endpoint,
            "host": self.host,
            "port": self.port,
            "transport": self.transport,
            "metadata": self.metadata,
            "uds": self.uds,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Instance":
        return cls(**{k: d[k] for k in (
            "instance_id", "namespace", "component", "endpoint",
            "host", "port", "transport", "metadata", "uds",
        ) if k in d})


class Namespace:
    def __init__(self, drt: "DistributedRuntime", name: str):
        self._drt = drt
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self._drt, self.name, name)


class Component:
    def __init__(self, drt: "DistributedRuntime", namespace: str, name: str):
        self._drt = drt
        self.namespace = namespace
        self.name = name

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.name}"

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self._drt, self.namespace, self.name, name)


class Endpoint:
    def __init__(self, drt: "DistributedRuntime", namespace: str, component: str, name: str):
        self._drt = drt
        self.namespace = namespace
        self.component = component
        self.name = name

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.component}/{self.name}"

    @property
    def instance_prefix(self) -> str:
        return f"{INSTANCE_ROOT}/{self.namespace}/{self.component}/{self.name}/"

    async def serve(
        self,
        handler: Handler,
        *,
        metadata: dict[str, Any] | None = None,
        graceful_shutdown: bool = True,
    ) -> "ServedEndpoint":
        """Register + serve this endpoint with ``handler``.

        Ref: bindings ``serve_endpoint`` (lib/bindings/python/rust/lib.rs:618)
        -> PushEndpoint.start + etcd instance registration.
        """
        return await self._drt.serve_endpoint(
            self, handler, metadata=metadata or {}, graceful_shutdown=graceful_shutdown
        )

    def client(self) -> "Client":
        return Client(self._drt, self)


@dataclass
class ServedEndpoint:
    """Handle to a live served endpoint (for deregistration/drain)."""

    instance: Instance
    endpoint: Endpoint
    _drt: "DistributedRuntime"

    async def shutdown(self, drain: bool = True) -> None:
        await self._drt.deregister_endpoint(self, drain=drain)


class Client:
    """Endpoint client: watches live instances, opens channels, issues calls.

    Ref: lib/runtime/src/component/client.rs - InstanceSource watch + the
    direct/random/round-robin issue paths used by PushRouter.
    """

    def __init__(self, drt: "DistributedRuntime", endpoint: Endpoint):
        self._drt = drt
        self.endpoint = endpoint
        self._instances: dict[int, Instance] = {}
        self._channels: dict[int, InstanceChannel] = {}
        self._dials: dict[int, asyncio.Task] = {}  # single-flight, by iid
        self._watch_task: asyncio.Task | None = None
        self._ready = asyncio.Event()
        self._started = False
        self._events: asyncio.Event = asyncio.Event()  # set on any membership change
        # monotonically bumped on every membership change: per-request
        # "did anything change" checks compare this int instead of
        # rebuilding and comparing the whole id set (O(instances) per
        # pick at fleet scale — cluster sim finding)
        self.membership_gen = 0

    async def start(self) -> "Client":
        if self._started:
            return self
        self._started = True
        self._watch_task = asyncio.get_running_loop().create_task(self._watch())
        return self

    async def _watch(self) -> None:
        try:
            async for ev in self._drt.hub.watch_prefix(self.endpoint.instance_prefix):
                if ev.kind == "put" and ev.value:
                    inst = Instance.from_dict(ev.value)
                    self._instances[inst.instance_id] = inst
                    if inst.transport == "tcp" and self._drt.config.prewarm_dials:
                        # warm the pool at discovery so the instance's
                        # first request doesn't pay the dial (cold-vs-warm
                        # TTFT delta: benchmarks/stream_bench.py)
                        spawn(
                            self._prewarm(inst),
                            name=f"prewarm-{inst.instance_id:x}",
                        )
                elif ev.kind == "delete":
                    iid = int(ev.key.rsplit("/", 1)[-1], 16)
                    self._instances.pop(iid, None)
                    dial = self._dials.pop(iid, None)
                    if dial is not None:
                        dial.cancel()
                    ch = self._channels.pop(iid, None)
                    if ch is not None:
                        await ch.close()
                self.membership_gen += 1
                self._ready.set()
                self._events.set()
        except asyncio.CancelledError:
            pass
        except ConnectionError:
            log.warning("hub watch lost for %s", self.endpoint.path)

    def instances(self) -> list[Instance]:
        return list(self._instances.values())

    def instance_ids(self) -> list[int]:
        return sorted(self._instances)

    async def wait_for_instances(self, n: int = 1, timeout: float = 30.0) -> list[Instance]:
        await self.start()
        deadline = asyncio.get_running_loop().time() + timeout
        while len(self._instances) < n:
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                raise TimeoutError(
                    f"{self.endpoint.path}: {len(self._instances)}/{n} instances after {timeout}s"
                )
            self._events.clear()
            try:
                await asyncio.wait_for(self._events.wait(), remaining)
            except asyncio.TimeoutError:
                continue
        return self.instances()

    async def membership_changed(self) -> None:
        """Wait for the next instance add/remove."""
        self._events.clear()
        await self._events.wait()

    async def call_instance(
        self, instance_id: int, payload: Any, context: Context
    ) -> AsyncIterator[Any]:
        """Issue a streaming call to a specific instance. The whole
        stream runs under a ``transport.call`` span — dispatch through
        end-of-stream — whose context the wire hop propagates, so the
        worker's spans nest directly beneath it (runtime/tracing.py)."""
        from dynamo_tpu.runtime import tracing

        inst = self._instances.get(instance_id)
        if inst is None:
            raise StreamError(f"instance {instance_id:x} not found for {self.endpoint.path}")
        with tracing.span(
            "transport.call",
            endpoint=self.endpoint.path, instance=f"{instance_id:x}",
        ):
            if inst.transport == "local":
                handler = self._drt.local_registry.get(inst.wire_path)
                if handler is None:
                    raise StreamError(f"local instance {instance_id:x} has no handler")
                local_stream = call_local(handler, payload, context)
                async with aclosing(local_stream):
                    async for item in local_stream:
                        yield item
                return
            ch = await self._channel(inst)
            try:
                stream = ch.call(inst.wire_path, payload, context)
                async with aclosing(stream):
                    async for item in stream:
                        yield item
            except StreamError:
                # connection-level death: drop the channel so the next
                # call redials
                self._channels.pop(instance_id, None)
                await ch.close()
                raise

    async def _prewarm(self, inst: Instance) -> None:
        try:
            await self._channel(inst)
        except (StreamError, asyncio.CancelledError):
            # best effort: the first real call redials (and migration
            # re-drives if the instance is truly gone)
            pass

    async def _channel(self, inst: Instance) -> InstanceChannel:
        ch = self._channels.get(inst.instance_id)
        if ch is not None and ch.connected:
            return ch
        # single-flight per instance id: two concurrent first calls used to
        # both dial, with the loser's socket leaking unclosed
        dial = self._dials.get(inst.instance_id)
        if dial is None:
            dial = asyncio.ensure_future(self._dial(inst))
            self._dials[inst.instance_id] = dial
            dial.add_done_callback(
                lambda _t, iid=inst.instance_id: self._dials.pop(iid, None)
            )
        # shield: a cancelled caller must not kill the shared dial the
        # other waiters (or the warm pool) are relying on
        try:
            return await asyncio.shield(dial)
        except asyncio.CancelledError:
            if dial.cancelled():
                # the dial itself was torn down (instance deleted mid-dial):
                # surface a retryable stream death, not caller cancellation
                raise StreamError(
                    f"instance {inst.instance_id:x} went away mid-dial"
                ) from None
            raise

    async def _dial(self, inst: Instance) -> InstanceChannel:
        ch = InstanceChannel(inst.host, inst.port, uds=inst.uds)
        try:
            await ch.connect(self._drt.config.connect_timeout_s)
        except (OSError, asyncio.TimeoutError) as e:
            await ch.close()
            raise StreamError(f"connect to {inst.host}:{inst.port} failed: {e}") from e
        self._channels[inst.instance_id] = ch
        return ch

    async def close(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
        for dial in list(self._dials.values()):
            dial.cancel()
        self._dials.clear()
        for ch in self._channels.values():
            await ch.close()
        self._channels.clear()
