"""Distributed tracing: W3C traceparent propagation + JSONL spans.

Role of the reference's tracing stack (lib/runtime/src/logging.rs:72-87,
:147 — OTEL/OTLP exporter with W3C context propagation across
HTTP->NATS->worker hops). This environment has no OTLP collector or
opentelemetry package, so spans are emitted as structured JSONL log
records carrying trace_id/span_id/parent — the same correlation model,
greppable and collector-ingestable. The ``traceparent`` header follows
https://www.w3.org/TR/trace-context/ (version 00) so external clients and
proxies interoperate.

Propagation: the frontend extracts/creates a traceparent per request and
stashes it in Context.headers; the transport carries headers to workers
(runtime/transport.py frame field); workers bind the trace with
``bind_trace(context.headers)`` so their spans join the request's trace.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import secrets
import time
from dataclasses import dataclass

log = logging.getLogger("dynamo.trace")

TRACEPARENT = "traceparent"

_current: contextvars.ContextVar["TraceContext | None"] = contextvars.ContextVar(
    "dynamo_trace", default=None
)


@dataclass(frozen=True)
class TraceContext:
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    sampled: bool = True

    def to_traceparent(self) -> str:
        flags = "01" if self.sampled else "00"
        return f"00-{self.trace_id}-{self.span_id}-{flags}"

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, _new_span_id(), self.sampled)


def _new_span_id() -> str:
    return secrets.token_hex(8)


def new_trace() -> TraceContext:
    return TraceContext(secrets.token_hex(16), _new_span_id())


def parse_traceparent(header: str | None) -> TraceContext | None:
    """W3C header -> TraceContext; None on absent/malformed."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if (
        len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16
        or trace_id == "0" * 32 or span_id == "0" * 16
    ):
        return None
    try:
        sampled = bool(int(flags, 16) & 1)
    except ValueError:
        return None
    return TraceContext(trace_id.lower(), span_id.lower(), sampled)


def current_trace() -> TraceContext | None:
    return _current.get()


def ensure_trace(headers: dict[str, str] | None = None) -> TraceContext:
    """Extract the incoming trace or start a new one; writes the (child)
    traceparent back into ``headers`` so downstream hops continue it."""
    incoming = parse_traceparent((headers or {}).get(TRACEPARENT))
    tc = incoming.child() if incoming else new_trace()
    if headers is not None:
        headers[TRACEPARENT] = tc.to_traceparent()
    _current.set(tc)
    return tc


def bind_trace(headers: dict[str, str] | None) -> TraceContext | None:
    """Worker side: join the caller's trace from propagated headers."""
    tc = parse_traceparent((headers or {}).get(TRACEPARENT))
    if tc is not None:
        tc = tc.child()
        _current.set(tc)
    return tc


@contextlib.contextmanager
def span(name: str, **attrs):
    """Timed span under the current trace, emitted as one JSONL record."""
    parent = _current.get()
    tc = parent.child() if parent else new_trace()
    token = _current.set(tc)
    t0 = time.monotonic()
    error: str | None = None
    try:
        yield tc
    except BaseException as e:
        error = f"{type(e).__name__}: {e}"
        raise
    finally:
        _current.reset(token)
        record = {
            "span": name,
            "trace_id": tc.trace_id,
            "span_id": tc.span_id,
            "parent_span_id": parent.span_id if parent else None,
            "duration_ms": round((time.monotonic() - t0) * 1e3, 3),
            **attrs,
        }
        if error:
            record["error"] = error
        log.info("%s", json.dumps(record))
