"""Distributed tracing: W3C traceparent propagation + JSONL spans +
optional OTLP/HTTP export.

Role of the reference's tracing stack (lib/runtime/src/logging.rs:72-87,
:147 — OTEL/OTLP exporter with W3C context propagation across
HTTP->NATS->worker hops). Spans are always emitted as structured JSONL
log records carrying trace_id/span_id/parent; when an OTLP endpoint is
configured (``DYN_OTLP_ENDPOINT`` or ``set_otlp_endpoint``), the same
spans also batch to ``{endpoint}/v1/traces`` as OTLP/HTTP JSON — the
opentelemetry package is not required; the request body is built by
hand to the OTLP spec, so any standard collector ingests it. The
``traceparent`` header follows https://www.w3.org/TR/trace-context/
(version 00) so external clients and proxies interoperate.

Propagation: the frontend extracts/creates a traceparent per request and
stashes it in Context.headers; the transport carries headers to workers
(runtime/transport.py frame field); workers bind the trace with
``bind_trace(context.headers)`` so their spans join the request's trace.
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import json
import logging
import os
import queue
import secrets
import threading
import time
import urllib.request
from dataclasses import dataclass

log = logging.getLogger("dynamo.trace")

TRACEPARENT = "traceparent"

_current: contextvars.ContextVar["TraceContext | None"] = contextvars.ContextVar(
    "dynamo_trace", default=None
)


@dataclass(frozen=True)
class TraceContext:
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    sampled: bool = True

    def to_traceparent(self) -> str:
        flags = "01" if self.sampled else "00"
        return f"00-{self.trace_id}-{self.span_id}-{flags}"

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, _new_span_id(), self.sampled)


def _new_span_id() -> str:
    return secrets.token_hex(8)


def new_trace() -> TraceContext:
    return TraceContext(secrets.token_hex(16), _new_span_id())


def parse_traceparent(header: str | None) -> TraceContext | None:
    """W3C header -> TraceContext; None on absent/malformed."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if (
        len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16
        or trace_id == "0" * 32 or span_id == "0" * 16
    ):
        return None
    try:
        sampled = bool(int(flags, 16) & 1)
    except ValueError:
        return None
    return TraceContext(trace_id.lower(), span_id.lower(), sampled)


def current_trace() -> TraceContext | None:
    return _current.get()


def ensure_trace(headers: dict[str, str] | None = None) -> TraceContext:
    """Extract the incoming trace or start a new one; writes the (child)
    traceparent back into ``headers`` so downstream hops continue it."""
    incoming = parse_traceparent((headers or {}).get(TRACEPARENT))
    tc = incoming.child() if incoming else new_trace()
    if headers is not None:
        headers[TRACEPARENT] = tc.to_traceparent()
    _current.set(tc)
    return tc


def bind_trace(headers: dict[str, str] | None) -> TraceContext | None:
    """Worker side: join the caller's trace from propagated headers."""
    tc = parse_traceparent((headers or {}).get(TRACEPARENT))
    if tc is not None:
        tc = tc.child()
        _current.set(tc)
    return tc


@contextlib.contextmanager
def span(name: str, **attrs):
    """Timed span under the current trace, emitted as one JSONL record
    (and to the OTLP exporter when configured)."""
    parent = _current.get()
    tc = parent.child() if parent else new_trace()
    token = _current.set(tc)
    t0 = time.monotonic()
    start_ns = time.time_ns()
    error: str | None = None
    try:
        yield tc
    except BaseException as e:
        error = f"{type(e).__name__}: {e}"
        raise
    finally:
        _current.reset(token)
        dur_ms = round((time.monotonic() - t0) * 1e3, 3)
        record = {
            "span": name,
            "trace_id": tc.trace_id,
            "span_id": tc.span_id,
            "parent_span_id": parent.span_id if parent else None,
            "duration_ms": dur_ms,
            **attrs,
        }
        if error:
            record["error"] = error
        log.info("%s", json.dumps(record))
        exporter = _exporter()
        if exporter is not None:
            exporter.enqueue(
                name, tc, parent, start_ns,
                start_ns + int(dur_ms * 1e6), attrs, error,
            )


# ------------------------------------------------------------ OTLP export


class OtlpExporter:
    """Batching OTLP/HTTP JSON exporter (ref logging.rs otlp_exporter_
    enabled). Spans queue from any thread; a daemon thread batches and
    POSTs to ``{endpoint}/v1/traces``. Failures drop batches with a
    warning — tracing must never take serving down."""

    def __init__(self, endpoint: str, *, service_name: str = "dynamo-tpu",
                 flush_interval_s: float = 1.0, max_batch: int = 256):
        self.url = endpoint.rstrip("/") + "/v1/traces"
        self.service_name = service_name
        self.flush_interval_s = flush_interval_s
        self.max_batch = max_batch
        self._q: queue.Queue = queue.Queue(maxsize=4096)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="otlp-export", daemon=True
        )
        self._thread.start()

    def enqueue(self, name, tc, parent, start_ns, end_ns, attrs, error):
        span = {
            "traceId": tc.trace_id,
            "spanId": tc.span_id,
            "name": name,
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(end_ns),
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in attrs.items()
            ],
            "status": (
                {"code": 2, "message": error} if error else {"code": 1}
            ),
        }
        if parent is not None:
            span["parentSpanId"] = parent.span_id
        try:
            self._q.put_nowait(span)
        except queue.Full:
            pass  # drop under backpressure

    def _drain(self, timeout: float) -> list[dict]:
        spans: list[dict] = []
        try:
            spans.append(self._q.get(timeout=timeout))
            while len(spans) < self.max_batch:
                spans.append(self._q.get_nowait())
        except queue.Empty:
            pass
        return spans

    def _post(self, spans: list[dict]) -> None:
        body = json.dumps({
            "resourceSpans": [{
                "resource": {"attributes": [{
                    "key": "service.name",
                    "value": {"stringValue": self.service_name},
                }]},
                "scopeSpans": [{
                    "scope": {"name": "dynamo_tpu.runtime.tracing"},
                    "spans": spans,
                }],
            }]
        }).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=5).read()

    def _run(self) -> None:
        while not self._stop.is_set():
            spans = self._drain(self.flush_interval_s)
            if not spans:
                continue
            try:
                self._post(spans)
            except Exception:  # noqa: BLE001
                log.warning("OTLP export failed (%d spans dropped)",
                            len(spans))

    def flush(self, timeout: float = 5.0) -> None:
        """Best-effort synchronous drain — tests and shutdown ONLY.
        Span emission itself never calls this (enqueue + daemon thread);
        loop-side reconfiguration should prefer ``aflush``."""
        deadline = time.monotonic() + timeout
        while not self._q.empty() and time.monotonic() < deadline:
            # dynalint: disable=DL001 -- shutdown/test drain, off-loop by
            # contract; aflush() is the event-loop-safe variant
            time.sleep(0.02)
        # one extra beat for the in-flight POST
        # dynalint: disable=DL001 -- same shutdown-only contract as above
        time.sleep(0.05)

    async def aflush(self, timeout: float = 5.0) -> None:
        """Event-loop-safe drain: same semantics as flush() without
        parking the loop (dynalint DL001)."""
        await asyncio.to_thread(self.flush, timeout)

    def close(self) -> None:
        self.flush()
        self._stop.set()


_otlp: OtlpExporter | None = None
_otlp_checked = False


def set_otlp_endpoint(endpoint: str | None, **kw) -> OtlpExporter | None:
    """Install (or clear, with None) the process-wide OTLP exporter."""
    global _otlp, _otlp_checked
    if _otlp is not None:
        _otlp.close()
    _otlp = OtlpExporter(endpoint, **kw) if endpoint else None
    _otlp_checked = True
    return _otlp


def _exporter() -> OtlpExporter | None:
    global _otlp, _otlp_checked
    if not _otlp_checked:
        _otlp_checked = True
        env = (os.environ.get("DYN_OTLP_ENDPOINT") or "").strip()
        if env:
            _otlp = OtlpExporter(env)
    return _otlp
