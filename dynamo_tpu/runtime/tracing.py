"""Distributed tracing: W3C traceparent propagation + JSONL spans +
optional OTLP/HTTP export.

Role of the reference's tracing stack (lib/runtime/src/logging.rs:72-87,
:147 — OTEL/OTLP exporter with W3C context propagation across
HTTP->NATS->worker hops). Spans are always emitted as structured JSONL
log records carrying trace_id/span_id/parent; when an OTLP endpoint is
configured (``DYN_OTLP_ENDPOINT`` or ``set_otlp_endpoint``), the same
spans also batch to ``{endpoint}/v1/traces`` as OTLP/HTTP JSON — the
opentelemetry package is not required; the request body is built by
hand to the OTLP spec, so any standard collector ingests it. With
``DYN_TRACE_FILE`` set (or ``set_trace_file``), every span record also
appends to that JSONL file — the artifact the e2e trace tests and the
flight-recorder docs parse. The ``traceparent`` header follows
https://www.w3.org/TR/trace-context/ (version 00) so external clients
and proxies interoperate.

Propagation: the frontend extracts the incoming trace (``bind_trace``)
and opens its server span; the transport client stamps ITS span's
traceparent onto the wire headers at send time; the worker binds the
caller's span context and the engine emits the request-lifecycle spans
under it (runtime/flight.py). Every emitted span name is catalogued in
tools/dynalint/catalog.py SPAN_NAMES (dynalint DL006 enforces the sync,
like fault sites and metric names).
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import json
import logging
import os
import queue
import secrets
import threading
import time
import urllib.request
from dataclasses import dataclass

log = logging.getLogger("dynamo.trace")

TRACEPARENT = "traceparent"

_current: contextvars.ContextVar["TraceContext | None"] = contextvars.ContextVar(
    "dynamo_trace", default=None
)


@dataclass(frozen=True)
class TraceContext:
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    sampled: bool = True

    def to_traceparent(self) -> str:
        flags = "01" if self.sampled else "00"
        return f"00-{self.trace_id}-{self.span_id}-{flags}"

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, new_span_id(), self.sampled)


def new_span_id() -> str:
    return secrets.token_hex(8)


def new_trace() -> TraceContext:
    return TraceContext(secrets.token_hex(16), new_span_id())


_HEX = frozenset("0123456789abcdef")


def _lower_hex(s: str) -> bool:
    """W3C trace-context requires LOWERCASE hex; uppercase is malformed."""
    return bool(s) and all(c in _HEX for c in s)


def parse_traceparent(header: str | None) -> TraceContext | None:
    """W3C header -> TraceContext; None on absent/malformed.

    Spec-compliant rejection set (https://www.w3.org/TR/trace-context/):
    wrong field count/length, non-hex or UPPERCASE hex in any field,
    version ``ff`` (explicitly forbidden), and all-zero trace/span ids.
    """
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if (
        len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16
        or len(flags) != 2
        or not _lower_hex(version) or not _lower_hex(trace_id)
        or not _lower_hex(span_id) or not _lower_hex(flags)
        or version == "ff"  # forbidden by the spec
        or trace_id == "0" * 32 or span_id == "0" * 16
    ):
        return None
    sampled = bool(int(flags, 16) & 1)
    return TraceContext(trace_id, span_id, sampled)


def current_trace() -> TraceContext | None:
    return _current.get()


def set_current(tc: TraceContext | None) -> None:
    """Explicitly (re)bind the current trace context. Prefer ``span()`` /
    ``bind_trace``; this is the escape hatch for code that manages span
    identities by hand (flight-recorder span derivation)."""
    _current.set(tc)


def ensure_trace(headers: dict[str, str] | None = None) -> TraceContext:
    """Extract the incoming trace or start a new one; writes the (child)
    traceparent back into ``headers`` so downstream hops continue it."""
    incoming = parse_traceparent((headers or {}).get(TRACEPARENT))
    tc = incoming.child() if incoming else new_trace()
    if headers is not None:
        headers[TRACEPARENT] = tc.to_traceparent()
    _current.set(tc)
    return tc


def bind_trace(headers) -> TraceContext | None:
    """Server side: join the CALLER's span context from propagated
    headers — the parsed context becomes current (the remote parent), so
    the first ``span()`` opened here is its direct child and the cross-
    process parent chain has no unemitted gap. Absent or malformed
    headers CLEAR the binding: a task reused across requests (keep-alive
    HTTP connections, transport reader loops) must not leak the previous
    request's trace into the next."""
    tc = parse_traceparent((headers or {}).get(TRACEPARENT))
    _current.set(tc)
    return tc


def _record_span(
    name: str,
    tc: TraceContext,
    parent_span_id: str | None,
    start_ns: int,
    end_ns: int,
    attrs: dict | None,
    error: str | None,
) -> None:
    """Single emission chokepoint: JSONL log record + optional trace file
    + optional OTLP batch. Never raises (tracing must not take serving
    down)."""
    if not _tracing_active():
        # nothing will observe this span: skip the JSON serialization
        return
    record = {
        "span": name,
        "trace_id": tc.trace_id,
        "span_id": tc.span_id,
        "parent_span_id": parent_span_id,
        "duration_ms": round((end_ns - start_ns) / 1e6, 3),
        **(attrs or {}),
    }
    if error:
        record["error"] = error
    line = json.dumps(record)
    log.info("%s", line)
    if _file_sink() is not None:
        try:
            with _trace_file_lock:
                # re-read under the lock: a concurrent set_trace_file
                # may have closed the handle _file_sink() returned
                if _trace_file is not None:
                    _trace_file.write(line + "\n")
                    _trace_file.flush()
        except (OSError, ValueError):  # disk full / closed file: drop,
            pass  # keep serving
    exporter = _exporter()
    if exporter is not None:
        exporter.enqueue(
            name, tc, parent_span_id, start_ns, end_ns, attrs or {}, error
        )


def emit_span(
    name: str,
    tc: TraceContext,
    *,
    parent_span_id: str | None = None,
    start_ns: int,
    end_ns: int,
    attrs: dict | None = None,
    error: str | None = None,
) -> None:
    """Emit one already-timed span with an explicit identity — the
    low-level API behind ``span()``, used where timings were recorded
    off-thread (the engine's flight recorder derives request-lifecycle
    spans from step-thread timestamps at finish)."""
    _record_span(name, tc, parent_span_id, start_ns, end_ns, attrs, error)


def _tracing_active() -> bool:
    """Anything observing spans in this process? When not, span() and
    _record_span() take fast paths — spans ride every pick and every
    transport call, and clock reads plus JSON serialization are
    measurable per-request tax at 1k+ req/s."""
    return (
        log.isEnabledFor(logging.INFO)
        or _file_sink() is not None
        or _exporter() is not None
    )


@contextlib.contextmanager
def span(name: str, **attrs):
    """Timed span under the current trace, emitted as one JSONL record
    (and to the trace file / OTLP exporter when configured)."""
    if not _tracing_active():
        # nothing records here: keep the identity contract (a fresh
        # child span context, installed for downstream wire hops) but
        # skip the clock reads and the record path entirely
        parent = _current.get()
        tc = parent.child() if parent else new_trace()
        token = _current.set(tc)
        try:
            yield tc
        finally:
            try:
                _current.reset(token)
            except ValueError:
                pass
        return
    parent = _current.get()
    tc = parent.child() if parent else new_trace()
    token = _current.set(tc)
    t0 = time.monotonic()
    start_ns = time.time_ns()
    error: str | None = None
    try:
        yield tc
    except BaseException as e:
        error = f"{type(e).__name__}: {e}"
        raise
    finally:
        try:
            _current.reset(token)
        except ValueError:
            # abandoned-async-generator finalization runs in a fresh
            # context (loop shutdown_asyncgens / GC hook); the token is
            # foreign there. The binding we'd reset doesn't exist in
            # this context anyway — emit the span and move on.
            pass
        end_ns = start_ns + int((time.monotonic() - t0) * 1e9)
        _record_span(
            name, tc, parent.span_id if parent else None,
            start_ns, end_ns, attrs, error,
        )


# ------------------------------------------------------------ file sink

_trace_file_lock = threading.Lock()
_trace_file = None
_trace_file_checked = False


def set_trace_file(path: str | None):
    """Install (or clear, with None) the process-wide span JSONL file."""
    global _trace_file, _trace_file_checked
    with _trace_file_lock:
        if _trace_file is not None:
            try:
                _trace_file.close()
            except OSError:
                pass
        _trace_file = open(path, "a") if path else None
        _trace_file_checked = True
    return _trace_file


def _file_sink():
    global _trace_file, _trace_file_checked
    if not _trace_file_checked:
        with _trace_file_lock:
            if not _trace_file_checked:
                _trace_file_checked = True
                env = (os.environ.get("DYN_TRACE_FILE") or "").strip()
                if env:
                    try:
                        _trace_file = open(env, "a")
                    except OSError as e:
                        log.warning("DYN_TRACE_FILE %r unusable: %s", env, e)
    return _trace_file


# ------------------------------------------------------------ OTLP export


class OtlpExporter:
    """Batching OTLP/HTTP JSON exporter (ref logging.rs otlp_exporter_
    enabled). Spans queue from any thread; a daemon thread batches and
    POSTs to ``{endpoint}/v1/traces``. Failures drop batches with a
    warning — tracing must never take serving down. ``close()`` drains
    the queue AND joins the worker thread, so the final batch's POST
    completes (or fails loudly) before shutdown proceeds."""

    def __init__(self, endpoint: str, *, service_name: str = "dynamo-tpu",
                 flush_interval_s: float = 1.0, max_batch: int = 256):
        self.url = endpoint.rstrip("/") + "/v1/traces"
        self.service_name = service_name
        self.flush_interval_s = flush_interval_s
        self.max_batch = max_batch
        self._q: queue.Queue = queue.Queue(maxsize=4096)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="otlp-export", daemon=True
        )
        self._thread.start()

    def enqueue(self, name, tc, parent_span_id, start_ns, end_ns, attrs,
                error):
        span = {
            "traceId": tc.trace_id,
            "spanId": tc.span_id,
            "name": name,
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(end_ns),
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in attrs.items()
            ],
            "status": (
                {"code": 2, "message": error} if error else {"code": 1}
            ),
        }
        if parent_span_id is not None:
            span["parentSpanId"] = parent_span_id
        try:
            self._q.put_nowait(span)
        except queue.Full:
            pass  # drop under backpressure

    def _drain(self, timeout: float) -> list[dict]:
        spans: list[dict] = []
        try:
            spans.append(self._q.get(timeout=timeout))
            while len(spans) < self.max_batch:
                spans.append(self._q.get_nowait())
        except queue.Empty:
            pass
        return spans

    def _post(self, spans: list[dict]) -> None:
        body = json.dumps({
            "resourceSpans": [{
                "resource": {"attributes": [{
                    "key": "service.name",
                    "value": {"stringValue": self.service_name},
                }]},
                "scopeSpans": [{
                    "scope": {"name": "dynamo_tpu.runtime.tracing"},
                    "spans": spans,
                }],
            }]
        }).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=5).read()

    def _run(self) -> None:
        # loop until a stop is requested AND the queue has drained: the
        # old exit-on-stop shape dropped whatever the final _drain had
        # not yet POSTed (the in-flight-batch shutdown race)
        while True:
            spans = self._drain(
                0.01 if self._stop.is_set() else self.flush_interval_s
            )
            if spans:
                try:
                    self._post(spans)
                except Exception:  # noqa: BLE001
                    log.warning("OTLP export failed (%d spans dropped)",
                                len(spans))
            elif self._stop.is_set():
                return

    def flush(self, timeout: float = 5.0) -> None:
        """Best-effort synchronous drain — tests and shutdown ONLY.
        Span emission itself never calls this (enqueue + daemon thread);
        loop-side reconfiguration should prefer ``aflush``."""
        deadline = time.monotonic() + timeout
        while not self._q.empty() and time.monotonic() < deadline:
            # dynalint: disable=DL001 -- shutdown/test drain, off-loop by
            # contract; aflush() is the event-loop-safe variant
            time.sleep(0.02)
        # one extra beat for the in-flight POST
        # dynalint: disable=DL001 -- same shutdown-only contract as above
        time.sleep(0.05)

    async def aflush(self, timeout: float = 5.0) -> None:
        """Event-loop-safe drain: same semantics as flush() without
        parking the loop (dynalint DL001)."""
        await asyncio.to_thread(self.flush, timeout)

    def close(self, timeout: float = 5.0) -> None:
        """Flush AND join: the worker thread drains the queue, finishes
        its final POST, and exits before close() returns — queued spans
        can no longer drop silently at shutdown (they either land at the
        collector or log an export-failure warning)."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)
            if self._thread.is_alive():  # pragma: no cover - wedged POST
                log.warning("OTLP exporter did not drain within %.1fs",
                            timeout)


_otlp: OtlpExporter | None = None
_otlp_checked = False


def set_otlp_endpoint(endpoint: str | None, **kw) -> OtlpExporter | None:
    """Install (or clear, with None) the process-wide OTLP exporter."""
    global _otlp, _otlp_checked
    if _otlp is not None:
        _otlp.close()
    _otlp = OtlpExporter(endpoint, **kw) if endpoint else None
    _otlp_checked = True
    return _otlp


def _exporter() -> OtlpExporter | None:
    global _otlp, _otlp_checked
    if not _otlp_checked:
        _otlp_checked = True
        env = (os.environ.get("DYN_OTLP_ENDPOINT") or "").strip()
        if env:
            _otlp = OtlpExporter(env)
    return _otlp
