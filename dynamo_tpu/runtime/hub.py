"""The hub: cluster coordination service (discovery + events + objects).

The reference requires two external services - etcd (discovery, leases, model
cards, config watches; lib/runtime/src/transports/etcd.rs) and NATS (request
transport, JetStream KV-event streams, object store; transports/nats.rs). This
framework self-hosts one small coordination service with the union of the
*capabilities actually used*:

  - lease-scoped KV store with atomic create and prefix watches (etcd role)
  - pub/sub subjects with wildcard suffix match (JetStream event-stream role)
  - object store buckets (NATS object-store role: model cards, router snapshots)

Requests do NOT flow through the hub - the data plane is direct worker TCP
(see transport.py) - so the hub stays off the hot path, like etcd/NATS-core in
the reference. ``InMemoryHub`` backs single-process tests; ``hub_server.py``
exposes the same interface over TCP for multi-process deployments.
"""

from __future__ import annotations

import asyncio
import fnmatch
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, AsyncIterator

__all__ = ["WatchEvent", "Hub", "InMemoryHub", "KeyExists", "NoQuorum"]


class KeyExists(Exception):
    """Atomic create failed: key already present."""


class NoQuorum(Exception):
    """A replicated-hub write could not reach a majority of the configured
    replica set before the commit timeout (leader cut off mid-partition,
    or too few replicas up). The write is NOT durably committed — it may
    be discarded when the partition heals. Surfaced to clients as a
    retryable ``no_quorum`` error (hub_client.py treats it like a
    mid-election ``not_leader`` bounce)."""


@dataclass(frozen=True)
class WatchEvent:
    """One KV mutation delivered to a prefix watcher."""

    kind: str  # "put" | "delete"
    key: str
    value: Any = None


@dataclass
class _Lease:
    lease_id: int
    ttl: float
    deadline: float
    keys: set[str] = field(default_factory=set)


class Hub:
    """Abstract hub interface (see module docstring)."""

    async def get_boot_id(self) -> str | None:
        """Identity of this hub INSTANCE: per-subject seq counters live
        in hub memory, so two boots have incomparable seq spaces. A
        consumer persisting seq baselines (the KV router's radix
        snapshot) must reset them when the boot id changes. None =
        unknown (older peers)."""
        return getattr(self, "boot_id", None)

    # -- kv ---------------------------------------------------------------
    async def put(self, key: str, value: Any, lease_id: int | None = None) -> None:
        raise NotImplementedError

    async def create(self, key: str, value: Any, lease_id: int | None = None) -> None:
        """Atomic create: raise KeyExists if the key is already present."""
        raise NotImplementedError

    async def get(self, key: str) -> Any:
        raise NotImplementedError

    async def delete(self, key: str) -> bool:
        raise NotImplementedError

    async def get_prefix(self, prefix: str) -> dict[str, Any]:
        raise NotImplementedError

    def watch_prefix(
        self, prefix: str, *, initial: bool = True, sync_marker: bool = False
    ) -> AsyncIterator[WatchEvent]:
        """Stream of WatchEvents for keys under ``prefix``.

        With ``initial=True`` the current contents are replayed as synthetic
        "put" events first (ref etcd.rs kv_get_and_watch_prefix). With
        ``sync_marker=True`` a ``kind="sync"`` event delimits the end of
        that replay — reconnecting clients use it to diff their known key
        set against the fresh snapshot (hub_client.py re-sync).
        """
        raise NotImplementedError

    # -- leases ------------------------------------------------------------
    async def grant_lease(self, ttl_s: float) -> int:
        raise NotImplementedError

    async def keepalive(self, lease_id: int) -> bool:
        raise NotImplementedError

    async def revoke_lease(self, lease_id: int) -> None:
        raise NotImplementedError

    # -- pub/sub -----------------------------------------------------------
    async def publish(
        self, subject: str, payload: Any, pub_id: str | None = None
    ) -> bool:
        """Publish one event. ``pub_id`` is an optional client-unique
        idempotency id: a retried publish (at-least-once transports
        re-send after a lost ack) carrying an already-seen id is dropped
        instead of minting a duplicate event under a fresh seq. Returns
        True when the event was applied, False when deduplicated."""
        raise NotImplementedError

    async def purge_subject(
        self, subject: str, keep_last: int = 0,
        up_to_seq: int | None = None,
    ) -> int:
        """Drop retained history for ``subject`` (snapshot compaction:
        after a consumer persists a snapshot, replay for late starters
        only needs the uncovered tail). With ``up_to_seq`` only events
        whose publish sequence is <= that value are dropped — the caller
        passes the seq of the last event its snapshot covers, so nothing
        unseen is ever lost; otherwise all but the newest ``keep_last``
        drop. Returns the number of events dropped."""
        raise NotImplementedError

    def subscribe(
        self, subject: str, *, replay: bool = False
    ) -> AsyncIterator[tuple[str, Any]]:
        """Subscribe to a subject; ``*`` suffix wildcard supported.

        With ``replay=True`` retained history for the subject is delivered
        first (JetStream-style persistent stream: late subscribers catch up
        on e.g. KV cache events published before they joined).
        """
        raise NotImplementedError

    # -- object store ------------------------------------------------------
    async def put_object(self, bucket: str, name: str, data: bytes) -> None:
        raise NotImplementedError

    async def get_object(self, bucket: str, name: str) -> bytes | None:
        raise NotImplementedError

    async def delete_object(self, bucket: str, name: str) -> None:
        raise NotImplementedError

    async def close(self) -> None:
        pass


class InMemoryHub(Hub):
    """Single-process hub; also the core logic reused by the TCP hub server."""

    RETAIN_PER_SUBJECT = 65536
    # publish-dedup window: ids older than this many publishes age out.
    # Retries happen within a reconnect window (seconds), so a bounded
    # recent-id set is enough — this is NATS-style msg-id dedup, not an
    # unbounded ledger.
    PUB_ID_WINDOW = 8192

    def __init__(self) -> None:
        import uuid

        self.boot_id = uuid.uuid4().hex
        self._retained: dict[str, deque] = {}  # subject -> (seq, payload)
        self._subject_seq: dict[str, int] = {}  # publish counter per subject
        self._seen_pub_ids: "OrderedDict[str, None]" = OrderedDict()
        self._kv: dict[str, Any] = {}
        self._key_lease: dict[str, int] = {}
        self._leases: dict[int, _Lease] = {}
        self._next_lease = 1
        self._watchers: list[tuple[str, asyncio.Queue]] = []
        self._subs: list[tuple[str, asyncio.Queue]] = []
        self._objects: dict[tuple[str, str], bytes] = {}
        self._reaper: asyncio.Task | None = None
        self._closed = False

    # -- internals ---------------------------------------------------------

    def _notify(self, ev: WatchEvent) -> None:
        for prefix, q in self._watchers:
            if ev.key.startswith(prefix):
                q.put_nowait(ev)

    def _ensure_reaper(self) -> None:
        if self._reaper is None or self._reaper.done():
            self._reaper = asyncio.get_running_loop().create_task(self._reap_loop())

    async def _reap_loop(self) -> None:
        while not self._closed:
            await asyncio.sleep(0.5)
            self.reap_expired()

    def reap_expired(self, now: float | None = None) -> list[int]:
        """Expire leases whose deadline passed; drop their keys. Returns ids."""
        now = time.monotonic() if now is None else now
        expired = [l for l in self._leases.values() if l.deadline <= now]
        for lease in expired:
            self._drop_lease(lease)
        return [l.lease_id for l in expired]

    def _drop_lease(self, lease: _Lease) -> None:
        self._leases.pop(lease.lease_id, None)
        for key in sorted(lease.keys):
            if self._kv.pop(key, None) is not None:
                self._key_lease.pop(key, None)
                self._notify(WatchEvent("delete", key))

    # -- kv ---------------------------------------------------------------

    async def put(self, key: str, value: Any, lease_id: int | None = None) -> None:
        if lease_id is not None:
            lease = self._leases.get(lease_id)
            if lease is None:
                raise ValueError(f"unknown lease {lease_id}")
            lease.keys.add(key)
            self._key_lease[key] = lease_id
        self._kv[key] = value
        self._notify(WatchEvent("put", key, value))

    async def create(self, key: str, value: Any, lease_id: int | None = None) -> None:
        if key in self._kv:
            raise KeyExists(key)
        await self.put(key, value, lease_id)

    async def get(self, key: str) -> Any:
        return self._kv.get(key)

    async def delete(self, key: str) -> bool:
        if key in self._kv:
            del self._kv[key]
            lease_id = self._key_lease.pop(key, None)
            if lease_id is not None and lease_id in self._leases:
                self._leases[lease_id].keys.discard(key)
            self._notify(WatchEvent("delete", key))
            return True
        return False

    async def get_prefix(self, prefix: str) -> dict[str, Any]:
        return {k: v for k, v in self._kv.items() if k.startswith(prefix)}

    async def watch_prefix(
        self, prefix: str, *, initial: bool = True, sync_marker: bool = False
    ) -> AsyncIterator[WatchEvent]:
        q: asyncio.Queue = asyncio.Queue()
        snapshot = (
            [WatchEvent("put", k, v) for k, v in sorted(self._kv.items()) if k.startswith(prefix)]
            if initial
            else []
        )
        self._watchers.append((prefix, q))
        try:
            for ev in snapshot:
                yield ev
            if sync_marker:
                yield WatchEvent("sync", "")
            while True:
                yield await q.get()
        finally:
            self._watchers.remove((prefix, q))

    # -- leases ------------------------------------------------------------

    async def grant_lease(self, ttl_s: float) -> int:
        self._ensure_reaper()
        lease_id = self._next_lease
        self._next_lease += 1
        self._leases[lease_id] = _Lease(
            lease_id, ttl_s, time.monotonic() + ttl_s
        )
        return lease_id

    async def keepalive(self, lease_id: int) -> bool:
        lease = self._leases.get(lease_id)
        if lease is None:
            return False
        lease.deadline = time.monotonic() + lease.ttl
        return True

    async def revoke_lease(self, lease_id: int) -> None:
        lease = self._leases.get(lease_id)
        if lease is not None:
            self._drop_lease(lease)

    # -- pub/sub -----------------------------------------------------------

    def _subject_seq_base(self) -> int:
        """Seq baseline for a subject with no recorded counter. The
        replicated hub overrides this (hub_replica.py): after a
        failover, subjects created in the dead leader's unshipped tail
        are unknown to the promoted leader, and minting their seqs from
        0 would make subscriber seq-dedup silently drop the first
        post-failover events."""
        return 0

    def _pub_id_fresh(self, pub_id: str | None) -> bool:
        """Record ``pub_id`` in the bounded dedup window; False when the
        id was already seen (a retried publish — drop it)."""
        if pub_id is None:
            return True
        if pub_id in self._seen_pub_ids:
            return False
        self._seen_pub_ids[pub_id] = None
        while len(self._seen_pub_ids) > self.PUB_ID_WINDOW:
            self._seen_pub_ids.popitem(last=False)
        return True

    async def publish(
        self, subject: str, payload: Any, pub_id: str | None = None
    ) -> bool:
        if not self._pub_id_fresh(pub_id):
            return False  # retried duplicate: already applied
        if subject not in self._retained:
            self._retained[subject] = deque(maxlen=self.RETAIN_PER_SUBJECT)
        seq = self._subject_seq.get(subject, self._subject_seq_base()) + 1
        self._subject_seq[subject] = seq
        self._retained[subject].append((seq, payload))
        for pattern, q in self._subs:
            if fnmatch.fnmatchcase(subject, pattern):
                q.put_nowait((subject, payload, seq))
        return True

    async def purge_subject(
        self, subject: str, keep_last: int = 0,
        up_to_seq: int | None = None,
    ) -> int:
        dropped = 0
        for subj in list(self._retained):
            if not fnmatch.fnmatchcase(subj, subject):
                continue
            dq = self._retained[subj]
            if up_to_seq is not None:
                while dq and dq[0][0] <= up_to_seq:
                    dq.popleft()
                    dropped += 1
            else:
                while len(dq) > keep_last:
                    dq.popleft()
                    dropped += 1
        return dropped

    async def subscribe(
        self, subject: str, *, replay: bool = False, with_seq: bool = False
    ) -> AsyncIterator[tuple]:
        # Snapshot history, then register live - both synchronous, so no gap
        # (single-threaded event loop) and no duplicates.
        backlog: list[tuple[str, Any, int]] = []
        if replay:
            for subj in sorted(self._retained):
                if fnmatch.fnmatchcase(subj, subject):
                    backlog.extend(
                        (subj, p, s) for s, p in self._retained[subj]
                    )
        q: asyncio.Queue = asyncio.Queue()
        self._subs.append((subject, q))
        try:
            for item in backlog:
                yield item if with_seq else item[:2]
            while True:
                item = await q.get()
                yield item if with_seq else item[:2]
        finally:
            self._subs.remove((subject, q))

    # -- object store ------------------------------------------------------

    async def put_object(self, bucket: str, name: str, data: bytes) -> None:
        self._objects[(bucket, name)] = bytes(data)

    async def get_object(self, bucket: str, name: str) -> bytes | None:
        return self._objects.get((bucket, name))

    async def delete_object(self, bucket: str, name: str) -> None:
        self._objects.pop((bucket, name), None)

    async def close(self) -> None:
        self._closed = True
        if self._reaper is not None:
            self._reaper.cancel()
