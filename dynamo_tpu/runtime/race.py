"""dynarace annotation shim: the package-side half of tools/dynarace.

Production code annotates its synchronization vocabulary through this
module — ``race.Lock(name)`` / ``race.Queue(name)`` / ``race.Event(name)``
factories for the primitives themselves, ``race.release/acquire`` for
ad-hoc happens-before edges (asyncio hand-offs, ``asyncio.to_thread``
boundaries), ``race.fork/join`` around thread lifecycles, and
``race.read/write`` for the catalogued shared state in
``tools/dynarace/registry.py``.

**Disabled (the default, ``DYN_RACE`` unset): everything here is a
no-op.** The factories return the plain stdlib objects (same types, zero
wrapper overhead on every subsequent acquire/put/set), and the annotate
functions are a shared ``_noop`` — one dict lookup and an empty call.
Nothing under ``tools/`` is imported. A tier-1 test
(tests/test_dynarace.py) asserts both properties: the import graph stays
clean and the disabled-path annotation cost is noise.

**Enabled (``DYN_RACE=1``):** the factories return instrumented wrappers
and the annotate functions feed the vector-clock happens-before detector
(tools/dynarace/detector.py). With ``DYN_RACE_SCHED=<seed>`` also set,
the wrappers additionally run the seeded deterministic schedule explorer
(tools/dynarace/sched.py): replayable yield points at sync boundaries,
biased toward just-released locks and just-put queue items.

``tools.dynarace`` lives in the repo checkout, not in the installed
package; if it is missing while ``DYN_RACE=1``, the shim warns once and
stays no-op — the flag is a dev/CI affordance, never a hard dependency.

Annotation discipline (docs/CONCURRENCY.md):

- annotate at per-step / per-request granularity, never per token;
- every ``race.read/write`` state string must be catalogued in
  tools/dynarace/registry.py ``SHARED_STATE`` (two-way, test-enforced
  against dynalint's catalog like the DL006 fault sites);
- every named ``race.Lock/Queue/Event`` must be catalogued in
  ``SYNC_POINTS``.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
from typing import Any

__all__ = [
    "ENABLED",
    "Event",
    "Lock",
    "Queue",
    "RLock",
    "acquire",
    "fork",
    "join",
    "read",
    "release",
    "write",
]

ENABLED = os.environ.get("DYN_RACE", "") == "1"


def _noop(*_args: Any, **_kwargs: Any) -> None:
    return None


# annotate functions (rebound below when enabled). Call through the
# module attribute (``race.write(...)``) so enabling rebinds every site.
read = _noop  # read(state: str) — catalogued shared-state read
write = _noop  # write(state: str) — catalogued shared-state write
acquire = _noop  # acquire(token, site) — HB edge: token's clock -> me
release = _noop  # release(token, site) — HB edge: me -> token's clock
fork = _noop  # fork(thread) — call in the parent just before .start()
join = _noop  # join(thread) — call in the parent after .join() returns


def Lock(name: str = "") -> "threading.Lock":  # noqa: N802 - factory
    """A ``threading.Lock`` (instrumented under DYN_RACE=1)."""
    return threading.Lock()


def RLock(name: str = "") -> "threading.RLock":  # noqa: N802 - factory
    """A ``threading.RLock`` (instrumented under DYN_RACE=1)."""
    return threading.RLock()


def Event(name: str = "") -> "threading.Event":  # noqa: N802 - factory
    """A ``threading.Event`` (instrumented under DYN_RACE=1)."""
    return threading.Event()


def Queue(name: str = "", maxsize: int = 0) -> "queue.Queue":  # noqa: N802
    """A ``queue.Queue`` (instrumented under DYN_RACE=1)."""
    return queue.Queue(maxsize=maxsize)


if ENABLED:  # pragma: no cover - exercised via subprocess tests
    try:
        from tools.dynarace import runtime as _rt
    except Exception:  # noqa: BLE001 - installed package without tools/
        logging.getLogger("dynamo.race").warning(
            "DYN_RACE=1 but tools.dynarace is not importable; "
            "race annotations stay no-op"
        )
    else:
        read = _rt.read
        write = _rt.write
        acquire = _rt.acquire
        release = _rt.release
        fork = _rt.fork
        join = _rt.join
        Lock = _rt.Lock  # noqa: F811 - deliberate enable-time rebind
        RLock = _rt.RLock  # noqa: F811
        Event = _rt.Event  # noqa: F811
        Queue = _rt.Queue  # noqa: F811
