"""TCP client for the hub service - same interface as InMemoryHub.

One connection per client, request/response multiplexed by message id;
watch/subscribe streams fan out to per-stream queues.

Reconnection is built in (``reconnect=True``): when the hub connection
drops — a hub crash/restart, not a clean close — calls retry after
re-dialing with backoff for up to ``reconnect_window_s``, and streams
re-establish transparently:

- ``watch_prefix`` re-opens with a fresh initial snapshot delimited by a
  server-side sync marker, diffs it against the keys it has already
  yielded, and emits synthetic ``delete`` events for keys that vanished
  while disconnected before replaying the snapshot — consumer state
  converges exactly (ref: etcd watch re-establishment semantics).
- ``subscribe`` with ``replay=True`` re-subscribes with replay and drops
  events whose per-subject seq it already delivered (the durable hub
  preserves seq counters across restarts, hub_store.py); with
  ``replay=False`` it re-subscribes live-only — events published while
  disconnected are lost, NATS-core semantics.

Retried mutations are at-least-once: a ``create`` whose ack was lost in
the crash may raise KeyExists on retry (same exposure etcd clients have
without txn ids). Workers still treat a hub that stays unreachable past
the reconnect window as fatal, mirroring the reference's etcd-loss =>
shutdown behavior (lib/runtime/src/lib.rs).

Replicated-hub failover (hub_replica.py): construct with a comma-
separated address list (or set ``DYN_HUB_ADDRESSES``) and the client
dials round-robin across replicas, follows ``not_leader`` redirects so
writes always land on the leader while reads are served by whichever
replica answered, and — because failover rides the same reconnect path —
re-syncs watches (snapshot diff) and re-subscribes with seq dedup
against the promoted follower exactly as it does across a restart (the
cluster shares one boot_id, so seq baselines stay valid).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import random
import time
from typing import Any, AsyncIterator

from dynamo_tpu.runtime import framing
from dynamo_tpu.runtime.faults import FAULTS
from dynamo_tpu.runtime.hub import Hub, KeyExists, WatchEvent
from dynamo_tpu.runtime.metrics import MetricsRegistry, register_registry

log = logging.getLogger("dynamo.hub.client")

# Failover observability, on every /metrics surface: a redirect-chase
# storm during a hub failover (every client bouncing not_leader /
# no_quorum around the replica ring) was previously only INFERRABLE from
# latency — these counters make it a first-class signal the cluster sim
# asserts on (dynamo_tpu/sim leader-kill / partition scenarios).
_METRICS = MetricsRegistry()
REDIRECTS = _METRICS.counter(
    "hub_redirects_total",
    "Hub client write bounces by reason "
    "(not_leader | no_quorum | unavailable).",
    ["reason"],
)
BACKOFF = _METRICS.histogram(
    "hub_backoff_seconds",
    "Seconds the hub client slept between redirect hops "
    "(server-hinted and exponential backoff alike).",
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0),
)
register_registry("hub_client", _METRICS)


def failover_stats() -> dict[str, float]:
    """Live sample of the redirect counters by reason (plus the backoff
    histogram's count/sum) — the sim's leader-kill and partition
    scenarios diff this across a chaos window instead of scraping and
    parsing their own /metrics exposition."""
    out: dict[str, float] = {}
    for metric in _METRICS.registry.collect():
        if metric.name == "dynamo_hub_redirects":
            for s in metric.samples:
                if s.name.endswith("_total"):
                    out[s.labels.get("reason", "?")] = s.value
        elif metric.name == "dynamo_hub_backoff_seconds":
            for s in metric.samples:
                if s.name.endswith("_count"):
                    out["backoff_count"] = s.value
                elif s.name.endswith("_sum"):
                    out["backoff_sum_s"] = round(s.value, 4)
    return out


class _ConnLost(Exception):
    """Internal: the stream's connection died mid-iteration."""


class NotLeader(Exception):
    """A write landed on a replicated-hub follower (or bounced
    ``no_quorum``/``unavailable``). ``leader`` is the current leader's
    address when known, None mid-election. ``retry_after_s`` is the
    server-supplied backoff hint when the bounce carried one — honored
    by _call ahead of its own jittered exponential backoff. _call
    follows the redirect transparently; this only escapes to callers
    when the cluster stays leaderless past the reconnect window."""

    def __init__(
        self, leader: str | None, retry_after_s: float | None = None
    ):
        super().__init__(leader or "<no leader>")
        self.leader = leader
        self.retry_after_s = retry_after_s


class RemoteHub(Hub):
    """Hub client. ``address`` may be ONE ``host:port`` or a comma-
    separated list (a replicated hub, hub_replica.py): dials round-robin
    across the list, follows ``not_leader`` redirects for writes, and
    fails over streams to whichever replica answers."""

    # redirect-chase bound: a mid-election cluster (every replica bouncing
    # with a different — or no — leader hint, or two stale replicas naming
    # each other) must not spin a client through an unbounded hot loop;
    # after this many hops the call fails even inside the reconnect
    # window. Sized so the backoff sum (~15 s expected at the 0.5 s cap)
    # comfortably exceeds the default reconnect window AND a default
    # 3 s-lease election — the window is the failover SLA, the hop cap
    # only kills true redirect loops.
    MAX_REDIRECT_HOPS = 32

    def __init__(
        self,
        address: str,
        *,
        reconnect: bool = True,
        reconnect_window_s: float = 10.0,
    ):
        import uuid

        self._addrs = [a.strip() for a in address.split(",") if a.strip()]
        if not self._addrs:
            raise ValueError("empty hub address")
        self._addr_idx = 0
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._ids = itertools.count(1)
        # pending/stream entries are tagged with the connection EPOCH they
        # were sent on: a stale rx task (old connection, still blocked on
        # its reader while a reconnect already dialed a new one) must only
        # fail entries from its own generation — nuking newer-epoch
        # futures would spuriously retry calls on the healthy connection
        # (duplicating non-idempotent ops) and force needless stream
        # re-syncs (ADVICE r5 medium).
        self._epoch = 0
        self._pending: dict[int, tuple[int, asyncio.Future]] = {}
        self._streams: dict[int, tuple[int, asyncio.Queue]] = {}
        self._rx_task: asyncio.Task | None = None
        # client-unique publish ids let the hub drop the duplicate when
        # _call's at-least-once retry re-sends a publish whose ack was
        # lost in a crash (ADVICE r5 low: a dup under a fresh seq defeats
        # the subscribe-side seq dedup and double-counts router blocks)
        self._pub_ids = itertools.count(1)
        self._client_id = uuid.uuid4().hex[:12]
        self._write_lock = asyncio.Lock()
        self._conn_lock = asyncio.Lock()
        self._reconnect = reconnect
        self._reconnect_window_s = reconnect_window_s
        self._closed = False

    @classmethod
    async def connect(
        cls,
        address: str,
        timeout: float = 5.0,
        *,
        reconnect: bool = True,
        reconnect_window_s: float = 10.0,
    ) -> "RemoteHub":
        hub = cls(
            address,
            reconnect=reconnect,
            reconnect_window_s=reconnect_window_s,
        )
        await hub._connect(timeout)
        return hub

    @staticmethod
    def _split(addr: str) -> tuple[str, int]:
        host, _, port = addr.rpartition(":")
        return host or "127.0.0.1", int(port)

    async def _connect(self, timeout: float = 5.0) -> None:
        """Dial the preferred address, falling back round-robin through
        the rest; raises the last dial error when every replica fails."""
        last_err: Exception | None = None
        for i in range(len(self._addrs)):
            idx = (self._addr_idx + i) % len(self._addrs)
            host, port = self._split(self._addrs[idx])
            try:
                if FAULTS.enabled:
                    await FAULTS.fire("hub.dial")  # drop/error -> dial fails
                self._reader, self._writer = await asyncio.wait_for(
                    asyncio.open_connection(host, port), timeout
                )
            except (OSError, asyncio.TimeoutError) as e:
                last_err = e
                continue
            self._addr_idx = idx
            self._epoch += 1
            self._rx_task = asyncio.get_running_loop().create_task(
                self._rx_loop(self._reader, self._epoch)
            )
            return
        raise last_err if last_err is not None else OSError(
            "no hub addresses"
        )

    def _connected(self) -> bool:
        return (
            self._writer is not None
            and not self._writer.is_closing()
            and self._rx_task is not None
            and not self._rx_task.done()
        )

    async def _ensure_connected(self) -> None:
        """Re-dial with backoff for up to the reconnect window. Raises
        ConnectionError when closed, reconnect is disabled, or the window
        is exhausted."""
        if self._closed:
            raise ConnectionError("hub client closed")
        if self._connected():
            return
        if not self._reconnect:
            raise ConnectionError("hub not connected")
        # dynalint: disable=DL009 -- deliberate: _conn_lock's whole job is
        # to serialize re-dials — contenders MUST wait for the one
        # reconnect in flight (a parallel dial would mint a duplicate rx
        # loop), and the span is bounded by reconnect_window_s
        async with self._conn_lock:
            if self._closed:
                raise ConnectionError("hub client closed")
            if self._connected():
                return  # a neighbor reconnected while we waited
            if self._writer is not None:
                self._writer.close()
            deadline = time.monotonic() + self._reconnect_window_s
            delay = 0.05
            while True:
                try:
                    await self._connect(timeout=2.0)
                    return
                except (OSError, asyncio.TimeoutError):
                    if self._closed or time.monotonic() + delay >= deadline:
                        raise ConnectionError(
                            f"hub unreachable for {self._reconnect_window_s}s"
                        )
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, 1.0)

    async def _rx_loop(self, reader: asyncio.StreamReader, epoch: int) -> None:
        try:
            while True:
                msg = await framing.read_frame(reader)
                if msg is None:
                    break
                mid = msg.get("id")
                if "stream" in msg:
                    entry = self._streams.get(mid)
                    if entry is not None:
                        entry[1].put_nowait(msg["stream"])
                else:
                    _ep, fut = self._pending.pop(mid, (0, None))
                    if fut is not None and not fut.done():
                        fut.set_result(msg)
        except Exception as e:  # noqa: BLE001 — any rx failure = conn lost
            # the finally block below converts this into the reconnect
            # path; keep the *cause* visible for post-mortems (an
            # oversized-frame bug looks identical to a cut cable without
            # this line — dynalint DL003)
            log.debug("hub rx loop (epoch %d) died: %s: %s",
                      epoch, type(e).__name__, e)
        finally:
            # connection lost: fail in-flight calls (their callers retry
            # via _call's reconnect loop) and wake stream consumers (they
            # re-open). MUST run even on unexpected read errors (OSError
            # variants, oversized/corrupt frames) or callers await their
            # futures forever. EPOCH-SCOPED: a reconnect can replace this
            # task while it is still blocked on the dead reader (a send-
            # side broken pipe surfaces before the read side EOFs), so
            # only entries from THIS connection generation — which no rx
            # loop will ever answer — may be failed; newer-epoch entries
            # belong to the live connection and its own rx loop.
            err = ConnectionError("hub connection lost")
            for mid, (ep, fut) in list(self._pending.items()):
                if ep <= epoch:
                    del self._pending[mid]
                    if not fut.done():
                        fut.set_exception(err)
            for _mid, (ep, q) in list(self._streams.items()):
                if ep <= epoch:
                    q.put_nowait(None)  # sentinel: stream closed

    async def _send_request(self, op: str, kwargs: dict[str, Any]) -> Any:
        if FAULTS.enabled:
            # drop -> ConnectionError -> _call's reconnect/retry loop;
            # delay simulates a slow hub RPC; error surfaces to the caller
            await FAULTS.fire("hub.call")
        mid = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        try:
            # dynalint: disable=DL009 -- deliberate: per-connection frame
            # writes MUST serialize (interleaved write_frame calls corrupt
            # the framing); the await is bounded by socket backpressure,
            # and a dead peer surfaces as ConnectionError to every waiter
            async with self._write_lock:
                # snapshot writer+epoch together INSIDE the lock: a
                # reconnect can land while we awaited the lock, and the
                # entry must be tagged with the epoch of the connection
                # the frame actually goes out on — a stale tag would let
                # the dying rx loop fail a request in flight on the
                # healthy connection (spurious retry of a non-idempotent
                # op, the exact bug the epochs exist to prevent)
                writer, epoch = self._writer, self._epoch
                self._pending[mid] = (epoch, fut)
                await framing.write_frame(
                    writer, {"id": mid, "op": op, **kwargs}
                )
        except (OSError, ConnectionError):
            self._pending.pop(mid, None)
            raise ConnectionError("hub connection lost on send")
        msg = await fut
        if not msg.get("ok"):
            if msg.get("error") == "key_exists":
                raise KeyExists(msg.get("key"))
            if msg.get("error") == "not_leader":
                REDIRECTS.labels("not_leader").inc()
                raise NotLeader(msg.get("leader"))
            if msg.get("error") in ("no_quorum", "unavailable"):
                REDIRECTS.labels(msg["error"]).inc()
                # the leader logged the write but could not commit it to a
                # majority (mid-partition): retryable exactly like a
                # mid-election bounce — chase until the cluster converges.
                # AMBIGUOUS like any timeout: the record may still commit
                # once stragglers ack, so a retried non-idempotent create
                # can see KeyExists for its own write — the same
                # at-least-once exposure the reconnect path documents
                # (publish stays exactly-once via pub_id dedup). A
                # server-supplied retry_after hint (election/lease scale)
                # rides along and takes precedence over our own backoff.
                hint = msg.get("retry_after")
                raise NotLeader(
                    None,
                    retry_after_s=float(hint) if hint is not None else None,
                )
            raise RuntimeError(f"hub error for {op}: {msg.get('error')}")
        return msg.get("result")

    async def _redirect(self, leader: str | None) -> None:
        """Point the next dial at the leader (when hinted; otherwise the
        next replica in the ring — an election may still be running) and
        drop the current connection so _ensure_connected re-dials."""
        if leader:
            if leader not in self._addrs:
                self._addrs.append(leader)
            self._addr_idx = self._addrs.index(leader)
        else:
            self._addr_idx = (self._addr_idx + 1) % len(self._addrs)
        async with self._conn_lock:
            if self._writer is not None:
                self._writer.close()

    async def _call(self, op: str, **kwargs: Any) -> Any:
        deadline: float | None = None
        hops = 0
        while True:
            try:
                await self._ensure_connected()
                return await self._send_request(op, kwargs)
            except NotLeader as e:
                # a follower bounced a write: chase the leader, but
                # BOUNDED — max hops with jittered exponential backoff, so
                # a mid-election cluster (or two stale replicas naming
                # each other as leader) cannot spin us in a redirect loop
                if not self._reconnect or self._closed:
                    raise ConnectionError(
                        f"hub follower refused {op!r}: leader is "
                        f"{e.leader or 'unknown'}"
                    )
                hops += 1
                deadline = deadline or (
                    time.monotonic() + self._reconnect_window_s
                )
                if hops > self.MAX_REDIRECT_HOPS:
                    raise ConnectionError(
                        f"hub redirect loop: {op!r} bounced "
                        f"{hops} times without reaching a leader"
                    )
                if time.monotonic() >= deadline:
                    raise ConnectionError(
                        f"hub leaderless for {self._reconnect_window_s}s "
                        f"(op {op!r})"
                    )
                await self._redirect(e.leader)
                hint = e.retry_after_s
                if hint:
                    # server-supplied hint (no_quorum/unavailable
                    # bounces): the server KNOWS its election/lease
                    # timescale — honor it (lightly jittered so a
                    # thundering herd of bounced writers still spreads),
                    # bounded by the remaining failover window
                    backoff = min(
                        float(hint) * (0.9 + 0.2 * random.random()),
                        max(deadline - time.monotonic(), 0.0),
                    )
                else:
                    backoff = (
                        min(0.05 * (2 ** (hops - 1)), 0.5)
                        * (0.5 + random.random())
                    )
                BACKOFF.observe(backoff)
                await asyncio.sleep(backoff)
            except ConnectionError:
                if not self._reconnect or self._closed:
                    raise
                deadline = deadline or (
                    time.monotonic() + self._reconnect_window_s
                )
                if time.monotonic() >= deadline:
                    raise
                await asyncio.sleep(0.05)

    async def _open_stream(
        self, op: str, **kwargs: Any
    ) -> tuple[int, asyncio.Queue]:
        await self._ensure_connected()
        mid = next(self._ids)
        q: asyncio.Queue = asyncio.Queue()
        try:
            # dynalint: disable=DL009 -- deliberate: same frame-write
            # serialization contract as _send_request (interleaved frames
            # corrupt the protocol; bounded by socket backpressure)
            async with self._write_lock:
                # same epoch-at-send discipline as _send_request
                writer, epoch = self._writer, self._epoch
                self._streams[mid] = (epoch, q)
                await framing.write_frame(
                    writer, {"id": mid, "op": op, **kwargs}
                )
        except (OSError, ConnectionError):
            self._streams.pop(mid, None)
            raise ConnectionError("hub connection lost on stream open")
        return mid, q

    async def _close_stream(self, mid: int) -> None:
        self._streams.pop(mid, None)
        if self._connected() and not self._closed:
            try:
                # dynalint: disable=DL009 -- deliberate: frame-write
                # serialization (see _send_request); cancel frames ride
                # the same connection as the calls they cancel
                async with self._write_lock:
                    await framing.write_frame(
                        self._writer, {"id": next(self._ids), "op": "cancel", "target": mid}
                    )
            except (ConnectionError, OSError, RuntimeError):
                pass

    # -- kv ---------------------------------------------------------------

    async def put(self, key: str, value: Any, lease_id: int | None = None) -> None:
        await self._call("put", key=key, value=value, lease=lease_id)

    async def create(self, key: str, value: Any, lease_id: int | None = None) -> None:
        await self._call("create", key=key, value=value, lease=lease_id)

    async def get(self, key: str) -> Any:
        return await self._call("get", key=key)

    async def delete(self, key: str) -> bool:
        return await self._call("delete", key=key)

    async def get_prefix(self, prefix: str) -> dict[str, Any]:
        return await self._call("get_prefix", prefix=prefix)

    def _stream_retry_gate(self, deadline: float | None) -> float:
        """Shared stream-reconnect policy: raise when reconnect is off,
        the client is closed, or the failure deadline passed; otherwise
        return the deadline (setting it on first failure). Streams must
        NOT retry unboundedly — a permanently dead hub has to surface as
        ConnectionError so consumers hit their etcd-loss => shutdown
        path."""
        if not self._reconnect or self._closed:
            raise ConnectionError("hub connection lost")
        deadline = deadline or time.monotonic() + self._reconnect_window_s
        if time.monotonic() >= deadline:
            raise ConnectionError(
                f"hub unreachable for {self._reconnect_window_s}s"
            )
        return deadline

    async def watch_prefix(
        self, prefix: str, *, initial: bool = True, sync_marker: bool = False
    ) -> AsyncIterator[WatchEvent]:
        known: set[str] = set()
        first = True
        fail_deadline: float | None = None
        while True:
            # first open: plain watch, events stream through untouched (no
            # marker — also keeps legacy servers working). Re-opens after a
            # connection loss request the sync marker so the fresh snapshot
            # can be diffed against ``known`` for missed deletes.
            resync = not first
            try:
                mid, q = await self._open_stream(
                    "watch", prefix=prefix,
                    initial=initial if first else True, sync=resync,
                )
            except ConnectionError:
                fail_deadline = self._stream_retry_gate(fail_deadline)
                await asyncio.sleep(0.05)
                continue
            fail_deadline = None
            try:
                if resync:
                    # collect the snapshot up to the server's sync marker,
                    # then reconcile: keys we know that are GONE from the
                    # fresh snapshot were deleted while we were away
                    snap: list[WatchEvent] = []
                    while True:
                        item = await q.get()
                        if item is None:
                            raise _ConnLost
                        if item["kind"] == "sync":
                            break
                        snap.append(
                            WatchEvent(
                                item["kind"], item["key"], item.get("value")
                            )
                        )
                    snap_keys = {ev.key for ev in snap if ev.kind == "put"}
                    for key in sorted(known - snap_keys):
                        known.discard(key)
                        yield WatchEvent("delete", key)
                    # snapshot puts re-yield even already-known keys:
                    # puts are idempotent upserts for every consumer, and
                    # the value may have changed while disconnected
                    for ev in snap:
                        if ev.kind == "put":
                            known.add(ev.key)
                        yield ev
                first = False
                while True:
                    item = await q.get()
                    if item is None:
                        raise _ConnLost
                    if item["kind"] == "sync":
                        continue
                    ev = WatchEvent(item["kind"], item["key"], item.get("value"))
                    if ev.kind == "put":
                        known.add(ev.key)
                    elif ev.kind == "delete":
                        known.discard(ev.key)
                    yield ev
            except _ConnLost:
                self._streams.pop(mid, None)
                fail_deadline = self._stream_retry_gate(fail_deadline)
                first = False
                continue
            finally:
                await self._close_stream(mid)

    # -- leases ------------------------------------------------------------

    async def grant_lease(self, ttl_s: float) -> int:
        return await self._call("grant_lease", ttl=ttl_s)

    async def get_boot_id(self) -> str | None:
        try:
            return await self._call("boot_id")
        except RuntimeError as e:
            # ONLY the legacy-server case maps to "unknown": transient
            # RPC failures must propagate, or a blip would silently store
            # boot=None and disable hub-reboot detection downstream
            if "unknown op" in str(e):
                return None
            raise

    async def keepalive(self, lease_id: int) -> bool:
        return await self._call("keepalive", lease=lease_id)

    async def revoke_lease(self, lease_id: int) -> None:
        await self._call("revoke_lease", lease=lease_id)

    # -- pub/sub -----------------------------------------------------------

    async def publish(
        self, subject: str, payload: Any, pub_id: str | None = None
    ) -> bool:
        # idempotency id: _call's reconnect loop may re-send after a lost
        # ack; the hub dedups on pub_id so the retry cannot mint a second
        # event under a fresh seq (hub.py publish; legacy servers ignore
        # the extra field and keep plain at-least-once semantics)
        res = await self._call(
            "publish", subject=subject, payload=payload,
            pub_id=pub_id or f"{self._client_id}:{next(self._pub_ids)}",
        )
        # legacy servers ack with a bare True; new ones relay the hub's
        # applied/deduplicated bool so the contract matches local hubs
        return True if res is None else bool(res)

    async def purge_subject(
        self, subject: str, keep_last: int = 0,
        up_to_seq: int | None = None,
    ) -> int:
        return await self._call(
            "purge_subject", subject=subject, keep_last=keep_last,
            up_to_seq=up_to_seq,
        )

    async def subscribe(
        self, subject: str, *, replay: bool = False, with_seq: bool = False
    ) -> AsyncIterator[tuple]:
        last_seq: dict[str, int] = {}
        boot: str | None = None
        first = True
        fail_deadline: float | None = None
        while True:
            # re-subscribe with replay only if the caller wanted replay:
            # a live-only subscription stays live-only across reconnects
            # (missed events are lost — NATS-core semantics); a replay
            # subscription dedups by per-subject seq, which the durable
            # hub preserves across restarts. Seq baselines are only valid
            # within one hub boot: a NON-durable hub restart resets seq
            # counters, so a changed boot_id clears the dedup map instead
            # of silently discarding fresh low-seq events.
            try:
                if replay:
                    new_boot = await self.get_boot_id()
                    if not first and new_boot != boot:
                        last_seq.clear()
                    boot = new_boot
                mid, q = await self._open_stream(
                    "subscribe", subject=subject, replay=replay
                )
            except ConnectionError:
                fail_deadline = self._stream_retry_gate(fail_deadline)
                await asyncio.sleep(0.05)
                continue
            fail_deadline = None
            try:
                while True:
                    item = await q.get()
                    if item is None:
                        raise _ConnLost
                    subj, seq = item["subject"], item.get("seq", 0)
                    if replay and not first and seq and seq <= last_seq.get(subj, 0):
                        continue  # already delivered before the reconnect
                    if seq:
                        last_seq[subj] = max(last_seq.get(subj, 0), seq)
                    if with_seq:
                        yield subj, item["payload"], seq
                    else:
                        yield subj, item["payload"]
            except _ConnLost:
                self._streams.pop(mid, None)
                fail_deadline = self._stream_retry_gate(fail_deadline)
                first = False
                continue
            finally:
                await self._close_stream(mid)

    # -- object store ------------------------------------------------------

    async def put_object(self, bucket: str, name: str, data: bytes) -> None:
        await self._call("put_object", bucket=bucket, name=name, data=bytes(data))

    async def get_object(self, bucket: str, name: str) -> bytes | None:
        return await self._call("get_object", bucket=bucket, name=name)

    async def delete_object(self, bucket: str, name: str) -> None:
        await self._call("delete_object", bucket=bucket, name=name)

    async def close(self) -> None:
        self._closed = True
        if self._rx_task is not None:
            self._rx_task.cancel()
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass


async def connect_hub(address: str | None) -> Hub:
    """Connect to a remote hub, or fall back to a process-local one.

    ``address`` may be a comma-separated multi-address list (a replicated
    hub deployment, hub_replica.py) — every connect site gets round-robin
    failover across the whole list, not just the first entry. Env
    layering (``DYN_HUB_ADDRESSES`` / ``DYN_HUB_ADDRESS``) lives in
    RuntimeConfig.hub_target(), the single source of truth — callers pass
    its result; an empty address always means in-memory."""
    from dynamo_tpu.runtime.hub import InMemoryHub

    address = (address or "").strip()
    if address:
        return await RemoteHub.connect(address)
    return InMemoryHub()
