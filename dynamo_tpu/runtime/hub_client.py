"""TCP client for the hub service - same interface as InMemoryHub.

One connection per client, request/response multiplexed by message id;
watch/subscribe streams fan out to per-stream queues. Reconnection is the
caller's concern (workers treat hub loss as fatal after retries, mirroring
the reference's etcd-loss => shutdown behavior, lib/runtime/src/lib.rs).
"""

from __future__ import annotations

import asyncio
import itertools
from typing import Any, AsyncIterator

from dynamo_tpu.runtime import framing
from dynamo_tpu.runtime.hub import Hub, KeyExists, WatchEvent


class RemoteHub(Hub):
    def __init__(self, address: str):
        host, _, port = address.rpartition(":")
        self._host, self._port = host or "127.0.0.1", int(port)
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._streams: dict[int, asyncio.Queue] = {}
        self._rx_task: asyncio.Task | None = None
        self._write_lock = asyncio.Lock()
        self._closed = False

    @classmethod
    async def connect(cls, address: str, timeout: float = 5.0) -> "RemoteHub":
        hub = cls(address)
        await hub._connect(timeout)
        return hub

    async def _connect(self, timeout: float = 5.0) -> None:
        self._reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(self._host, self._port), timeout
        )
        self._rx_task = asyncio.get_running_loop().create_task(self._rx_loop())

    async def _rx_loop(self) -> None:
        assert self._reader is not None
        while True:
            msg = await framing.read_frame(self._reader)
            if msg is None:
                break
            mid = msg.get("id")
            if "stream" in msg:
                q = self._streams.get(mid)
                if q is not None:
                    q.put_nowait(msg["stream"])
            else:
                fut = self._pending.pop(mid, None)
                if fut is not None and not fut.done():
                    fut.set_result(msg)
        # connection lost: fail everything
        err = ConnectionError("hub connection lost")
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(err)
        self._pending.clear()
        for q in self._streams.values():
            q.put_nowait(None)  # sentinel: stream closed

    async def _call(self, op: str, **kwargs: Any) -> Any:
        if self._writer is None:
            raise ConnectionError("hub not connected")
        mid = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[mid] = fut
        async with self._write_lock:
            await framing.write_frame(self._writer, {"id": mid, "op": op, **kwargs})
        msg = await fut
        if not msg.get("ok"):
            if msg.get("error") == "key_exists":
                raise KeyExists(msg.get("key"))
            raise RuntimeError(f"hub error for {op}: {msg.get('error')}")
        return msg.get("result")

    async def _open_stream(self, op: str, **kwargs: Any) -> tuple[int, asyncio.Queue]:
        if self._writer is None:
            raise ConnectionError("hub not connected")
        mid = next(self._ids)
        q: asyncio.Queue = asyncio.Queue()
        self._streams[mid] = q
        async with self._write_lock:
            await framing.write_frame(self._writer, {"id": mid, "op": op, **kwargs})
        return mid, q

    async def _close_stream(self, mid: int) -> None:
        self._streams.pop(mid, None)
        if self._writer is not None and not self._closed:
            try:
                async with self._write_lock:
                    await framing.write_frame(
                        self._writer, {"id": next(self._ids), "op": "cancel", "target": mid}
                    )
            except (ConnectionError, RuntimeError):
                pass

    # -- kv ---------------------------------------------------------------

    async def put(self, key: str, value: Any, lease_id: int | None = None) -> None:
        await self._call("put", key=key, value=value, lease=lease_id)

    async def create(self, key: str, value: Any, lease_id: int | None = None) -> None:
        await self._call("create", key=key, value=value, lease=lease_id)

    async def get(self, key: str) -> Any:
        return await self._call("get", key=key)

    async def delete(self, key: str) -> bool:
        return await self._call("delete", key=key)

    async def get_prefix(self, prefix: str) -> dict[str, Any]:
        return await self._call("get_prefix", prefix=prefix)

    async def watch_prefix(
        self, prefix: str, *, initial: bool = True
    ) -> AsyncIterator[WatchEvent]:
        mid, q = await self._open_stream("watch", prefix=prefix, initial=initial)
        try:
            while True:
                item = await q.get()
                if item is None:
                    raise ConnectionError("hub connection lost during watch")
                yield WatchEvent(item["kind"], item["key"], item.get("value"))
        finally:
            await self._close_stream(mid)

    # -- leases ------------------------------------------------------------

    async def grant_lease(self, ttl_s: float) -> int:
        return await self._call("grant_lease", ttl=ttl_s)

    async def get_boot_id(self) -> str | None:
        try:
            return await self._call("boot_id")
        except RuntimeError as e:
            # ONLY the legacy-server case maps to "unknown": transient
            # RPC failures must propagate, or a blip would silently store
            # boot=None and disable hub-reboot detection downstream
            if "unknown op" in str(e):
                return None
            raise

    async def keepalive(self, lease_id: int) -> bool:
        return await self._call("keepalive", lease=lease_id)

    async def revoke_lease(self, lease_id: int) -> None:
        await self._call("revoke_lease", lease=lease_id)

    # -- pub/sub -----------------------------------------------------------

    async def publish(self, subject: str, payload: Any) -> None:
        await self._call("publish", subject=subject, payload=payload)

    async def purge_subject(
        self, subject: str, keep_last: int = 0,
        up_to_seq: int | None = None,
    ) -> int:
        return await self._call(
            "purge_subject", subject=subject, keep_last=keep_last,
            up_to_seq=up_to_seq,
        )

    async def subscribe(
        self, subject: str, *, replay: bool = False, with_seq: bool = False
    ) -> AsyncIterator[tuple]:
        mid, q = await self._open_stream("subscribe", subject=subject, replay=replay)
        try:
            while True:
                item = await q.get()
                if item is None:
                    raise ConnectionError("hub connection lost during subscribe")
                if with_seq:
                    yield item["subject"], item["payload"], item.get("seq", 0)
                else:
                    yield item["subject"], item["payload"]
        finally:
            await self._close_stream(mid)

    # -- object store ------------------------------------------------------

    async def put_object(self, bucket: str, name: str, data: bytes) -> None:
        await self._call("put_object", bucket=bucket, name=name, data=bytes(data))

    async def get_object(self, bucket: str, name: str) -> bytes | None:
        return await self._call("get_object", bucket=bucket, name=name)

    async def delete_object(self, bucket: str, name: str) -> None:
        await self._call("delete_object", bucket=bucket, name=name)

    async def close(self) -> None:
        self._closed = True
        if self._rx_task is not None:
            self._rx_task.cancel()
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass


async def connect_hub(address: str | None) -> Hub:
    """Connect to a remote hub, or fall back to a process-local one."""
    from dynamo_tpu.runtime.hub import InMemoryHub

    if address:
        return await RemoteHub.connect(address)
    return InMemoryHub()
