"""Hierarchical metrics registry (ref lib/runtime/src/metrics.rs).

Thin layer over prometheus_client: one CollectorRegistry per
DistributedRuntime, metric names auto-prefixed ``dynamo_`` with
namespace/component/endpoint labels, exposition as Prometheus text via the
frontend's /metrics route and the system status server.
"""

from __future__ import annotations

from typing import Callable, Iterable

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

PREFIX = "dynamo_"

# Process-global exposition providers: named callables returning Prometheus
# text appended to EVERY MetricsRegistry exposition. Process-wide subsystems
# that don't hang off one registry (fault-injection trip counters,
# migration recovery counters) register here once and show up on every
# /metrics surface — frontend, system status server, EPP.
_GLOBAL_PROVIDERS: dict[str, Callable[[], str]] = {}


def register_global_provider(name: str, fn: Callable[[], str]) -> None:
    _GLOBAL_PROVIDERS[name] = fn


def register_registry(name: str, registry: "MetricsRegistry") -> None:
    """Expose a module-level MetricsRegistry on every /metrics surface.
    Renders the underlying collector registry directly — going through
    ``registry.exposition()`` would recurse into the global providers."""
    register_global_provider(
        name, lambda: generate_latest(registry.registry).decode()
    )

# Buckets tuned for LLM serving latencies (seconds).
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


class MetricsRegistry:
    def __init__(self, labels: dict[str, str] | None = None):
        self.registry = CollectorRegistry()
        self.const_labels = labels or {}
        self._metrics: dict[str, object] = {}

    def _full(self, name: str) -> str:
        return name if name.startswith(PREFIX) else PREFIX + name

    def counter(self, name: str, doc: str, labelnames: Iterable[str] = ()) -> Counter:
        key = "c:" + name
        if key not in self._metrics:
            self._metrics[key] = Counter(
                self._full(name), doc, list(labelnames), registry=self.registry
            )
        return self._metrics[key]  # type: ignore[return-value]

    def gauge(self, name: str, doc: str, labelnames: Iterable[str] = ()) -> Gauge:
        key = "g:" + name
        if key not in self._metrics:
            self._metrics[key] = Gauge(
                self._full(name), doc, list(labelnames), registry=self.registry
            )
        return self._metrics[key]  # type: ignore[return-value]

    def histogram(
        self,
        name: str,
        doc: str,
        labelnames: Iterable[str] = (),
        buckets: tuple = LATENCY_BUCKETS,
    ) -> Histogram:
        key = "h:" + name
        if key not in self._metrics:
            self._metrics[key] = Histogram(
                self._full(name), doc, list(labelnames),
                buckets=buckets, registry=self.registry,
            )
        return self._metrics[key]  # type: ignore[return-value]

    def exposition(self) -> bytes:
        out = generate_latest(self.registry)
        for fn in _GLOBAL_PROVIDERS.values():
            try:
                extra = fn()
            # dynalint: disable=DL003 -- /metrics must never 500 because
            # one provider is broken; the other providers still render
            except Exception:  # noqa: BLE001 - never break /metrics
                continue
            if extra:
                out += extra.encode()
        return out
