"""Generic operator graph: named, composable AsyncEngine operators.

Role of the reference's pipeline layer (lib/runtime/src/pipeline/
nodes.rs ServiceFrontend -> operators -> ServiceBackend, registry.rs):
every stage of a serving chain implements the same AsyncEngine surface
(``generate(request, context) -> async iterator``), so chains are DATA —
an ordered list of operator names + kwargs — rather than hand-wired
constructor nests. The frontend's model pipelines build through this
registry (frontend/watcher.py), and deployments can splice custom
operators (request rewriting, shadowing, rate limiting, ...) without
touching the wiring code.

Operators register lazily by import path, so registering the builtin
table costs nothing until a chain is built and custom operators can
live anywhere.
"""

from __future__ import annotations

import importlib
import logging
from typing import Any, Callable

log = logging.getLogger("dynamo.pipeline")

__all__ = ["OperatorRegistry", "registry", "build_chain"]


class OperatorRegistry:
    """name -> factory(sink_engine, **kwargs) -> engine."""

    def __init__(self) -> None:
        self._factories: dict[str, Callable] = {}
        self._lazy: dict[str, tuple[str, str]] = {}

    def register(self, name: str, factory: Callable) -> None:
        self._factories[name] = factory

    def register_lazy(self, name: str, module: str, attr: str) -> None:
        """Register by import path; resolved on first build."""
        self._lazy[name] = (module, attr)

    def names(self) -> list[str]:
        return sorted(set(self._factories) | set(self._lazy))

    def _resolve(self, name: str) -> Callable:
        if name in self._factories:
            return self._factories[name]
        if name in self._lazy:
            module, attr = self._lazy[name]
            factory = getattr(importlib.import_module(module), attr)
            self._factories[name] = factory
            return factory
        raise KeyError(
            f"unknown pipeline operator {name!r}; registered: {self.names()}"
        )

    def build(self, name: str, sink: Any, /, **kwargs: Any) -> Any:
        # positional-only: operator kwargs may legitimately be called
        # "name" or "sink"
        return self._resolve(name)(sink, **kwargs)


registry = OperatorRegistry()

# builtin operator table (the reference's registry.rs equivalent).
# Factories take (sink, **kwargs) and return an AsyncEngine-shaped object.
registry.register_lazy(
    "migration", "dynamo_tpu.frontend.migration", "make_operator"
)
registry.register_lazy(
    "backend", "dynamo_tpu.frontend.backend_op", "make_operator"
)
registry.register_lazy(
    "mm_encode", "dynamo_tpu.multimodal.operator", "make_operator"
)


def build_chain(ops: list, sink: Any, *, reg: OperatorRegistry | None = None):
    """Compose operators onto ``sink``, OUTERMOST FIRST.

    ``ops`` entries are ``"name"`` or ``("name", {kwargs})``:
    ``build_chain(["backend", "migration"], router)`` produces
    backend(migration(router)) — requests flow left-to-right, responses
    right-to-left, exactly the forward/backward edges of nodes.rs.
    """
    reg = reg or registry
    engine = sink
    normalized = []
    for op in ops:
        if isinstance(op, str):
            normalized.append((op, {}))
        elif isinstance(op, (list, tuple)) and len(op) == 2 and isinstance(
            op[0], str
        ) and isinstance(op[1], dict):
            normalized.append((op[0], dict(op[1])))
        else:
            raise ValueError(
                f"bad pipeline operator entry {op!r}: expected \"name\" "
                "or [name, kwargs]"
            )
    for name, kwargs in reversed(normalized):
        engine = reg.build(name, engine, **kwargs)
    return engine
