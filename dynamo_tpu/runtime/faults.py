"""Deterministic fault-injection layer: named fault points, seeded schedules.

The recovery machinery in this framework (frontend/migration.py re-drives,
runtime/health.py withdrawal, transport drain, hub client failover) only gets
exercised when something actually fails. This module makes failure a
first-class, *reproducible* input: code under test declares named fault
points (``fire("transport.send")``), and a process-wide registry decides —
from a seeded per-site RNG — whether that call drops, delays, or errors.
Ref: the reference's fault-tolerance test tier provokes failures with real
SIGKILLs (tests/fault_tolerance/); this layer covers the partial-failure
space kill -9 can't reach (slow fsync, lossy links, flaky admission).

Spec grammar (``DYN_FAULTS`` env var, or the worker admin ``faults`` RPC)::

    site:action[=param][@prob][xN][~instance][,site:action...]

    transport.send:drop@0.02          2% of sends die like a cut connection
    hub.fsync:delay=50ms              every WAL fsync takes +50ms
    engine.step:error@0.001           1-in-1000 steps raises (recovery path)
    disagg.pull:error@1x1             the first KV pull fails, then clean
    disagg.pull:corrupt=3x1           flip 3 bits in the first pulled KV
                                      payload (checksum detection path)
    engine.step:delay=80ms~10.0.0.3:*   sticky per-instance degradation:
                                      only the worker whose fault identity
                                      matches the fnmatch pattern slows
                                      down (the gray-failure straggler)
    transport.partition:drop=A|B      bidirectional partition between the
                                      address pair A and B
    transport.partition:drop=A>B      one-way partition: traffic A -> B is
                                      cut (B never hears A; A still hears B)

Actions:
    drop     raise ``FaultDrop`` (a ConnectionResetError): the site behaves
             exactly as if the peer vanished — existing except-clauses and
             migration/retry paths handle it with zero special-casing.
    delay    sleep ``param`` (``50ms``/``0.2s``/bare seconds) at the site.
    error    raise ``FaultInjected`` (a RuntimeError): an internal failure.
    corrupt  flip ``param`` bits (default 1, positive integer) at seeded
             positions in the payload a ``corrupt_bytes()`` call site
             hands over — silent data corruption on the wire/tier, which
             ONLY the receiver's content checksum can catch
             (runtime/integrity.py). Never raises at the site.

Instance scoping (``~pattern``): a rule suffixed with ``~pattern`` only
fires for call sites whose fault identity matches the fnmatch pattern.
Workers set their identity once via ``FAULTS.set_instance(addr)`` (or the
``DYN_FAULT_INSTANCE`` env var); multi-worker processes (the cluster sim)
pass ``instance=`` per call instead. A scoped rule is STICKY: the same
worker degrades on every matching fire, which is the gray-failure
straggler shape — one slow replica in an otherwise healthy fleet.
Unscoped rules fire for everyone, scoped rules never fire for callers
with no identity.

Partitions are address-pair scoped: the ``transport.partition`` site takes
a ``drop`` action whose param names the pair (``A|B`` symmetric, ``A>B``
one-way src->dst; either side may be a ``*`` fnmatch pattern). Code that
speaks peer-to-peer (the hub replication plane, hub_replica.py) consults
``link_blocked(site, src, dst)`` / ``fire_link(site, src, dst)`` with its
own advertise address and the peer's — a cut link refuses dials, kills
established streams at the next frame, and drops follower acks, which is
exactly the partial-failure surface a Raft-style election has to survive.
Live-flippable like every other rule: ``configure()`` (the worker admin
``faults`` RPC) swaps the partition set atomically.

Determinism: every site draws its own decision stream from
``random.Random(f"{seed}:{site}")`` — the schedule at one site is a pure
function of (spec, seed, call index at that site), independent of thread
interleavings or what other sites are doing. The same spec + seed replays
the same fault schedule; tests assert this (tests/test_faults.py).

Registered fault points (see tools/dynalint/catalog.py for the full,
drift-checked catalog):
    transport.connect / transport.send / transport.recv   (transport.py)
    hub.dial / hub.call                                   (hub_client.py)
    hub.wal_append / hub.fsync                            (hub_store.py)
    engine.step / engine.admit / engine.spec_verify       (engine/core.py)
    engine.guided_compile                                 (guided/runtime.py)
    disagg.pull                                           (disagg/transfer.py)
    kvbm.onboard                                          (kvbm/manager.py)
    migration.resume                                      (frontend/migration.py)
    health.canary                                         (runtime/health.py)

Trip counters are exported on every ``/metrics`` surface as
``dynamo_fault_trips_total{site,action}`` (runtime/metrics.py global
exposition providers), so a chaos run can assert its faults actually fired.
"""

from __future__ import annotations

import asyncio
import fnmatch
import logging
import os
import random
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any

log = logging.getLogger("dynamo.faults")


# Machine-readable site catalog (mirrored by tools/dynalint/catalog.py,
# cross-checked by tests/test_static_analysis.py): every fire()/fire_sync()
# call site in the tree must use one of these strings, and configure()
# warns when a DYN_FAULTS spec names a site no code declares — both
# directions of the drift that silently kills chaos-schedule replay.
KNOWN_SITES: frozenset[str] = frozenset({
    "transport.connect",
    "transport.send",
    "transport.recv",
    "transport.partition",
    "hub.dial",
    "hub.call",
    "hub.wal_append",
    "hub.fsync",
    "hub.snap_fsync",
    "engine.step",
    "engine.admit",
    "engine.compile",
    "engine.spec_verify",
    "engine.guided_compile",
    "engine.quant",
    "engine.preempt",
    "epp.breaker",
    "disagg.pull",
    "kvbm.onboard",
    "migration.resume",
    "health.canary",
})


class FaultInjected(RuntimeError):
    """An injected ``error`` action fired at a fault point."""


class FaultDrop(ConnectionResetError):
    """An injected ``drop`` action fired: behave like the peer vanished."""


_DURATION = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m)?$")


def _parse_duration(text: str) -> float:
    m = _DURATION.match(text.strip())
    if m is None:
        raise ValueError(f"bad duration {text!r} (want e.g. 50ms, 0.2s)")
    val = float(m.group(1))
    unit = m.group(2) or "s"
    return val * {"ms": 1e-3, "s": 1.0, "m": 60.0}[unit]


@dataclass
class FaultRule:
    site: str
    action: str  # drop | delay | error | corrupt
    prob: float = 1.0
    delay_s: float = 0.0
    limit: int = 0  # max trips; 0 = unbounded
    trips: int = 0
    # corrupt rules: bits to flip per trip (seeded positions)
    flips: int = 1
    # instance scoping: fnmatch pattern over the caller's fault identity;
    # "" = unscoped (fires for everyone)
    instance: str = ""
    # partition rules only (site transport.partition): the address pair.
    # ``one_way`` cuts src->dst traffic only; symmetric cuts both ways.
    src: str | None = None
    dst: str | None = None
    one_way: bool = False

    def is_partition(self) -> bool:
        return self.dst is not None

    def instance_matches(self, instance: str) -> bool:
        if not self.instance:
            return True
        return bool(instance) and fnmatch.fnmatchcase(instance, self.instance)

    def link_matches(self, src: str, dst: str) -> bool:
        if self.one_way:
            return (
                fnmatch.fnmatchcase(src, self.src)
                and fnmatch.fnmatchcase(dst, self.dst)
            )
        return (
            fnmatch.fnmatchcase(src, self.src)
            and fnmatch.fnmatchcase(dst, self.dst)
        ) or (
            fnmatch.fnmatchcase(src, self.dst)
            and fnmatch.fnmatchcase(dst, self.src)
        )

    def spec(self) -> str:
        out = f"{self.site}:{self.action}"
        if self.is_partition():
            out += f"={self.src}{'>' if self.one_way else '|'}{self.dst}"
        elif self.action == "delay":
            out += f"={self.delay_s * 1000:g}ms"
        elif self.action == "corrupt" and self.flips != 1:
            out += f"={self.flips}"
        if self.prob != 1.0:
            out += f"@{self.prob:g}"
        if self.limit:
            out += f"x{self.limit}"
        if self.instance:
            out += f"~{self.instance}"
        return out


def parse_spec(spec: str) -> list[FaultRule]:
    """Parse a ``DYN_FAULTS`` spec string into rules (see module doc)."""
    rules: list[FaultRule] = []
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        site, _, rest = entry.partition(":")
        if not rest:
            raise ValueError(f"fault entry {entry!r}: want site:action")
        instance = ""
        if "~" in rest:
            rest, _, instance = rest.rpartition("~")
            instance = instance.strip()
            if not instance:
                raise ValueError(
                    f"fault entry {entry!r}: ~ needs an instance pattern"
                )
        limit = 0
        m = re.search(r"x(\d+)$", rest)
        if m:
            limit = int(m.group(1))
            rest = rest[: m.start()]
        prob = 1.0
        if "@" in rest:
            rest, _, p = rest.rpartition("@")
            prob = float(p)
        action, _, param = rest.partition("=")
        action = action.strip()
        if action not in ("drop", "delay", "error", "corrupt"):
            raise ValueError(f"fault entry {entry!r}: unknown action {action!r}")
        site = site.strip()
        if site == "transport.partition":
            if action != "drop" or not param:
                raise ValueError(
                    f"fault entry {entry!r}: partition wants "
                    "transport.partition:drop=A|B (or A>B one-way)"
                )
            if instance:
                # partitions are already address-pair scoped; a ~instance
                # suffix on top is contradictory, not composable
                raise ValueError(
                    f"fault entry {entry!r}: partitions are address-pair "
                    "scoped; ~instance is not valid on them"
                )
            if limit:
                # a partition is link STATE probed by traffic, not a
                # countable event: xN would silently heal after N probes
                # (including idle polls), which is never what a chaos
                # schedule means — flip the spec off to heal instead
                raise ValueError(
                    f"fault entry {entry!r}: xN limits are not valid on "
                    "partitions (clear/replace the spec to heal)"
                )
            one_way = ">" in param
            src, _, dst = param.partition(">" if one_way else "|")
            if not src.strip() or not dst.strip():
                raise ValueError(
                    f"fault entry {entry!r}: partition needs both addresses"
                )
            rules.append(FaultRule(
                site=site, action=action, prob=prob,
                src=src.strip(), dst=dst.strip(), one_way=one_way,
            ))
            continue
        flips = 1
        delay_s = 0.0
        if action == "corrupt":
            # typed param validation: the only meaningful corrupt param is
            # a positive bit-flip count — "50ms", "0", "-2" or random text
            # would silently mean "1 flip" and make the schedule lie
            if param:
                try:
                    flips = int(param)
                except ValueError:
                    raise ValueError(
                        f"fault entry {entry!r}: corrupt wants a positive "
                        f"integer bit-flip count, not {param!r}"
                    ) from None
                if flips <= 0:
                    raise ValueError(
                        f"fault entry {entry!r}: corrupt bit-flip count "
                        "must be >= 1"
                    )
        else:
            delay_s = _parse_duration(param) if param else 0.0
            if action == "delay" and not delay_s:
                raise ValueError(
                    f"fault entry {entry!r}: delay needs =duration"
                )
        rules.append(FaultRule(
            site=site, action=action, prob=prob,
            delay_s=delay_s, limit=limit, flips=flips, instance=instance,
        ))
    return rules


class FaultRegistry:
    """Process-wide fault-point registry.

    ``enabled`` is the hot-path gate: with no rules configured every
    ``fire``/``fire_sync`` call is one attribute read and a return —
    production overhead is negligible.
    """

    def __init__(self, spec: str = "", seed: int = 0, instance: str = ""):
        self._lock = threading.Lock()
        self.enabled = False
        self.seed = seed
        # process-default fault identity for ~instance-scoped rules;
        # per-call instance= overrides it (multi-worker sim processes)
        self.instance = instance
        self._rules: dict[str, list[FaultRule]] = {}
        self._rngs: dict[str, random.Random] = {}
        self.trip_counts: dict[tuple[str, str], int] = {}
        if spec:
            self.configure(spec, seed)

    def set_instance(self, instance: str) -> None:
        """Declare this process's fault identity (worker advertise
        address) so ``~instance``-scoped rules can target it."""
        self.instance = instance or ""

    # -- configuration -----------------------------------------------------

    def configure(self, spec: str, seed: int | None = None) -> None:
        """Replace the active rule set (live reconfig: the admin ``faults``
        RPC lands here). Resets per-site RNGs so the new schedule is
        deterministic from the configure point."""
        rules = parse_spec(spec)
        with self._lock:
            if seed is not None:
                self.seed = seed
            self._rules = {}
            for r in rules:
                self._rules.setdefault(r.site, []).append(r)
            self._rngs = {}
            self.enabled = bool(self._rules)
        if rules:
            unknown = {r.site for r in rules} - KNOWN_SITES
            if unknown:
                # warn, don't raise: an old schedule replayed against a
                # newer build should degrade loudly, not crash the worker
                log.warning(
                    "fault spec names unknown site(s) %s — these will "
                    "NEVER trip (known: %s)",
                    ",".join(sorted(unknown)), ",".join(sorted(KNOWN_SITES)),
                )
            log.warning(
                "fault injection ACTIVE (seed=%d): %s",
                self.seed, ",".join(r.spec() for r in rules),
            )
        else:
            log.info("fault injection cleared")

    def clear(self) -> None:
        self.configure("")

    # -- decision ----------------------------------------------------------

    def _site_rng(self, site: str) -> random.Random:
        rng = self._rngs.get(site)
        if rng is None:
            rng = self._rngs[site] = random.Random(f"{self.seed}:{site}")
        return rng

    def decide(
        self,
        site: str,
        instance: str | None = None,
        kinds: tuple[str, ...] | None = None,
    ) -> FaultRule | None:
        """One decision draw at ``site``; returns the rule to apply (and
        counts the trip) or None. Deterministic per (spec, seed, site,
        call index). ``instance`` is the caller's fault identity for
        ``~``-scoped rules (defaults to the process identity); ``kinds``
        restricts which actions this call site can apply (payload sites
        draw corrupt rules via ``corrupt_bytes``, never ``fire``)."""
        if not self.enabled:
            return None
        who = self.instance if instance is None else instance
        with self._lock:
            rules = self._rules.get(site)
            if not rules:
                return None
            # one draw per configured rule, in spec order, so multi-rule
            # sites (delay + rare drop) keep independent schedules
            for rule in rules:
                if rule.is_partition():
                    continue  # pair-scoped: only link_blocked matches these
                if kinds is not None and rule.action not in kinds:
                    continue
                if not rule.instance_matches(who):
                    continue
                if rule.limit and rule.trips >= rule.limit:
                    continue
                if self._site_rng(site).random() < rule.prob:
                    rule.trips += 1
                    key = (site, rule.action)
                    self.trip_counts[key] = self.trip_counts.get(key, 0) + 1
                    return rule
            return None

    def link_blocked(self, site: str, src: str, dst: str) -> bool:
        """True when a partition rule at ``site`` cuts the directed link
        ``src -> dst``. Symmetric rules match either direction; one-way
        rules match src->dst only. Probabilistic partitions (flaky links)
        draw from the same seeded per-site stream as every other rule, so
        a chaos schedule replays. Trip semantics differ from event sites:
        a partition is link STATE, so ``trips`` counts blocked link
        CHECKS (dials refused, stream frames cut, idle polls while cut) —
        nonzero trips still means the partition was live and consulted."""
        if not self.enabled:
            return False
        with self._lock:
            rules = self._rules.get(site)
            if not rules:
                return False
            for rule in rules:
                if not rule.is_partition():
                    continue
                if not rule.link_matches(src, dst):
                    continue
                if (
                    rule.prob < 1.0
                    and self._site_rng(site).random() >= rule.prob
                ):
                    continue
                rule.trips += 1
                key = (site, rule.action)
                self.trip_counts[key] = self.trip_counts.get(key, 0) + 1
                return True
            return False

    async def fire_link(self, site: str, src: str, dst: str) -> None:
        """Async fault point for directed peer traffic: raises FaultDrop
        (the peer-vanished contract) when the link is partitioned."""
        if self.link_blocked(site, src, dst):
            raise FaultDrop(f"injected partition at {site}: {src} -/-> {dst}")

    def _raise(self, rule: FaultRule) -> None:
        log.warning("fault injected: %s (trip %d)", rule.spec(), rule.trips)
        if rule.action == "drop":
            raise FaultDrop(f"injected drop at {rule.site}")
        raise FaultInjected(f"injected error at {rule.site}")

    _FIRE_KINDS = ("drop", "delay", "error")

    def fire_sync(self, site: str, instance: str | None = None) -> None:
        """Blocking fault point (step thread, WAL append, transfer pull).
        Event-loop call sites must use the async ``fire`` instead."""
        rule = self.decide(site, instance=instance, kinds=self._FIRE_KINDS)
        if rule is None:
            return
        if rule.action == "delay":
            # dynalint: disable=DL001 -- blocking delay IS the contract
            # here: fire_sync is documented thread-side only (step thread,
            # WAL fsync, transfer pull); loop sites use async fire()
            time.sleep(rule.delay_s)
            return
        self._raise(rule)

    async def fire(self, site: str, instance: str | None = None) -> None:
        """Async fault point (event-loop call sites)."""
        rule = self.decide(site, instance=instance, kinds=self._FIRE_KINDS)
        if rule is None:
            return
        if rule.action == "delay":
            await asyncio.sleep(rule.delay_s)
            return
        self._raise(rule)

    def corrupt_bytes(
        self, site: str, data, instance: str | None = None
    ) -> bytes:
        """Payload fault point: when a ``corrupt`` rule trips at ``site``,
        return a copy of ``data`` with ``flips`` bits flipped at seeded
        positions; otherwise return ``data`` unchanged. The flip positions
        are a pure function of (seed, site, trip index), so a red chaos
        run replays bit-for-bit. Call sites place this where the payload
        crosses a process boundary — the receiver's content checksum
        (runtime/integrity.py) is the detection under test."""
        rule = self.decide(site, instance=instance, kinds=("corrupt",))
        if rule is None:
            return data
        buf = bytearray(data)
        if not buf:
            return data
        rng = random.Random(f"{self.seed}:{site}:corrupt:{rule.trips}")
        for _ in range(rule.flips):
            i = rng.randrange(len(buf))
            buf[i] ^= 1 << rng.randrange(8)
        log.warning(
            "fault injected: %s flipped %d bit(s) across %d bytes (trip %d)",
            rule.spec(), rule.flips, len(buf), rule.trips,
        )
        return bytes(buf)

    # -- observability -----------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "seed": self.seed,
                "rules": [
                    r.spec() for rs in self._rules.values() for r in rs
                ],
                "trips": {
                    f"{site}:{action}": n
                    for (site, action), n in sorted(self.trip_counts.items())
                },
            }

    def exposition(self) -> str:
        """Prometheus text lines for every /metrics surface (registered as
        a global provider with runtime/metrics.py)."""
        if not self.trip_counts:
            return ""
        lines = [
            "# HELP dynamo_fault_trips_total Injected fault trips by site/action.",
            "# TYPE dynamo_fault_trips_total counter",
        ]
        with self._lock:
            for (site, action), n in sorted(self.trip_counts.items()):
                lines.append(
                    f'dynamo_fault_trips_total{{site="{site}",'
                    f'action="{action}"}} {n}'
                )
        return "\n".join(lines) + "\n"


# The process-wide registry: env-configured at import, reconfigurable live
# via the worker admin ``faults`` RPC.
FAULTS = FaultRegistry(
    os.environ.get("DYN_FAULTS", ""),
    seed=int(os.environ.get("DYN_FAULTS_SEED", "0") or 0),
    instance=os.environ.get("DYN_FAULT_INSTANCE", ""),
)


def _register_metrics() -> None:
    from dynamo_tpu.runtime import metrics

    metrics.register_global_provider("faults", FAULTS.exposition)


_register_metrics()
