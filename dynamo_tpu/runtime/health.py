"""Health subsystem: canary probes, readiness state, status server, engine
watchdog — and the gray-failure plane (degradation scoring, SDC canaries,
quarantine-and-replace).

Reference parity:
  - HealthCheckManager (lib/runtime/src/health_check.rs:44-353): periodic
    canary requests THROUGH the real endpoint transport with a
    configurable payload; consecutive failures flip the endpoint
    unhealthy.
  - system_status_server.rs: /live /ready /health (+ /metrics) on a
    dedicated port.
  - engine-death watchdog (components/src/dynamo/vllm/engine_monitor.py):
    a dead engine loop deregisters the worker and shuts the runtime down.

TPU-framework twist: an unhealthy endpoint's instance key is WITHDRAWN
from the hub (lease kept alive), so routers drop it immediately — the
same effect the reference gets from lease-expiry, but without waiting out
the TTL; recovery re-publishes the key.

Beyond the reference (gray failures — degraded-but-alive capacity):

  - **SDC canaries**: the canary is a known-answer test, not just a
    liveness ping. A pinned greedy decode's tokens are compared against a
    golden recorded at the endpoint's first clean canary; any later
    mismatch is a silent-data-corruption verdict — immediate QUARANTINE,
    no failure-threshold grace (a chip that flips bits once will flip
    them again). ``readmit_threshold`` consecutive clean canaries
    re-admit.
  - **Quarantine** is soft-withdrawal: the instance card stays in the hub
    with ``metadata.state = "quarantined"`` (+ reason), so routers
    exclude it through their existing exclude= fail-open path while the
    autoscaler still SEES it (counts it as zero capacity and spawns a
    replacement) — unlike the fail-stop delete above, which makes the
    worker invisible to both.
  - **DegradationDetector**: fleet-side peer-relative outlier scoring
    over the ``step_time_ms`` fingerprint workers publish in
    ForwardPassMetrics. score = EWMA(step_time / fleet median); no
    absolute threshold to mistune, so a 10x-slow straggler is flagged
    within a few observations on any hardware generation, real or
    time-dilated sim.
"""

from __future__ import annotations

import asyncio
import logging
import statistics
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any

from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.integrity import corrupt_token_ids
from dynamo_tpu.runtime.transport import InstanceChannel, call_local

log = logging.getLogger("dynamo.health")

DEFAULT_CANARY = {
    "token_ids": [1],
    "stop_conditions": {"max_tokens": 1, "ignore_eos": True},
    "sampling": {"temperature": 0.0},
    "annotations": ["health-canary"],
}

# process-wide quarantine counters by reason (sdc | degraded | manual),
# exported on every /metrics surface as
# ``dynamo_worker_quarantines_total{reason}``
QUARANTINE_STATS: dict[str, int] = {}
_QUARANTINE_LOCK = threading.Lock()


def count_quarantine(reason: str) -> None:
    with _QUARANTINE_LOCK:
        QUARANTINE_STATS[reason] = QUARANTINE_STATS.get(reason, 0) + 1


def _quarantine_exposition() -> str:
    with _QUARANTINE_LOCK:
        snap = dict(QUARANTINE_STATS)
    if not snap:
        return ""
    lines = [
        "# HELP dynamo_worker_quarantines_total Workers soft-withdrawn "
        "by reason (sdc | degraded | manual).",
        "# TYPE dynamo_worker_quarantines_total counter",
    ]
    for reason, n in sorted(snap.items()):
        lines.append(
            f'dynamo_worker_quarantines_total{{reason="{reason}"}} {n}'
        )
    return "\n".join(lines) + "\n"


def _register_quarantine_metrics() -> None:
    from dynamo_tpu.runtime import metrics

    metrics.register_global_provider("quarantine", _quarantine_exposition)


_register_quarantine_metrics()


def quarantined_card(instance, reason: str):
    """The soft-withdrawn instance card: same identity, ``metadata.state``
    flipped to "quarantined" (+ reason). Routers exclude it; the
    autoscaler counts it as zero capacity."""
    meta = dict(instance.metadata)
    meta["state"] = "quarantined"
    meta["quarantine_reason"] = reason
    return replace(instance, metadata=meta)


def admitted_card(instance):
    """The re-admitted card: quarantine metadata stripped."""
    meta = {
        k: v for k, v in instance.metadata.items()
        if k not in ("state", "quarantine_reason")
    }
    return replace(instance, metadata=meta)


def is_quarantined(instance) -> bool:
    """True for an Instance (or raw card dict) in the quarantined state."""
    meta = (
        instance.get("metadata") if isinstance(instance, dict)
        else getattr(instance, "metadata", None)
    )
    return bool(meta) and meta.get("state") == "quarantined"


class DegradationDetector:
    """Peer-relative straggler scoring over worker step-time fingerprints.

    ``observe(worker, step_time_ms)`` feeds the latest fingerprint (from
    ForwardPassMetrics); ``scores()`` returns the EWMA-smoothed ratio of
    each worker's step time to the FLEET MEDIAN. A healthy fleet scores
    ~1.0 everywhere; a thermally-throttled chip drifts to its slowdown
    factor within a few observations (alpha=0.3: >2x after 3, >5x after
    ~6 observations of a 10x straggler). No absolute threshold exists to
    mistune — hardware generation and sim time-dilation divide out.

    Guards: scoring needs ``min_peers`` reporting workers (the median of
    a tiny fleet is the straggler itself — score everything 1.0 rather
    than flag noise), and workers with no fingerprint yet (0) are
    skipped. Thread-safe; ``forget()`` drops departed workers.
    """

    def __init__(
        self,
        *,
        tolerance: float = 3.0,
        ewma_alpha: float = 0.3,
        min_peers: int = 3,
    ):
        self.tolerance = tolerance
        self.ewma_alpha = ewma_alpha
        self.min_peers = min_peers
        self._latest: dict[Any, float] = {}
        self._ewma: dict[Any, float] = {}
        self._lock = threading.Lock()

    def observe(self, worker, step_time_ms: float) -> None:
        if step_time_ms and step_time_ms > 0:
            with self._lock:
                self._latest[worker] = float(step_time_ms)

    def forget(self, worker) -> None:
        with self._lock:
            self._latest.pop(worker, None)
            self._ewma.pop(worker, None)

    def scores(self) -> dict[Any, float]:
        """Smoothed peer-relative scores; advances the EWMA one step, so
        call at a steady cadence (router tick / autoscaler tick)."""
        with self._lock:
            if len(self._latest) < self.min_peers:
                # min-sample guard: don't score a fleet too small for its
                # median to mean anything
                return {w: 1.0 for w in self._latest}
            med = statistics.median(self._latest.values())
            if med <= 0:
                return {w: 1.0 for w in self._latest}
            a = self.ewma_alpha
            out = {}
            for w, v in self._latest.items():
                raw = v / med
                prev = self._ewma.get(w)
                self._ewma[w] = raw if prev is None else a * raw + (1 - a) * prev
                out[w] = self._ewma[w]
            return out

    def degraded(self) -> list:
        """Workers whose smoothed score breaches ``tolerance`` (e.g. 3.0 =
        3x the fleet median step time)."""
        return [w for w, s in self.scores().items() if s >= self.tolerance]

    def exposition(self) -> str:
        with self._lock:
            snap = dict(self._ewma)
        if not snap:
            return ""
        lines = [
            "# HELP dynamo_worker_degradation_score Peer-relative "
            "step-time ratio (EWMA vs fleet median; 1.0 = healthy).",
            "# TYPE dynamo_worker_degradation_score gauge",
        ]
        for w, s in sorted(snap.items(), key=lambda kv: str(kv[0])):
            lines.append(
                f'dynamo_worker_degradation_score{{worker="{w}"}} {s:.4f}'
            )
        return "\n".join(lines) + "\n"

    def export_metrics(self, name: str = "degradation") -> None:
        """Publish this detector's scores on every /metrics surface."""
        from dynamo_tpu.runtime import metrics

        metrics.register_global_provider(name, self.exposition)


@dataclass
class HealthCheckConfig:
    interval_s: float = 5.0
    timeout_s: float = 5.0
    failure_threshold: int = 2  # consecutive failures -> unhealthy
    # known-answer (SDC) checking: the first clean canary's tokens become
    # the golden; later mismatches quarantine IMMEDIATELY (no threshold —
    # silent corruption is not a transient), and ``readmit_threshold``
    # consecutive clean canaries lift the quarantine
    sdc_check: bool = True
    readmit_threshold: int = 3
    payload: dict[str, Any] = field(
        default_factory=lambda: dict(DEFAULT_CANARY)
    )


@dataclass
class EndpointHealth:
    path: str
    status: str = "unknown"  # unknown | ready | unhealthy | quarantined
    consecutive_failures: int = 0
    last_ok: float | None = None
    last_error: str | None = None
    probes: int = 0
    # quarantine lifecycle (SDC verdicts)
    quarantine_reason: str | None = None
    clean_streak: int = 0
    quarantines: int = 0

    def to_dict(self) -> dict[str, Any]:
        return {
            "path": self.path,
            "status": self.status,
            "consecutive_failures": self.consecutive_failures,
            "last_ok": self.last_ok,
            "last_error": self.last_error,
            "probes": self.probes,
            "quarantine_reason": self.quarantine_reason,
            "clean_streak": self.clean_streak,
            "quarantines": self.quarantines,
        }


@dataclass
class _ProbeEntry:
    served: Any
    health: EndpointHealth
    payload: dict
    golden: list | None = None  # known-answer tokens, set on first success


class HealthCheckManager:
    """Canary-probes served endpoints; withdraws/restores their instance
    keys in the hub as they flip unhealthy/ready."""

    def __init__(self, drt, config: HealthCheckConfig | None = None):
        self.drt = drt
        self.config = config or HealthCheckConfig()
        self._entries: list[_ProbeEntry] = []
        self._tasks: list[asyncio.Task] = []
        self._closed = False

    def register(self, served, payload: dict | None = None) -> EndpointHealth:
        """Start probing a ServedEndpoint (worker supplies the canary
        payload when the default token probe doesn't fit, ref
        vllm/main.py:199 health_check_payload)."""
        health = EndpointHealth(path=served.instance.endpoint_path)
        entry = _ProbeEntry(
            served=served, health=health,
            payload=payload or self.config.payload,
        )
        self._entries.append(entry)
        self._tasks.append(
            asyncio.get_running_loop().create_task(self._probe_loop(entry))
        )
        return health

    @property
    def statuses(self) -> list[EndpointHealth]:
        return [e.health for e in self._entries]

    @property
    def all_ready(self) -> bool:
        return bool(self._entries) and all(
            e.health.status == "ready" for e in self._entries
        )

    async def close(self) -> None:
        self._closed = True
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass  # we cancelled it: the expected outcome
            except Exception:  # noqa: BLE001
                # a probe loop that died of something OTHER than our
                # cancel was broken before close() — surface it
                # (dynalint DL003)
                log.warning("health probe task died unclean",
                            exc_info=True)

    # -- probing -----------------------------------------------------------

    @staticmethod
    def _check_item(item) -> None:
        """A handler reporting failure as an error item (finish_reason
        'error') is just as unhealthy as one that raises."""
        if isinstance(item, dict) and (
            item.get("finish_reason") == "error" or item.get("error")
        ):
            raise RuntimeError(f"canary error item: {item.get('error')}")

    @staticmethod
    def _fault_key(inst) -> str:
        """Identity this instance presents to ~instance-scoped faults."""
        return (
            f"{inst.host}:{inst.port}" if inst.port
            else f"{inst.instance_id:x}"
        )

    async def _canary(self, served, payload: dict) -> list:
        """One canary generate through the instance's real transport.
        Returns the first item's token ids — the known-answer material —
        after they pass the ``health.canary`` corrupt fault (the chaos
        stand-in for a chip flipping bits in the decode path)."""
        inst = served.instance
        ctx = Context(request_id=f"canary-{inst.instance_id:x}")
        toks: list = []
        if inst.transport == "local":
            handler = self.drt.local_registry.get(inst.wire_path)
            if handler is None:
                raise RuntimeError("handler not registered")
            stream = call_local(handler, payload, ctx)
            async for item in stream:
                self._check_item(item)
                toks = list(item.get("token_ids") or [])
                break
            ctx.stop_generating()
        else:
            ch = InstanceChannel(inst.host, inst.port)
            await ch.connect(self.drt.config.connect_timeout_s)
            try:
                async for item in ch.call(inst.wire_path, payload, ctx):
                    self._check_item(item)
                    toks = list(item.get("token_ids") or [])
                    break
                ctx.stop_generating()
            finally:
                await ch.close()
        return corrupt_token_ids(
            "health.canary", toks, instance=self._fault_key(inst)
        )

    async def _publish_card(self, instance) -> None:
        lease = await self.drt.lease_id()
        await self.drt.hub.put(
            instance.path, instance.to_dict(), lease_id=lease
        )

    async def _quarantine(self, served, health: EndpointHealth,
                          reason: str) -> None:
        """Soft-withdraw: the card stays in the hub, flagged quarantined —
        routers exclude it (fail-open), the autoscaler counts it as zero
        capacity and spawns a replacement."""
        health.status = "quarantined"
        health.quarantine_reason = reason
        health.clean_streak = 0
        health.quarantines += 1
        count_quarantine(reason)
        log.warning(
            "endpoint %s QUARANTINED (%s); soft-withdrawing instance %x",
            health.path, reason, served.instance.instance_id,
        )
        await self._publish_card(quarantined_card(served.instance, reason))

    async def _readmit(self, served, health: EndpointHealth) -> None:
        log.info(
            "endpoint %s re-admitted after %d clean canaries; "
            "re-publishing instance %x",
            health.path, health.clean_streak, served.instance.instance_id,
        )
        health.quarantine_reason = None
        health.clean_streak = 0
        await self._publish_card(admitted_card(served.instance))

    async def _probe_loop(self, entry: _ProbeEntry) -> None:
        served, health, payload = entry.served, entry.health, entry.payload
        cfg = self.config
        while not self._closed:
            await asyncio.sleep(cfg.interval_s)
            health.probes += 1
            try:
                toks = await asyncio.wait_for(
                    self._canary(served, payload), cfg.timeout_s
                )
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                health.consecutive_failures += 1
                health.last_error = f"{type(e).__name__}: {e}"
                if (
                    health.consecutive_failures >= cfg.failure_threshold
                    and health.status != "unhealthy"
                ):
                    health.status = "unhealthy"
                    log.warning(
                        "endpoint %s unhealthy (%s); withdrawing instance %x",
                        health.path, health.last_error,
                        served.instance.instance_id,
                    )
                    await self.drt.hub.delete(served.instance.path)
                continue
            health.consecutive_failures = 0
            health.last_ok = time.time()
            if cfg.sdc_check:
                if entry.golden is None:
                    # golden recorded at startup: the first clean canary's
                    # tokens ARE the known answer (pinned greedy decode)
                    entry.golden = toks
                elif toks != entry.golden:
                    # silent data corruption: the worker answered — fast,
                    # confidently, and WRONG. Quarantine immediately; no
                    # consecutive-failure grace for flipped bits.
                    health.last_error = (
                        f"sdc: canary tokens {toks} != golden {entry.golden}"
                    )
                    if health.status != "quarantined":
                        await self._quarantine(served, health, "sdc")
                    else:
                        health.clean_streak = 0
                    continue
            if health.status == "quarantined":
                health.clean_streak += 1
                if health.clean_streak < cfg.readmit_threshold:
                    continue
                await self._readmit(served, health)
            elif health.status == "unhealthy":
                log.info(
                    "endpoint %s recovered; re-publishing instance %x",
                    health.path, served.instance.instance_id,
                )
                await self._publish_card(served.instance)
            health.status = "ready"


class SystemStatusServer:
    """Liveness/readiness/health/metrics on a dedicated port (ref
    system_status_server.rs, DYN_SYSTEM_PORT)."""

    def __init__(
        self,
        *,
        health: HealthCheckManager | None = None,
        metrics=None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        from aiohttp import web

        self.health = health
        self.metrics = metrics
        self.host = host
        self.port = port
        self._web = web
        self.app = web.Application()
        self.app.add_routes([
            web.get("/live", self._live),
            web.get("/ready", self._ready),
            web.get("/health", self._health),
            web.get("/metrics", self._metrics),
        ])
        self._runner = None

    async def start(self) -> "SystemStatusServer":
        web = self._web
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in site._server.sockets:
            self.port = s.getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    async def _live(self, _request):
        return self._web.json_response({"status": "live"})

    async def _ready(self, _request):
        ready = self.health.all_ready if self.health is not None else True
        return self._web.json_response(
            {"status": "ready" if ready else "notready"},
            status=200 if ready else 503,
        )

    async def _health(self, _request):
        statuses = (
            [h.to_dict() for h in self.health.statuses]
            if self.health is not None
            else []
        )
        ready = self.health.all_ready if self.health is not None else True
        return self._web.json_response(
            {"status": "ready" if ready else "notready",
             "endpoints": statuses}
        )

    async def _metrics(self, _request):
        if self.metrics is None:
            return self._web.Response(status=404)
        return self._web.Response(
            body=self.metrics.exposition(),
            content_type="text/plain",
        )


class EngineMonitor:
    """Watchdog: if the engine's step loop dies, deregister this worker and
    shut the runtime down (ref VllmEngineMonitor engine_monitor.py;
    EngineDeadError -> runtime.shutdown in handlers.py:112-117)."""

    def __init__(self, drt, engine, *, interval_s: float = 1.0):
        self.drt = drt
        self.engine = engine
        self.interval_s = interval_s
        self._task = asyncio.get_running_loop().create_task(self._watch())

    def _engine_dead(self) -> bool:
        dead = getattr(self.engine, "is_dead", None)
        if dead is not None:
            return bool(dead)
        task = getattr(self.engine, "_loop_task", None)
        return task is not None and task.done()

    async def _watch(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            if self.engine is None:
                continue
            if getattr(self.engine, "_closed", False):
                return  # orderly close, not a death
            if self._engine_dead():
                log.error(
                    "engine step loop died; deregistering worker and "
                    "shutting down"
                )
                await self.drt.shutdown(drain=False)
                return

    async def close(self) -> None:
        self._task.cancel()
        try:
            await self._task
        except asyncio.CancelledError:
            pass
