"""Health subsystem: canary probes, readiness state, status server, engine
watchdog.

Reference parity:
  - HealthCheckManager (lib/runtime/src/health_check.rs:44-353): periodic
    canary requests THROUGH the real endpoint transport with a
    configurable payload; consecutive failures flip the endpoint
    unhealthy.
  - system_status_server.rs: /live /ready /health (+ /metrics) on a
    dedicated port.
  - engine-death watchdog (components/src/dynamo/vllm/engine_monitor.py):
    a dead engine loop deregisters the worker and shuts the runtime down.

TPU-framework twist: an unhealthy endpoint's instance key is WITHDRAWN
from the hub (lease kept alive), so routers drop it immediately — the
same effect the reference gets from lease-expiry, but without waiting out
the TTL; recovery re-publishes the key.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any

from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.transport import InstanceChannel, call_local

log = logging.getLogger("dynamo.health")

DEFAULT_CANARY = {
    "token_ids": [1],
    "stop_conditions": {"max_tokens": 1, "ignore_eos": True},
    "sampling": {"temperature": 0.0},
    "annotations": ["health-canary"],
}


@dataclass
class HealthCheckConfig:
    interval_s: float = 5.0
    timeout_s: float = 5.0
    failure_threshold: int = 2  # consecutive failures -> unhealthy
    payload: dict[str, Any] = field(
        default_factory=lambda: dict(DEFAULT_CANARY)
    )


@dataclass
class EndpointHealth:
    path: str
    status: str = "unknown"  # unknown | ready | unhealthy
    consecutive_failures: int = 0
    last_ok: float | None = None
    last_error: str | None = None
    probes: int = 0

    def to_dict(self) -> dict[str, Any]:
        return {
            "path": self.path,
            "status": self.status,
            "consecutive_failures": self.consecutive_failures,
            "last_ok": self.last_ok,
            "last_error": self.last_error,
            "probes": self.probes,
        }


class HealthCheckManager:
    """Canary-probes served endpoints; withdraws/restores their instance
    keys in the hub as they flip unhealthy/ready."""

    def __init__(self, drt, config: HealthCheckConfig | None = None):
        self.drt = drt
        self.config = config or HealthCheckConfig()
        self._entries: list[tuple[Any, EndpointHealth, dict]] = []
        self._tasks: list[asyncio.Task] = []
        self._closed = False

    def register(self, served, payload: dict | None = None) -> EndpointHealth:
        """Start probing a ServedEndpoint (worker supplies the canary
        payload when the default token probe doesn't fit, ref
        vllm/main.py:199 health_check_payload)."""
        health = EndpointHealth(path=served.instance.endpoint_path)
        entry = (served, health, payload or self.config.payload)
        self._entries.append(entry)
        self._tasks.append(
            asyncio.get_running_loop().create_task(self._probe_loop(entry))
        )
        return health

    @property
    def statuses(self) -> list[EndpointHealth]:
        return [h for _, h, _ in self._entries]

    @property
    def all_ready(self) -> bool:
        return bool(self._entries) and all(
            h.status == "ready" for _, h, _ in self._entries
        )

    async def close(self) -> None:
        self._closed = True
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass  # we cancelled it: the expected outcome
            except Exception:  # noqa: BLE001
                # a probe loop that died of something OTHER than our
                # cancel was broken before close() — surface it
                # (dynalint DL003)
                log.warning("health probe task died unclean",
                            exc_info=True)

    # -- probing -----------------------------------------------------------

    @staticmethod
    def _check_item(item) -> None:
        """A handler reporting failure as an error item (finish_reason
        'error') is just as unhealthy as one that raises."""
        if isinstance(item, dict) and (
            item.get("finish_reason") == "error" or item.get("error")
        ):
            raise RuntimeError(f"canary error item: {item.get('error')}")

    async def _canary(self, served, payload: dict) -> None:
        """One canary generate through the instance's real transport."""
        inst = served.instance
        ctx = Context(request_id=f"canary-{inst.instance_id:x}")
        if inst.transport == "local":
            handler = self.drt.local_registry.get(inst.wire_path)
            if handler is None:
                raise RuntimeError("handler not registered")
            stream = call_local(handler, payload, ctx)
            async for item in stream:
                self._check_item(item)
                break
            ctx.stop_generating()
            return
        ch = InstanceChannel(inst.host, inst.port)
        await ch.connect(self.drt.config.connect_timeout_s)
        try:
            async for item in ch.call(inst.wire_path, payload, ctx):
                self._check_item(item)
                break
            ctx.stop_generating()
        finally:
            await ch.close()

    async def _probe_loop(self, entry) -> None:
        served, health, payload = entry
        cfg = self.config
        while not self._closed:
            await asyncio.sleep(cfg.interval_s)
            health.probes += 1
            try:
                await asyncio.wait_for(
                    self._canary(served, payload), cfg.timeout_s
                )
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                health.consecutive_failures += 1
                health.last_error = f"{type(e).__name__}: {e}"
                if (
                    health.consecutive_failures >= cfg.failure_threshold
                    and health.status != "unhealthy"
                ):
                    health.status = "unhealthy"
                    log.warning(
                        "endpoint %s unhealthy (%s); withdrawing instance %x",
                        health.path, health.last_error,
                        served.instance.instance_id,
                    )
                    await self.drt.hub.delete(served.instance.path)
                continue
            health.consecutive_failures = 0
            health.last_ok = time.time()
            if health.status == "unhealthy":
                log.info(
                    "endpoint %s recovered; re-publishing instance %x",
                    health.path, served.instance.instance_id,
                )
                lease = await self.drt.lease_id()
                await self.drt.hub.put(
                    served.instance.path,
                    served.instance.to_dict(),
                    lease_id=lease,
                )
            health.status = "ready"


class SystemStatusServer:
    """Liveness/readiness/health/metrics on a dedicated port (ref
    system_status_server.rs, DYN_SYSTEM_PORT)."""

    def __init__(
        self,
        *,
        health: HealthCheckManager | None = None,
        metrics=None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        from aiohttp import web

        self.health = health
        self.metrics = metrics
        self.host = host
        self.port = port
        self._web = web
        self.app = web.Application()
        self.app.add_routes([
            web.get("/live", self._live),
            web.get("/ready", self._ready),
            web.get("/health", self._health),
            web.get("/metrics", self._metrics),
        ])
        self._runner = None

    async def start(self) -> "SystemStatusServer":
        web = self._web
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in site._server.sockets:
            self.port = s.getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    async def _live(self, _request):
        return self._web.json_response({"status": "live"})

    async def _ready(self, _request):
        ready = self.health.all_ready if self.health is not None else True
        return self._web.json_response(
            {"status": "ready" if ready else "notready"},
            status=200 if ready else 503,
        )

    async def _health(self, _request):
        statuses = (
            [h.to_dict() for h in self.health.statuses]
            if self.health is not None
            else []
        )
        ready = self.health.all_ready if self.health is not None else True
        return self._web.json_response(
            {"status": "ready" if ready else "notready",
             "endpoints": statuses}
        )

    async def _metrics(self, _request):
        if self.metrics is None:
            return self._web.Response(status=404)
        return self._web.Response(
            body=self.metrics.exposition(),
            content_type="text/plain",
        )


class EngineMonitor:
    """Watchdog: if the engine's step loop dies, deregister this worker and
    shut the runtime down (ref VllmEngineMonitor engine_monitor.py;
    EngineDeadError -> runtime.shutdown in handlers.py:112-117)."""

    def __init__(self, drt, engine, *, interval_s: float = 1.0):
        self.drt = drt
        self.engine = engine
        self.interval_s = interval_s
        self._task = asyncio.get_running_loop().create_task(self._watch())

    def _engine_dead(self) -> bool:
        dead = getattr(self.engine, "is_dead", None)
        if dead is not None:
            return bool(dead)
        task = getattr(self.engine, "_loop_task", None)
        return task is not None and task.done()

    async def _watch(self) -> None:
        while True:
            await asyncio.sleep(self.interval_s)
            if self.engine is None:
                continue
            if getattr(self.engine, "_closed", False):
                return  # orderly close, not a death
            if self._engine_dead():
                log.error(
                    "engine step loop died; deregistering worker and "
                    "shutting down"
                )
                await self.drt.shutdown(drain=False)
                return

    async def close(self) -> None:
        self._task.cancel()
        try:
            await self._task
        except asyncio.CancelledError:
            pass
