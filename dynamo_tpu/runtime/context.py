"""Per-request context: identity, cancellation, tracing baggage.

Mirrors the role of the reference's ``AsyncEngineContext``
(lib/runtime/src/engine.rs:112 - ``stop_generating``, ``killed``) and the
pipeline ``Context`` (lib/runtime/src/pipeline/context.rs): a handle that
travels with a request through every operator and across process boundaries,
letting any stage observe or trigger cancellation.
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Any


class StreamError(RuntimeError):
    """A response stream died mid-flight (worker crash / connection loss).

    The migration operator (frontend.migration) catches this to re-dispatch
    the request to another worker; ref lib/llm/src/migration.rs STREAM_ERR_MSG.
    """


class Context:
    """Cancellation + identity context for one in-flight request."""

    def __init__(self, request_id: str | None = None, headers: dict[str, str] | None = None):
        self.id: str = request_id or uuid.uuid4().hex
        self.headers: dict[str, str] = headers or {}
        self._stopped = asyncio.Event()
        self._killed = asyncio.Event()
        self._children: list[Context] = []

    # -- cancellation ------------------------------------------------------

    def stop_generating(self) -> None:
        """Graceful cancel: finish the current step, emit no more tokens."""
        self._stopped.set()
        for c in self._children:
            c.stop_generating()

    def kill(self) -> None:
        """Hard cancel: abandon the request immediately."""
        self._killed.set()
        self.stop_generating()

    @property
    def is_stopped(self) -> bool:
        return self._stopped.is_set()

    @property
    def is_killed(self) -> bool:
        return self._killed.is_set()

    async def stopped(self) -> None:
        await self._stopped.wait()

    async def killed_or_stopped(self) -> None:
        await self._stopped.wait()

    def child(self, request_id: str | None = None) -> "Context":
        """Derived context: cancelling the parent cancels the child."""
        c = Context(request_id or self.id, dict(self.headers))
        if self.is_stopped:
            c.stop_generating()
        if self.is_killed:
            c.kill()
        self._children.append(c)
        return c

    def link_task(self, task: asyncio.Task) -> None:
        """Cancel ``task`` when this context is stopped."""

        async def _watch() -> None:
            await self._stopped.wait()
            if not task.done():
                task.cancel()

        watcher = asyncio.get_running_loop().create_task(_watch())
        task.add_done_callback(lambda _t: watcher.cancel())

    def __repr__(self) -> str:  # pragma: no cover
        state = "killed" if self.is_killed else "stopped" if self.is_stopped else "live"
        return f"Context({self.id[:8]}, {state})"


def ensure_context(ctx: Context | None) -> Context:
    return ctx if ctx is not None else Context()


def annotation(event: str, data: Any = None) -> dict[str, Any]:
    """Out-of-band event envelope entry (ref protocols Annotated<T>)."""
    return {"event": event, "data": data}
