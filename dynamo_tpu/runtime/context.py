"""Per-request context: identity, cancellation, tracing baggage.

Mirrors the role of the reference's ``AsyncEngineContext``
(lib/runtime/src/engine.rs:112 - ``stop_generating``, ``killed``) and the
pipeline ``Context`` (lib/runtime/src/pipeline/context.rs): a handle that
travels with a request through every operator and across process boundaries,
letting any stage observe or trigger cancellation.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
import uuid
from typing import Any, Coroutine

_task_log = logging.getLogger("dynamo.tasks")

# Strong references for fire-and-forget tasks: the event loop itself only
# holds tasks *weakly*, so a task whose result is dropped can be garbage-
# collected mid-flight — silently cancelling the work (the PR-3 drain-task
# bug; dynalint DL002 now rejects bare create_task/ensure_future).
_BACKGROUND_TASKS: set[asyncio.Task] = set()


def spawn(coro: Coroutine, *, name: str | None = None) -> asyncio.Task:
    """create_task with the two things every fire-and-forget site needs:
    a strong reference until the task finishes, and a done-callback that
    logs unexpected exceptions instead of letting them vanish with the
    task object. Returns the task so callers can still cancel/await it."""
    task = asyncio.get_running_loop().create_task(coro, name=name)
    _BACKGROUND_TASKS.add(task)

    def _done(t: asyncio.Task) -> None:
        _BACKGROUND_TASKS.discard(t)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None:
            _task_log.error(
                "background task %s crashed: %s: %s",
                t.get_name(), type(exc).__name__, exc,
                exc_info=exc,
            )

    task.add_done_callback(_done)
    return task


class StreamError(RuntimeError):
    """A response stream died mid-flight (worker crash / connection loss).

    The migration operator (frontend.migration) catches this to re-dispatch
    the request to another worker; ref lib/llm/src/migration.rs STREAM_ERR_MSG.
    """


class ServiceUnavailable(StreamError):
    """A worker refused the request because it is draining or saturated.

    Retryable (another instance may accept — a StreamError, so the
    migration operator re-drives it with backoff); when retries exhaust,
    the HTTP frontend maps it to 503 with ``Retry-After``.
    """

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class DeadlineExceeded(RuntimeError):
    """The request's end-to-end deadline passed. NOT a StreamError: spending
    more time retrying a request whose client has given up is the failure
    mode deadlines exist to prevent. HTTP maps it to 504."""


class OverQuota(RuntimeError):
    """The tenant's token bucket cannot cover this request (engine
    admission quota, engine/tenancy.py). NOT a StreamError: the quota is
    a policy decision about this tenant's traffic, so migrating to
    another worker would just burn its bucket there too — the client
    must back off. HTTP maps it to 429 with ``Retry-After`` computed
    from live bucket state (deficit / refill rate)."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


# Remaining request budget in milliseconds, attached to the wire headers at
# send time (relative, so no cross-host clock sync needed) and rebuilt into
# an absolute monotonic deadline on the receiving side.
DEADLINE_HEADER = "x-dyn-deadline-ms"

# Tenancy baggage (overload-control plane): stamped into Context.headers
# at the serving edge (validated there — see frontend/validation.py
# validate_tenancy), carried through EPP -> transport -> worker like any
# other baggage header, and read by the engine's fair-admission layer.
TENANT_HEADER = "x-dyn-tenant"
PRIORITY_HEADER = "x-dyn-priority"


def tenancy_from_headers(
    headers: dict[str, str] | None,
) -> tuple[str, str]:
    """(tenant, priority) from wire headers, defaulted for untagged
    traffic (direct engine callers, pre-tenancy clients): tenant
    "default", priority "interactive" — untagged traffic must never be
    easier to shed than tagged interactive traffic."""
    h = headers or {}
    tenant = (h.get(TENANT_HEADER) or "default").strip() or "default"
    priority = (h.get(PRIORITY_HEADER) or "interactive").strip().lower()
    if priority not in ("interactive", "batch"):
        priority = "interactive"
    return tenant, priority


def tighten_timeout_s(default_s: float, raw_ms: Any) -> float:
    """Tighten (never loosen) an end-to-end budget with a client-supplied
    relative timeout in milliseconds — the one clamp rule shared by every
    serving surface (HTTP ``x-dyn-timeout-ms``, gRPC ``timeout_ms``), so
    the DYN_REQUEST_TIMEOUT_S contract can't drift between them.

    Invalid or non-finite input leaves the default; with the default
    disabled (``<= 0``) the client value is the sole source; the floor is
    1ms so a zero/negative request fails fast instead of disabling the
    deadline."""
    try:
        ms = float(raw_ms)
    except (TypeError, ValueError):
        return default_s
    if not math.isfinite(ms):  # 'nan'/'inf' must not drop the cap
        return default_s
    s = max(ms / 1000.0, 0.001)
    return min(s, default_s) if default_s > 0 else s


def deadline_from_headers(headers: dict[str, str] | None) -> float | None:
    """Absolute monotonic deadline from a relative wire header, or None."""
    raw = (headers or {}).get(DEADLINE_HEADER)
    if not raw:
        return None
    try:
        return time.monotonic() + max(float(raw), 0.0) / 1000.0
    except ValueError:
        return None


class Context:
    """Cancellation + identity context for one in-flight request."""

    def __init__(
        self,
        request_id: str | None = None,
        headers: dict[str, str] | None = None,
        deadline: float | None = None,
    ):
        self.id: str = request_id or uuid.uuid4().hex
        self.headers: dict[str, str] = headers or {}
        # absolute time.monotonic() deadline; None = unbounded (legacy)
        self.deadline: float | None = deadline
        self._stopped = asyncio.Event()
        self._killed = asyncio.Event()
        self._children: list[Context] = []
        # sync callbacks fired once on the stopped edge — lets hot paths
        # (one per in-flight wire call) observe cancellation without
        # parking a watcher task each on ``stopped()``
        self._stop_cbs: list = []

    # -- cancellation ------------------------------------------------------

    def stop_generating(self) -> None:
        """Graceful cancel: finish the current step, emit no more tokens."""
        first = not self._stopped.is_set()
        self._stopped.set()
        if first and self._stop_cbs:
            cbs, self._stop_cbs = self._stop_cbs, []
            for cb in cbs:
                cb()
        for c in self._children:
            c.stop_generating()

    def add_stop_callback(self, cb) -> None:
        """Register a sync callback for the stopped edge (fires
        immediately if already stopped). Pair with
        ``remove_stop_callback`` when the interest ends."""
        if self._stopped.is_set():
            cb()
            return
        self._stop_cbs.append(cb)

    def remove_stop_callback(self, cb) -> None:
        try:
            self._stop_cbs.remove(cb)
        except ValueError:
            pass

    def kill(self) -> None:
        """Hard cancel: abandon the request immediately."""
        self._killed.set()
        self.stop_generating()

    @property
    def is_stopped(self) -> bool:
        return self._stopped.is_set()

    @property
    def is_killed(self) -> bool:
        return self._killed.is_set()

    async def stopped(self) -> None:
        await self._stopped.wait()

    async def killed_or_stopped(self) -> None:
        await self._stopped.wait()

    # -- deadlines ---------------------------------------------------------

    def remaining_s(self) -> float | None:
        """Seconds until the deadline (clamped at 0), or None if unbounded."""
        if self.deadline is None:
            return None
        return max(self.deadline - time.monotonic(), 0.0)

    @property
    def deadline_expired(self) -> bool:
        return self.deadline is not None and time.monotonic() >= self.deadline

    def wire_headers(self) -> dict[str, str]:
        """Headers to send with this request: baggage plus the remaining
        deadline budget in ms (the receiver rebuilds an absolute deadline
        via deadline_from_headers), plus the LIVE trace context — the
        sender's current span, not the traceparent stashed at admission —
        so the receiver binds the actual calling span as its remote
        parent and every wire hop propagates tracing for free
        (runtime/tracing.py)."""
        from dynamo_tpu.runtime import tracing

        cur = tracing.current_trace()
        remaining = self.remaining_s()
        if remaining is None and cur is None:
            return self.headers
        headers = dict(self.headers)
        if cur is not None:
            headers[tracing.TRACEPARENT] = cur.to_traceparent()
        if remaining is not None:
            headers[DEADLINE_HEADER] = str(int(remaining * 1000))
        return headers

    def child(self, request_id: str | None = None) -> "Context":
        """Derived context: cancelling the parent cancels the child."""
        c = Context(request_id or self.id, dict(self.headers), deadline=self.deadline)
        if self.is_stopped:
            c.stop_generating()
        if self.is_killed:
            c.kill()
        self._children.append(c)
        return c

    def link_task(self, task: asyncio.Task) -> None:
        """Cancel ``task`` when this context is stopped."""

        async def _watch() -> None:
            await self._stopped.wait()
            if not task.done():
                task.cancel()

        watcher = asyncio.get_running_loop().create_task(_watch())
        task.add_done_callback(lambda _t: watcher.cancel())

    def __repr__(self) -> str:  # pragma: no cover
        state = "killed" if self.is_killed else "stopped" if self.is_stopped else "live"
        return f"Context({self.id[:8]}, {state})"


def ensure_context(ctx: Context | None) -> Context:
    return ctx if ctx is not None else Context()


def annotation(event: str, data: Any = None) -> dict[str, Any]:
    """Out-of-band event envelope entry (ref protocols Annotated<T>)."""
    return {"event": event, "data": data}
