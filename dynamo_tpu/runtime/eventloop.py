"""Opt-in uvloop installation for process entrypoints.

``DYN_UVLOOP=1`` swaps the default asyncio event loop for uvloop at the
frontend/worker/gateway entrypoints — worth ~20-40% on the syscall-bound
stream plane (benchmarks/stream_bench.py measures it on this box). The
dependency is deliberately optional: when uvloop isn't installed (it is
not vendored) or the platform doesn't support it, we log once and fall
back to the stock loop. Library code must never call this — only process
``main()``s, before their ``asyncio.run``.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("dynamo.eventloop")


def maybe_install_uvloop(env: dict[str, str] | None = None) -> bool:
    """Install uvloop as the event-loop policy if DYN_UVLOOP asks for it.

    Returns True iff uvloop is now the policy; falls back cleanly (False)
    when the knob is off or uvloop is unavailable.
    """
    raw = (env or os.environ).get("DYN_UVLOOP", "")
    if raw.lower() not in ("1", "true", "yes", "on"):
        return False
    try:
        import uvloop
    except ImportError:
        log.warning("DYN_UVLOOP=1 but uvloop is not installed; using asyncio")
        return False
    uvloop.install()
    log.info("uvloop installed as event-loop policy")
    return True
