"""Runtime and DistributedRuntime: process + cluster handles.

``Runtime`` owns the process lifecycle (shutdown event, graceful-shutdown
tracking) - ref lib/runtime/src/lib.rs:72. ``DistributedRuntime`` adds the
cluster: hub connection, lease + keepalive, the shared EndpointServer for
this process's endpoints, the local in-proc registry, and the component tree
accessor - ref lib.rs:184.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
from typing import Any

from dynamo_tpu.runtime.component import (
    Endpoint,
    Instance,
    Namespace,
    ServedEndpoint,
)
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.hub import Hub, InMemoryHub
from dynamo_tpu.runtime.hub_client import RemoteHub
from dynamo_tpu.runtime.transport import EndpointServer, Handler, LocalRegistry

log = logging.getLogger("dynamo.runtime")


class Runtime:
    """Process runtime: shutdown coordination."""

    def __init__(self) -> None:
        self._shutdown = asyncio.Event()

    def shutdown(self) -> None:
        self._shutdown.set()

    @property
    def is_shutdown(self) -> bool:
        return self._shutdown.is_set()

    async def wait_for_shutdown(self) -> None:
        await self._shutdown.wait()


class DistributedRuntime:
    """Cluster handle: hub + lease + endpoint serving + component tree."""

    def __init__(self, hub: Hub, config: RuntimeConfig | None = None, runtime: Runtime | None = None):
        self.hub = hub
        self.config = config or RuntimeConfig()
        self.runtime = runtime or Runtime()
        self.local_registry = LocalRegistry()
        self._server: EndpointServer | None = None
        self._lease_id: int | None = None
        self._keepalive_task: asyncio.Task | None = None
        self._served: list[ServedEndpoint] = []
        self._closed = False
        # local instances dispatch in-proc only when hub state is shared, i.e.
        # the hub is the in-memory one living in this very process.
        self._local_ok = isinstance(hub, InMemoryHub)

    # -- construction ------------------------------------------------------

    @classmethod
    async def from_settings(cls, config: RuntimeConfig | None = None) -> "DistributedRuntime":
        """Connect per config: remote hub if ``hub_target()`` (replica
        list or single address) is set, else local."""
        config = config or RuntimeConfig.from_env()
        hub: Hub
        if config.hub_target():
            hub = await RemoteHub.connect(config.hub_target(), config.connect_timeout_s)
        else:
            hub = InMemoryHub()
        return cls(hub, config)

    # -- component tree ----------------------------------------------------

    def namespace(self, name: str | None = None) -> Namespace:
        return Namespace(self, name or self.config.namespace)

    # -- lease -------------------------------------------------------------

    async def lease_id(self) -> int:
        """This process's primary lease (allocated on first use)."""
        if self._lease_id is None:
            self._lease_id = await self.hub.grant_lease(self.config.lease_ttl_s)
            self._keepalive_task = asyncio.get_running_loop().create_task(
                self._keepalive_loop()
            )
        return self._lease_id

    async def _keepalive_loop(self) -> None:
        try:
            while not self._closed:
                await asyncio.sleep(self.config.keepalive_interval_s)
                if self._lease_id is None:
                    continue
                ok = await self.hub.keepalive(self._lease_id)
                if not ok:
                    log.error("lease %s lost; shutting down", self._lease_id)
                    self.runtime.shutdown()
                    return
        except asyncio.CancelledError:
            pass
        except ConnectionError:
            log.error("hub connection lost in keepalive; shutting down")
            self.runtime.shutdown()

    # -- endpoint serving --------------------------------------------------

    _uds_seq = 0

    async def _endpoint_server(self) -> EndpointServer:
        if self._server is None:
            uds_path = None
            if self.config.uds_dir:
                DistributedRuntime._uds_seq += 1
                uds_path = os.path.join(
                    self.config.uds_dir,
                    f"dyn-{os.getpid()}-{DistributedRuntime._uds_seq}.sock",
                )
            self._server = EndpointServer(host=self.config.host, uds_path=uds_path)
            await self._server.start()
        return self._server

    async def serve_endpoint(
        self,
        endpoint: Endpoint,
        handler: Handler,
        *,
        metadata: dict[str, Any],
        graceful_shutdown: bool = True,
    ) -> ServedEndpoint:
        lease = await self.lease_id()
        instance_id = self._alloc_instance_id(lease)
        if self._local_ok:
            # In-proc hub => single-process deployment: skip the TCP hop.
            inst = Instance(
                instance_id=instance_id,
                namespace=endpoint.namespace,
                component=endpoint.component,
                endpoint=endpoint.name,
                host="local",
                port=0,
                transport="local",
                metadata=metadata,
            )
            self.local_registry.register(inst.wire_path, handler)
        else:
            server = await self._endpoint_server()
            inst = Instance(
                instance_id=instance_id,
                namespace=endpoint.namespace,
                component=endpoint.component,
                endpoint=endpoint.name,
                host=server.host,
                port=server.port,
                transport="tcp",
                metadata=metadata,
                uds=server.uds_path or "",
            )
            server.register(inst.wire_path, handler)
        await self.hub.put(inst.path, inst.to_dict(), lease_id=lease)
        served = ServedEndpoint(inst, endpoint, self)
        self._served.append(served)
        log.info("serving %s as instance %x", endpoint.path, inst.instance_id)
        return served

    _instance_seq = 0

    def _alloc_instance_id(self, lease: int) -> int:
        """Unique instance id: lease id in the high bits + per-process seq.

        The reference uses the etcd lease id directly; we add a sequence so
        one process can serve several endpoints under one lease.
        """
        DistributedRuntime._instance_seq += 1
        return (lease << 16) | (
            (DistributedRuntime._instance_seq & 0xFF) << 8
        ) | random.randrange(256)

    async def deregister_endpoint(
        self,
        served: ServedEndpoint,
        drain: bool = True,
        grace_s: float | None = None,
    ) -> None:
        """Withdraw an instance: hub key first, handler last.

        The ordering is the scale-down drain contract (ISSUE 17 ride-along):
        routers route from a WATCHED copy of the instance set, so there is a
        propagation window between the hub delete and every router observing
        it. A pick made inside that window must still land on a live handler
        — so with ``drain=True`` the wire-path handler stays registered for
        ``grace_s`` after the key withdrawal (racing dispatches are served),
        and only then is torn down. ``grace_s=None`` uses the runtime's
        ``withdraw_grace_s``; mass teardown (``shutdown``) passes 0 because
        the server-level drain already covers in-flight streams and the
        whole process is exiting anyway.
        """
        await self.hub.delete(served.instance.path)
        if drain:
            g = self.config.withdraw_grace_s if grace_s is None else grace_s
            if g > 0:
                await asyncio.sleep(g)
        if served.instance.transport == "local":
            self.local_registry.unregister(served.instance.wire_path)
        elif self._server is not None:
            self._server.unregister(served.instance.wire_path)
        if served in self._served:
            self._served.remove(served)

    # -- shutdown ----------------------------------------------------------

    async def shutdown(
        self, drain: bool = True, drain_timeout: float = 30.0
    ) -> None:
        if self._closed:
            return
        self._closed = True
        for served in list(self._served):
            await self.deregister_endpoint(served, drain=drain, grace_s=0.0)
        if self._server is not None:
            await self._server.stop(drain=drain, timeout=drain_timeout)
        if self._keepalive_task is not None:
            self._keepalive_task.cancel()
        if self._lease_id is not None:
            try:
                await self.hub.revoke_lease(self._lease_id)
            except (ConnectionError, RuntimeError):
                pass
        self.runtime.shutdown()

    async def close(self) -> None:
        await self.shutdown()
        await self.hub.close()
