"""End-to-end KV payload integrity: content checksums + typed failure.

A gray accelerator or interconnect fault does not crash anything — it
flips bits. Every KV payload that crosses a process boundary (disagg
``transfer.py`` pull blocks, migration resume prompts, KVBM G2/G3/G4
tier blocks, packed fp8 codec included) is stamped with a content
checksum at the sender and verified on receipt, so poisoned KV is
*detected* instead of decoded into garbage tokens.

The checksum is pure-stdlib: chained ``zlib.crc32`` over ``memoryview``s
(zero-copy over numpy blocks; C-speed, xxhash-class throughput for the
block sizes KV payloads come in). It is an integrity check against
*accidental* corruption — bit flips, truncation, torn writes — not an
authenticity MAC.

A failed check raises :class:`IntegrityError`, a ``StreamError``
subclass, so it rides every existing recovery path with zero new
plumbing:

  - disagg pull      -> the decode engine's local-prefill fallback
                        (token continuity preserved);
  - KVBM onboard     -> tier miss + eviction of the poisoned block
                        (caught inside the manager, never raised);
  - migration resume -> the Migration operator re-drives / re-resumes
                        (StreamError IS its retry trigger).

Failures are counted per path and exported on every /metrics surface as
``dynamo_integrity_failures_total{path}`` via the global-provider hook
(runtime/metrics.py) — a fleet quietly eating checksum failures is a
hardware signal, not noise.
"""

from __future__ import annotations

import threading
import zlib

from dynamo_tpu.runtime.context import StreamError

__all__ = [
    "IntegrityError",
    "corrupt_token_ids",
    "integrity_failure",
    "integrity_snapshot",
    "kv_checksum",
    "token_checksum",
    "verify_checksum",
    "verify_resume_tokens",
]


class IntegrityError(StreamError):
    """A KV payload failed its content checksum on receipt.

    Subclassing StreamError is the design: the migration operator
    retries StreamErrors, the disagg pull path falls back to local
    prefill on them, so corrupt payloads recover through the exact
    machinery worker death already exercises — never decoded."""


_lock = threading.Lock()
_failures: dict[str, int] = {}


def kv_checksum(*parts) -> int:
    """Chained CRC-32 over byte-like parts (bytes, memoryview, numpy
    arrays via their buffer). Zero-copy: numpy blocks hash through a
    flattened memoryview without a tobytes() materialization."""
    crc = 0
    for p in parts:
        if p is None:
            continue
        if isinstance(p, (bytes, bytearray, memoryview)):
            mv = memoryview(p)
        else:
            try:
                # numpy path: C-contiguous blocks expose their buffer;
                # cast to bytes-shape so crc32 accepts it — zero-copy
                mv = memoryview(p).cast("B")
            except TypeError:
                # strided view (non-contiguous slice): one materializing
                # copy, same bytes as its contiguous layout
                mv = memoryview(p.tobytes())
        crc = zlib.crc32(mv, crc)
    return crc & 0xFFFFFFFF


def token_checksum(token_ids) -> int:
    """Checksum over a token-id sequence (migration resume payloads).
    Order- and value-sensitive, independent of list/tuple container."""
    crc = 0
    for t in token_ids or ():
        crc = zlib.crc32(int(t).to_bytes(8, "big", signed=True), crc)
    return crc & 0xFFFFFFFF


def integrity_failure(path: str) -> None:
    """Count one checksum failure on ``path`` (disagg.pull, kvbm.host,
    kvbm.disk, kvbm.remote, migration.resume)."""
    with _lock:
        _failures[path] = _failures.get(path, 0) + 1


def integrity_snapshot() -> dict[str, int]:
    with _lock:
        return dict(_failures)


def verify_checksum(expected, *parts, path: str) -> None:
    """Verify ``parts`` against ``expected``; raise IntegrityError (and
    count the failure) on mismatch. ``expected`` may be None — unstamped
    payloads from an older sender verify trivially (rolling upgrades)."""
    if expected is None:
        return
    actual = kv_checksum(*parts)
    if actual != int(expected):
        integrity_failure(path)
        raise IntegrityError(
            f"KV payload checksum mismatch on {path}: "
            f"expected {int(expected):#010x}, got {actual:#010x}"
        )


def corrupt_token_ids(site: str, token_ids: list, instance=None) -> list:
    """Chaos hook: run a token-id sequence through the ``corrupt`` fault
    at ``site`` (no-op unless a rule is armed; ``instance`` scopes sticky
    per-worker rules). Tokens round-trip through the same 8-byte encoding
    :func:`token_checksum` hashes, so a flipped bit lands in exactly one
    token value."""
    from dynamo_tpu.runtime.faults import FAULTS

    if not FAULTS.enabled or not token_ids:
        return token_ids
    buf = b"".join(
        int(t).to_bytes(8, "big", signed=True) for t in token_ids
    )
    # dynalint: disable=DL006 -- wrapper forwards its caller's literal
    # site (every corrupt_token_ids() call site is catalog-checked)
    flipped = FAULTS.corrupt_bytes(site, buf, instance=instance)
    if flipped is buf:
        return token_ids
    return [
        int.from_bytes(flipped[i : i + 8], "big", signed=True)
        for i in range(0, len(flipped), 8)
    ]


def verify_resume_tokens(request: dict) -> dict:
    """Engine-intake guard for migration resume payloads.

    The migration operator stamps ``token_checksum`` over the resume
    prompt (original + pre-crash tokens). Here — the receiving engine —
    the tokens first pass the ``migration.resume`` corrupt fault (the
    simulated wire), then verify. A mismatch raises IntegrityError, a
    StreamError, so the operator re-drives from its pristine copy
    instead of this engine prefilling a poisoned prompt. Requests
    without the stamp pass through untouched."""
    expected = request.get("token_checksum")
    if expected is None:
        return request
    toks = corrupt_token_ids(
        "migration.resume", list(request.get("token_ids") or [])
    )
    actual = token_checksum(toks)
    if actual != int(expected):
        integrity_failure("migration.resume")
        raise IntegrityError(
            f"resume prompt checksum mismatch: expected "
            f"{int(expected):#010x}, got {actual:#010x}"
        )
    return request


def _exposition() -> str:
    snap = integrity_snapshot()
    if not snap:
        return ""
    lines = [
        "# HELP dynamo_integrity_failures_total KV payload checksum "
        "failures by path (detected corruption, never decoded).",
        "# TYPE dynamo_integrity_failures_total counter",
    ]
    for path, n in sorted(snap.items()):
        lines.append(
            f'dynamo_integrity_failures_total{{path="{path}"}} {n}'
        )
    return "\n".join(lines) + "\n"


def _register_metrics() -> None:
    from dynamo_tpu.runtime import metrics

    metrics.register_global_provider("integrity", _exposition)


_register_metrics()
