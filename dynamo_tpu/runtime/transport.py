"""Request/response data plane: direct TCP streaming to workers.

The reference splits its data plane across NATS (request push) and a
call-home TCP response stream (lib/runtime/src/pipeline/network/). Here both
directions ride one direct TCP connection from client to worker: each worker
process runs a single ``EndpointServer``; all of its endpoints share it,
demultiplexed by endpoint path. Multiple in-flight requests are multiplexed
per connection by request id.

Frames (framing.py msgpack):
  client -> worker: {"kind": "req", "req": id, "path": str, "payload": ..., "headers": {}}
                    {"kind": "cancel", "req": id}
  worker -> client: {"kind": "data", "req": id, "payload": ...}
                    {"kind": "end", "req": id}
                    {"kind": "err", "req": id, "error": str}

In-process instances short-circuit the wire entirely (LocalRegistry), which
is what hermetic tests and single-process deployments use.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Any, AsyncIterator, Awaitable, Callable

from dynamo_tpu.runtime import framing
from dynamo_tpu.runtime.context import (
    Context,
    DeadlineExceeded,
    OverQuota,
    ServiceUnavailable,
    StreamError,
    deadline_from_headers,
    spawn,
)
from dynamo_tpu.runtime.faults import FAULTS

log = logging.getLogger("dynamo.transport")

Handler = Callable[[Any, Context], AsyncIterator[Any]]


class LocalRegistry:
    """Process-local instance registry for zero-copy in-proc dispatch."""

    def __init__(self) -> None:
        self._handlers: dict[str, Handler] = {}

    def register(self, path: str, handler: Handler) -> None:
        self._handlers[path] = handler

    def unregister(self, path: str) -> None:
        self._handlers.pop(path, None)

    def get(self, path: str) -> Handler | None:
        return self._handlers.get(path)


class EndpointServer:
    """Worker-side TCP listener serving all endpoints of one process."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._handlers: dict[str, Handler] = {}
        self._server: asyncio.AbstractServer | None = None
        self._inflight: set[asyncio.Task] = set()
        self._conns: set[asyncio.StreamWriter] = set()
        self.draining = False
        self.drain_retry_after_s = 1.0  # hint sent with draining refusals
        self.aborted_inflight = 0  # streams force-cancelled at drain timeout

    def register(self, path: str, handler: Handler) -> None:
        self._handlers[path] = handler

    def unregister(self, path: str) -> None:
        self._handlers.pop(path, None)

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.host, self.port = self._server.sockets[0].getsockname()[:2]
        return self.host, self.port

    async def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop accepting; optionally wait for in-flight requests to finish.

        Streams that outlive the drain timeout are FORCE-cancelled (and
        counted in ``aborted_inflight``): a wedged handler must not turn a
        graceful drain into an unbounded hang — its client sees a stream
        death and re-drives via migration."""
        self.draining = True
        if self._server is not None:
            self._server.close()
        if drain and self._inflight:
            _done, pending = await asyncio.wait(self._inflight, timeout=timeout)
            if pending:
                self.aborted_inflight += len(pending)
                log.warning(
                    "drain timeout (%.1fs): force-cancelling %d in-flight "
                    "stream(s)", timeout, len(pending),
                )
        leftover = list(self._inflight)
        for t in leftover:
            t.cancel()
        if leftover:
            # give cancellation a moment to actually unwind the handlers
            await asyncio.wait(leftover, timeout=5)
        # Actively close peer connections: from 3.12 Server.wait_closed()
        # blocks until every client connection is gone.
        for w in list(self._conns):
            w.close()
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=5)
            except asyncio.TimeoutError:  # pragma: no cover
                pass

    @property
    def num_inflight(self) -> int:
        return len(self._inflight)

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        write_lock = asyncio.Lock()
        contexts: dict[str, Context] = {}
        self._conns.add(writer)

        async def send(msg: dict[str, Any]) -> None:
            # dynalint: disable=DL009 -- deliberate: frames to one client
            # connection must serialize (interleaving corrupts framing);
            # per-connection scope, bounded by that peer's backpressure
            async with write_lock:
                await framing.write_frame(writer, msg)

        try:
            while True:
                msg = await framing.read_frame(reader)
                if msg is None:
                    break
                kind = msg.get("kind")
                if kind == "req":
                    # Register the context BEFORE scheduling the handler task:
                    # a cancel frame in the same read buffer must find it.
                    headers = msg.get("headers") or {}
                    ctx = Context(
                        request_id=msg["req"], headers=headers,
                        deadline=deadline_from_headers(headers),
                    )
                    # join the caller's W3C trace (runtime/tracing.py)
                    from dynamo_tpu.runtime.tracing import bind_trace

                    bind_trace(ctx.headers)
                    contexts[msg["req"]] = ctx
                    task = asyncio.ensure_future(
                        self._serve_request(msg, ctx, send, contexts)
                    )
                    self._inflight.add(task)
                    task.add_done_callback(self._inflight.discard)
                elif kind == "cancel":
                    ctx = contexts.get(msg["req"])
                    if ctx is not None:
                        ctx.stop_generating()
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            # peer gone: cancel everything it had in flight here
            for ctx in contexts.values():
                ctx.kill()
            self._conns.discard(writer)
            writer.close()

    async def _serve_request(
        self, msg: dict[str, Any], ctx: Context, send, contexts: dict[str, Context]
    ) -> None:
        req_id = msg["req"]
        path = msg.get("path", "")
        handler = self._handlers.get(path)
        if handler is None or self.draining:
            contexts.pop(req_id, None)
            # draining carries a machine-readable code + Retry-After hint:
            # the client raises ServiceUnavailable, migration re-drives on
            # a live worker, and the frontend maps exhaustion to HTTP 503
            err: dict[str, Any] = {"kind": "err", "req": req_id}
            if self.draining:
                err.update(error="draining", code="unavailable",
                           retry_after=self.drain_retry_after_s)
            else:
                err.update(error=f"no handler for {path!r}")
            try:
                await send(err)
            except (ConnectionError, RuntimeError):
                pass
            return
        try:
            async for item in handler(msg.get("payload"), ctx):
                if ctx.is_killed:
                    break
                await send({"kind": "data", "req": req_id, "payload": item})
            if not ctx.is_killed:
                await send({"kind": "end", "req": req_id})
        except (ConnectionResetError, BrokenPipeError):
            ctx.kill()
        except asyncio.CancelledError:
            ctx.kill()
            raise
        except ServiceUnavailable as e:
            # typed refusal (draining/saturated handler): ship the code so
            # the client side re-raises ServiceUnavailable, not a generic
            # RuntimeError — that's what makes it retryable + 503-mappable
            try:
                await send({"kind": "err", "req": req_id, "error": str(e),
                            "code": "unavailable",
                            "retry_after": e.retry_after_s})
            except (ConnectionError, RuntimeError):
                pass
        except OverQuota as e:
            # tenant quota refusal: typed so the client side re-raises
            # OverQuota (NOT retryable — migration must not burn the
            # tenant's bucket on every other worker too) and the
            # frontend maps it to 429 + Retry-After
            try:
                await send({"kind": "err", "req": req_id, "error": str(e),
                            "code": "over_quota",
                            "retry_after": e.retry_after_s})
            except (ConnectionError, RuntimeError):
                pass
        except DeadlineExceeded as e:
            try:
                await send({"kind": "err", "req": req_id, "error": str(e),
                            "code": "deadline"})
            except (ConnectionError, RuntimeError):
                pass
        except Exception as e:  # noqa: BLE001 - report handler errors to the peer
            log.exception("handler error on %s", path)
            try:
                await send({"kind": "err", "req": req_id, "error": repr(e)})
            except (ConnectionError, RuntimeError):
                pass
        finally:
            contexts.pop(req_id, None)


class InstanceChannel:
    """Client-side multiplexed connection to one worker instance."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._queues: dict[str, asyncio.Queue] = {}
        self._rx: asyncio.Task | None = None
        self._lock = asyncio.Lock()
        self._closed = False

    async def connect(self, timeout: float = 5.0) -> None:
        if FAULTS.enabled:
            await FAULTS.fire("transport.connect")  # drop/error -> dial fails
        self._reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), timeout
        )
        self._rx = asyncio.get_running_loop().create_task(self._rx_loop())

    @property
    def connected(self) -> bool:
        return self._writer is not None and not self._closed

    async def _rx_loop(self) -> None:
        assert self._reader is not None
        while True:
            msg = await framing.read_frame(self._reader)
            if msg is None:
                break
            if FAULTS.enabled:
                try:
                    await FAULTS.fire("transport.recv")
                except (ConnectionError, RuntimeError):
                    # injected drop OR error: die exactly like a cut
                    # connection — close the socket so both sides see a
                    # real death; falling out of the loop marks the
                    # channel closed and delivers the death sentinels
                    if self._writer is not None:
                        self._writer.close()
                    break
            q = self._queues.get(msg.get("req"))
            if q is not None:
                q.put_nowait(msg)
        self._closed = True
        for q in self._queues.values():
            q.put_nowait(None)  # stream death sentinel

    async def call(
        self, path: str, payload: Any, context: Context
    ) -> AsyncIterator[Any]:
        """Issue a request; yields response payloads; raises StreamError on
        mid-stream connection death (the migration trigger)."""
        if not self.connected:
            raise StreamError(f"not connected to {self.host}:{self.port}")
        if context.deadline_expired:
            raise DeadlineExceeded(
                f"deadline passed before dispatch of {context.id}"
            )
        req_id = context.id or uuid.uuid4().hex
        q: asyncio.Queue = asyncio.Queue()
        self._queues[req_id] = q
        try:
            if FAULTS.enabled:
                await FAULTS.fire("transport.send")  # drop -> StreamError
            # dynalint: disable=DL009 -- deliberate: request frames on one
            # worker channel must serialize (interleaving corrupts
            # framing); bounded by that worker's socket backpressure
            async with self._lock:
                await framing.write_frame(
                    self._writer,
                    {
                        "kind": "req",
                        "req": req_id,
                        "path": path,
                        "payload": payload,
                        # remaining deadline budget + the live trace
                        # context ride the headers (context.wire_headers
                        # stamps the sender's current span)
                        "headers": context.wire_headers(),
                    },
                )
        except (ConnectionError, RuntimeError) as e:
            self._queues.pop(req_id, None)
            raise StreamError(f"send failed: {e}") from e

        cancel_task = asyncio.ensure_future(self._watch_cancel(req_id, context))
        finished = False
        try:
            while True:
                msg = await q.get()
                if msg is None:
                    finished = True
                    raise StreamError("response stream died (worker lost)")
                kind = msg["kind"]
                if kind == "data":
                    yield msg["payload"]
                elif kind == "end":
                    finished = True
                    return
                elif kind == "err":
                    finished = True
                    code = msg.get("code")
                    if code == "unavailable":
                        raise ServiceUnavailable(
                            msg.get("error", "worker unavailable"),
                            retry_after_s=float(msg.get("retry_after") or 1.0),
                        )
                    if code == "over_quota":
                        raise OverQuota(
                            msg.get("error", "tenant over quota"),
                            retry_after_s=float(msg.get("retry_after") or 1.0),
                        )
                    if code == "deadline":
                        raise DeadlineExceeded(
                            msg.get("error", "deadline exceeded")
                        )
                    raise RuntimeError(msg.get("error", "remote error"))
        finally:
            cancel_task.cancel()
            self._queues.pop(req_id, None)
            if not finished:
                # Consumer abandoned the stream (break / exception upstream):
                # tell the worker to stop generating. Fire-and-forget - we may
                # be inside GeneratorExit where awaiting is restricted; spawn
                # keeps the strong reference so GC can't cancel the send.
                spawn(self._send_cancel(req_id), name="transport-cancel")

    async def _watch_cancel(self, req_id: str, context: Context) -> None:
        await context.stopped()
        await self._send_cancel(req_id)

    async def _send_cancel(self, req_id: str) -> None:
        if self.connected:
            try:
                # dynalint: disable=DL009 -- deliberate: cancel frames ride
                # the same serialized channel as the requests they cancel
                async with self._lock:
                    await framing.write_frame(
                        self._writer, {"kind": "cancel", "req": req_id}
                    )
            except (ConnectionError, RuntimeError):
                pass

    async def close(self) -> None:
        self._closed = True
        if self._rx is not None:
            self._rx.cancel()
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass


async def call_local(
    handler: Handler, payload: Any, context: Context
) -> AsyncIterator[Any]:
    """In-process dispatch path (no serialization)."""
    async for item in handler(payload, context):
        yield item
