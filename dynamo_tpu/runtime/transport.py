"""Request/response data plane: direct TCP streaming to workers.

The reference splits its data plane across NATS (request push) and a
call-home TCP response stream (lib/runtime/src/pipeline/network/). Here both
directions ride one direct TCP connection from client to worker: each worker
process runs a single ``EndpointServer``; all of its endpoints share it,
demultiplexed by endpoint path. Multiple in-flight requests are multiplexed
per connection by a per-connection integer channel id established by the
``open`` handshake (headers and the uuid request id cross the wire once, at
open; every subsequent frame is stamped with the small ``ch`` int instead
of a 32-hex uuid).

Frames (framing.py msgpack):
  client -> worker: {"kind": "open", "ch": n, "req": id, "path": str,
                     "payload": ..., "headers": {}}
                    {"kind": "cancel", "ch": n}
  worker -> client: {"kind": "data", "ch": n, "payload": ...}
                    {"kind": "data", "ch": n, "payloads": [...]}  (coalesced)
                    {"kind": "end", "ch": n}
                    {"kind": "err", "ch": n, "error": str}
  legacy client -> worker: {"kind": "req", "req": id, ...} — served with
                    ``req``-stamped uncoalesced replies for pre-``open``
                    peers during rolling upgrades.

The send path is corked (framing.FrameWriter): frames buffer in user space
and hit the socket once per event-loop tick, draining only on transport
backpressure; adjacent items of one stream coalesce into a single
``payloads`` frame (DYN_STREAM_COALESCE, default on). See README "Stream
plane" and benchmarks/stream_bench.py for the measured effect.

In-process instances short-circuit the wire entirely (LocalRegistry), which
is what hermetic tests and single-process deployments use.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
import uuid
from typing import Any, AsyncIterator, Awaitable, Callable

import msgpack

from dynamo_tpu.runtime import framing
from dynamo_tpu.runtime.context import (
    Context,
    DeadlineExceeded,
    OverQuota,
    ServiceUnavailable,
    StreamError,
    deadline_from_headers,
    spawn,
)
from dynamo_tpu.runtime.faults import FAULTS
from dynamo_tpu.runtime.metrics import MetricsRegistry, register_registry

log = logging.getLogger("dynamo.transport")

Handler = Callable[[Any, Context], AsyncIterator[Any]]


# ------------------------------------------------------------------ knobs

def _env_flag(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    return default if raw is None else int(raw)


# ---------------------------------------------------------------- metrics

_METRICS = MetricsRegistry()
_FRAMES_TOTAL = _METRICS.counter(
    "transport_frames_total",
    "Data-plane frames sent, by frame kind (a coalesced data frame "
    "counts once however many payloads it carries).",
    ["kind"],
)
_FLUSH_BYTES = _METRICS.histogram(
    "transport_flush_bytes",
    "Bytes handed to the transport per corked flush.",
    buckets=(64, 256, 1024, 4096, 16384, 65536, 262144, 1048576),
)
register_registry("transport", _METRICS)

# Plain-int mirror of the counters for the stream bench / tier-1
# micro-guard: resettable and free of prometheus overhead to read.
# ``flushes``/``drains``/``bytes_out`` are fed by framing.FrameWriter.
STREAM_STATS: dict[str, int] = {}


def reset_stream_stats() -> None:
    for k in (
        "frames", "flushes", "drains", "bytes_out", "data_frames",
        "data_items",
    ):
        STREAM_STATS[k] = 0


def stream_stats() -> dict[str, int]:
    return dict(STREAM_STATS)


reset_stream_stats()

# pre-bound label children: .labels() does a dict lookup + lock per call,
# too hot for the per-frame path
_FRAME_KINDS = ("open", "req", "cancel", "data", "end", "err")
_FRAME_COUNTERS = {k: _FRAMES_TOTAL.labels(k) for k in _FRAME_KINDS}


def _note_frame(kind: str) -> None:
    STREAM_STATS["frames"] += 1
    if kind == "data":
        STREAM_STATS["data_frames"] += 1
    _FRAME_COUNTERS[kind].inc()


def _note_flush(nbytes: int) -> None:
    _FLUSH_BYTES.observe(nbytes)


def _frame_writer(writer: asyncio.StreamWriter, cork: bool) -> framing.FrameWriter:
    return framing.FrameWriter(
        writer, cork=cork, stats=STREAM_STATS, on_flush=_note_flush
    )


class LocalRegistry:
    """Process-local instance registry for zero-copy in-proc dispatch."""

    def __init__(self) -> None:
        self._handlers: dict[str, Handler] = {}

    def register(self, path: str, handler: Handler) -> None:
        self._handlers[path] = handler

    def unregister(self, path: str) -> None:
        self._handlers.pop(path, None)

    def get(self, path: str) -> Handler | None:
        return self._handlers.get(path)


def _rough_size(item: Any) -> int:
    """Cheap payload-size estimate for the coalescer's byte cap.

    Not a serialization: just large-blob detection, so a stream of fat
    payloads commits per-frame instead of accumulating max_batch of them
    into one giant frame (which would defeat frame-granular rx bounding
    on the receiver and add head-of-line latency).
    """
    if isinstance(item, (str, bytes, bytearray)):
        return len(item)
    if isinstance(item, dict):
        # one level deep, blobs only — token-delta dicts are small and
        # a full recursive walk per item taxes every send; a fat blob
        # (the thing the cap exists for) lives in a top-level value
        return 16 + sum(
            len(v) for v in item.values()
            if isinstance(v, (str, bytes, bytearray))
        )
    if isinstance(item, (list, tuple)):
        return 8 + 8 * len(item)
    return 8


class _StreamSender:
    """Send half of one response stream.

    With coalescing on, adjacent items buffer and ship as a single
    ``{"kind": "data", "payloads": [...]}`` frame at end-of-tick, at the
    batch cap, or at the byte cap — a decode burst that yields N tokens
    between two event-loop ticks costs one frame, not N. Item order and
    error placement are exact: ``end``/``err`` always commit pending
    items first, into the same corked buffer, so the peer observes the
    identical stream the uncoalesced path would produce.
    """

    __slots__ = ("fw", "reply", "coalesce", "max_batch", "max_bytes",
                 "_pending", "_pending_sz", "_tick_scheduled")

    def __init__(
        self,
        fw: framing.FrameWriter,
        reply: dict[str, Any],
        *,
        coalesce: bool,
        max_batch: int,
        max_bytes: int = 64 * 1024,
    ) -> None:
        self.fw = fw
        self.reply = reply
        self.coalesce = coalesce
        self.max_batch = max_batch
        self.max_bytes = max_bytes
        self._pending: list[Any] = []
        self._pending_sz = 0
        self._tick_scheduled = False

    async def data(self, item: Any) -> None:
        STREAM_STATS["data_items"] += 1
        if not self.coalesce:
            frame = {"kind": "data", "payload": item}
            frame.update(self.reply)
            _note_frame("data")
            await self.fw.send(frame)
            return
        self._pending.append(item)
        self._pending_sz += _rough_size(item)
        if len(self._pending) >= self.max_batch or self._pending_sz >= self.max_bytes:
            self._commit()
            await self.fw.pump()
            return
        if not self._tick_scheduled:
            self._tick_scheduled = True
            asyncio.get_running_loop().call_soon(self._tick)
        # backpressure check rides every item: a stalled peer blocks the
        # handler here instead of ballooning the transport buffer
        await self.fw.pump()

    def _tick(self) -> None:
        self._tick_scheduled = False
        self._commit()

    def _commit(self) -> None:
        pending = self._pending
        if not pending:
            return
        self._pending_sz = 0
        if len(pending) == 1:
            frame = {"kind": "data", "payload": pending[0]}
        else:
            frame = {"kind": "data", "payloads": list(pending)}
        frame.update(self.reply)
        pending.clear()
        _note_frame("data")
        self.fw.feed(frame)

    async def end(self) -> None:
        self._commit()
        frame = {"kind": "end"}
        frame.update(self.reply)
        _note_frame("end")
        await self.fw.send(frame)

    async def err(self, frame: dict[str, Any]) -> None:
        # pending items ship first: the peer sees every item the handler
        # yielded before the failure, then the error — same placement as
        # the uncoalesced path
        self._commit()
        frame.update(self.reply)
        _note_frame("err")
        try:
            await self.fw.send(frame)
        except (ConnectionError, RuntimeError):
            pass


class EndpointServer:
    """Worker-side TCP listener serving all endpoints of one process."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        uds_path: str | None = None,
        coalesce: bool | None = None,
        cork: bool | None = None,
    ):
        self.host = host
        self.port = port
        self.uds_path = uds_path
        self.coalesce = (
            _env_flag("DYN_STREAM_COALESCE", True)
            if coalesce is None else coalesce
        )
        self.cork = _env_flag("DYN_STREAM_CORK", True) if cork is None else cork
        self.coalesce_max = _env_int("DYN_STREAM_COALESCE_MAX", 64)
        self._handlers: dict[str, Handler] = {}
        self._server: asyncio.AbstractServer | None = None
        self._uds_server: asyncio.AbstractServer | None = None
        self._inflight: set[asyncio.Task] = set()
        self._conns: set[asyncio.StreamWriter] = set()
        self.draining = False
        self.drain_retry_after_s = 1.0  # hint sent with draining refusals
        self.aborted_inflight = 0  # streams force-cancelled at drain timeout

    def register(self, path: str, handler: Handler) -> None:
        self._handlers[path] = handler

    def unregister(self, path: str) -> None:
        self._handlers.pop(path, None)

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.host, self.port = self._server.sockets[0].getsockname()[:2]
        if self.uds_path:
            # co-located hop fast path; falls back to TCP-only cleanly
            try:
                self._uds_server = await asyncio.start_unix_server(
                    self._handle, self.uds_path
                )
            except (OSError, NotImplementedError, AttributeError) as e:
                log.warning("UDS listener unavailable (%s): %s", self.uds_path, e)
                self.uds_path = None
        return self.host, self.port

    async def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop accepting; optionally wait for in-flight requests to finish.

        Streams that outlive the drain timeout are FORCE-cancelled (and
        counted in ``aborted_inflight``): a wedged handler must not turn a
        graceful drain into an unbounded hang — its client sees a stream
        death and re-drives via migration."""
        self.draining = True
        if self._server is not None:
            self._server.close()
        if self._uds_server is not None:
            self._uds_server.close()
        if drain and self._inflight:
            _done, pending = await asyncio.wait(self._inflight, timeout=timeout)
            if pending:
                self.aborted_inflight += len(pending)
                log.warning(
                    "drain timeout (%.1fs): force-cancelling %d in-flight "
                    "stream(s)", timeout, len(pending),
                )
        leftover = list(self._inflight)
        for t in leftover:
            t.cancel()
        if leftover:
            # give cancellation a moment to actually unwind the handlers
            await asyncio.wait(leftover, timeout=5)
        # Actively close peer connections: from 3.12 Server.wait_closed()
        # blocks until every client connection is gone.
        for w in list(self._conns):
            w.close()
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=5)
            except asyncio.TimeoutError:  # pragma: no cover
                pass
        if self.uds_path:
            with contextlib.suppress(OSError):
                os.unlink(self.uds_path)

    @property
    def num_inflight(self) -> int:
        return len(self._inflight)

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        fw = _frame_writer(writer, self.cork)
        # streams keyed by int channel id ("open") or uuid req id (legacy
        # "req"); the two cannot collide (int vs str)
        contexts: dict[Any, Context] = {}
        self._conns.add(writer)

        try:
            # chunked rx: one socket read drains every frame the peer's
            # corked writer packed into the segment (framing.FrameFeeder)
            feeder = framing.FrameFeeder()
            while True:
                chunk = await reader.read(65536)
                if not chunk:
                    break
                for msg, _nbytes in feeder.feed(chunk):
                    if not isinstance(msg, dict):
                        raise ValueError(
                            f"bad frame type {type(msg).__name__}"
                        )
                    self._handle_frame(msg, fw, contexts)
        except (ConnectionResetError, BrokenPipeError):
            pass
        except (ValueError, TypeError, KeyError,
                msgpack.exceptions.UnpackException) as e:
            # torn length header, oversize frame, garbage bytes, or a
            # malformed envelope: length-prefixed framing cannot resync
            # mid-stream, so drop THIS connection — the accept loop stays
            # up and well-formed peers are unaffected
            log.warning("dropping connection with bad framing: %r", e)
        finally:
            # peer gone: cancel everything it had in flight here
            for ctx in contexts.values():
                ctx.kill()
            self._conns.discard(writer)
            writer.close()

    def _handle_frame(
        self,
        msg: dict[str, Any],
        fw: framing.FrameWriter,
        contexts: dict[Any, Context],
    ) -> None:
        kind = msg.get("kind")
        if kind == "open" or kind == "req":
            key = msg["ch"] if kind == "open" else msg["req"]
            # Register the context BEFORE scheduling the handler task:
            # a cancel frame in the same read buffer must find it.
            headers = msg.get("headers") or {}
            ctx = Context(
                request_id=msg["req"], headers=headers,
                deadline=deadline_from_headers(headers),
            )
            # join the caller's W3C trace (runtime/tracing.py)
            from dynamo_tpu.runtime.tracing import bind_trace

            bind_trace(ctx.headers)
            contexts[key] = ctx
            task = asyncio.ensure_future(
                self._serve_request(msg, ctx, fw, contexts, key)
            )
            self._inflight.add(task)
            task.add_done_callback(self._inflight.discard)
        elif kind == "cancel":
            key = msg["ch"] if "ch" in msg else msg.get("req")
            ctx = contexts.get(key)
            if ctx is not None:
                ctx.stop_generating()

    async def _serve_request(
        self,
        msg: dict[str, Any],
        ctx: Context,
        fw: framing.FrameWriter,
        contexts: dict[Any, Context],
        key: Any,
    ) -> None:
        path = msg.get("path", "")
        # legacy "req" peers get req-stamped, uncoalesced replies (they
        # predate the payloads fan-out)
        legacy = msg.get("kind") == "req"
        reply: dict[str, Any] = {"req": key} if legacy else {"ch": key}
        handler = self._handlers.get(path)
        if handler is None or self.draining:
            contexts.pop(key, None)
            # draining carries a machine-readable code + Retry-After hint:
            # the client raises ServiceUnavailable, migration re-drives on
            # a live worker, and the frontend maps exhaustion to HTTP 503
            err: dict[str, Any] = {"kind": "err"}
            err.update(reply)
            if self.draining:
                err.update(error="draining", code="unavailable",
                           retry_after=self.drain_retry_after_s)
            else:
                err.update(error=f"no handler for {path!r}")
            _note_frame("err")
            try:
                await fw.send(err)
            except (ConnectionError, RuntimeError):
                pass
            return
        out = _StreamSender(
            fw, reply,
            coalesce=self.coalesce and not legacy,
            max_batch=self.coalesce_max,
        )
        try:
            async for item in handler(msg.get("payload"), ctx):
                if ctx.is_killed:
                    break
                await out.data(item)
            if not ctx.is_killed:
                await out.end()
        except (ConnectionResetError, BrokenPipeError):
            ctx.kill()
        except asyncio.CancelledError:
            ctx.kill()
            raise
        except ServiceUnavailable as e:
            # typed refusal (draining/saturated handler): ship the code so
            # the client side re-raises ServiceUnavailable, not a generic
            # RuntimeError — that's what makes it retryable + 503-mappable
            await out.err({"kind": "err", "error": str(e),
                           "code": "unavailable",
                           "retry_after": e.retry_after_s})
        except OverQuota as e:
            # tenant quota refusal: typed so the client side re-raises
            # OverQuota (NOT retryable — migration must not burn the
            # tenant's bucket on every other worker too) and the
            # frontend maps it to 429 + Retry-After
            await out.err({"kind": "err", "error": str(e),
                           "code": "over_quota",
                           "retry_after": e.retry_after_s})
        except DeadlineExceeded as e:
            await out.err({"kind": "err", "error": str(e),
                           "code": "deadline"})
        except StreamError as e:
            # worker-death-shaped failure raised IN the handler (e.g. a
            # backend losing its engine mid-stream): keep the retryable
            # typing across the wire so the migration operator re-drives
            # it — locally-dispatched handlers already propagate
            # StreamError natively, and the TCP plane must match
            await out.err({"kind": "err", "error": str(e),
                           "code": "stream"})
        except Exception as e:  # noqa: BLE001 - report handler errors to the peer
            log.exception("handler error on %s", path)
            await out.err({"kind": "err", "error": repr(e)})
        finally:
            contexts.pop(key, None)


class _BoundedRx:
    """Per-request rx queue with a byte/item high-water mark.

    The bound is enforced by the channel's rx loop, not the queue: when a
    consumer falls behind, the rx loop parks on ``wait_resume()`` and
    stops reading the socket, so kernel-side TCP backpressure propagates
    to the worker and caps memory on BOTH sides — the old unbounded
    ``asyncio.Queue`` let one stalled SSE consumer balloon the process.
    Death sentinels bypass the bound (they must always be deliverable).
    """

    __slots__ = ("_q", "_bytes", "max_items", "max_bytes", "_resume",
                 "_released")

    def __init__(self, max_items: int, max_bytes: int) -> None:
        self._q: asyncio.Queue = asyncio.Queue()
        self._bytes = 0
        self.max_items = max_items
        self.max_bytes = max_bytes
        self._resume = asyncio.Event()
        self._resume.set()
        self._released = False

    @property
    def saturated(self) -> bool:
        return not self._released and (
            self._q.qsize() >= self.max_items or self._bytes >= self.max_bytes
        )

    def put(self, msg: dict[str, Any], nbytes: int) -> None:
        self._q.put_nowait((msg, nbytes))
        self._bytes += nbytes
        if self.saturated:
            self._resume.clear()

    def put_sentinel(self) -> None:
        self._q.put_nowait((None, 0))
        self._resume.set()

    async def get(self) -> dict[str, Any] | None:
        msg, nbytes = await self._q.get()
        self._bytes -= nbytes
        if not self.saturated:
            self._resume.set()
        return msg

    async def wait_resume(self) -> None:
        await self._resume.wait()

    def release(self) -> None:
        """Consumer is gone: never park the rx loop on this queue again."""
        self._released = True
        self._resume.set()

    def terminal_pending(self) -> bool:
        """True if the stream's terminal frame (end/err/death sentinel)
        is already queued — nothing more will arrive, so an abandoning
        consumer need not send a cancel for it."""
        queue = self._q._queue
        if not queue:
            return False
        msg, _ = queue[-1]
        return msg is None or msg["kind"] in ("end", "err")


class InstanceChannel:
    """Client-side multiplexed connection to one worker instance."""

    def __init__(self, host: str, port: int, uds: str = ""):
        self.host, self.port = host, port
        self.uds = uds
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._fw: framing.FrameWriter | None = None
        self._queues: dict[int, _BoundedRx] = {}
        self._next_ch = 0
        self._rx: asyncio.Task | None = None
        self._closed = False
        self.rx_max_items = _env_int("DYN_STREAM_RX_MAX_ITEMS", 1024)
        self.rx_max_bytes = _env_int("DYN_STREAM_RX_MAX_BYTES", 8 * 1024 * 1024)

    async def connect(self, timeout: float = 5.0) -> None:
        if FAULTS.enabled:
            await FAULTS.fire("transport.connect")  # drop/error -> dial fails
        if self.uds and os.path.exists(self.uds):
            # co-located worker advertised a unix socket; TCP remains the
            # fallback if it races the worker's shutdown/unlink
            try:
                self._reader, self._writer = await asyncio.wait_for(
                    asyncio.open_unix_connection(self.uds), timeout
                )
            except (OSError, NotImplementedError, asyncio.TimeoutError):
                self._reader = self._writer = None
        if self._writer is None:
            self._reader, self._writer = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port), timeout
            )
        self._fw = _frame_writer(self._writer, _env_flag("DYN_STREAM_CORK", True))
        self._rx = asyncio.get_running_loop().create_task(self._rx_loop())

    @property
    def connected(self) -> bool:
        return self._writer is not None and not self._closed

    async def _rx_loop(self) -> None:
        assert self._reader is not None
        try:
            # chunked rx (framing.FrameFeeder): one await per socket
            # read, all frames the peer's corked writer batched into the
            # segment handled synchronously
            feeder = framing.FrameFeeder()
            stop = False
            while not stop:
                chunk = await self._reader.read(65536)
                if not chunk:
                    break
                for msg, nbytes in feeder.feed(chunk):
                    if not isinstance(msg, dict):
                        stop = True
                        break
                    if FAULTS.enabled:
                        try:
                            await FAULTS.fire("transport.recv")
                        except (ConnectionError, RuntimeError):
                            # injected drop OR error: die exactly like a
                            # cut connection — close the socket so both
                            # sides see a real death; falling out of the
                            # loop marks the channel closed and delivers
                            # the death sentinels
                            if self._writer is not None:
                                self._writer.close()
                            stop = True
                            break
                    key = msg["ch"] if "ch" in msg else msg.get("req")
                    q = self._queues.get(key)
                    if q is None:
                        continue
                    q.put(msg, nbytes)
                    if q.saturated:
                        # stop reading the socket until the consumer
                        # catches up: TCP backpressure does the rest
                        # (satellite of the unbounded-queue fix; see
                        # _BoundedRx)
                        await q.wait_resume()
        finally:
            self._closed = True
            for q in self._queues.values():
                q.put_sentinel()  # stream death sentinel

    async def call(
        self, path: str, payload: Any, context: Context
    ) -> AsyncIterator[Any]:
        """Issue a request; yields response payloads; raises StreamError on
        mid-stream connection death (the migration trigger)."""
        if not self.connected:
            raise StreamError(f"not connected to {self.host}:{self.port}")
        if context.deadline_expired:
            raise DeadlineExceeded(
                f"deadline passed before dispatch of {context.id}"
            )
        req_id = context.id or uuid.uuid4().hex
        self._next_ch += 1
        ch_id = self._next_ch
        q = _BoundedRx(self.rx_max_items, self.rx_max_bytes)
        self._queues[ch_id] = q
        try:
            if FAULTS.enabled:
                await FAULTS.fire("transport.send")  # drop -> StreamError
            # corked single-writer send path: feed() appends whole packed
            # frames, so concurrent opens/cancels on this channel cannot
            # interleave mid-frame (the old per-call write lock is gone)
            frame = {
                "kind": "open",
                "ch": ch_id,
                "req": req_id,
                "path": path,
                "payload": payload,
                # remaining deadline budget + the live trace
                # context ride the headers (context.wire_headers
                # stamps the sender's current span)
                "headers": context.wire_headers(),
            }
            _note_frame("open")
            await self._fw.send(frame)
        except (ConnectionError, RuntimeError) as e:
            self._queues.pop(ch_id, None)
            raise StreamError(f"send failed: {e}") from e

        # stop-edge callback instead of a watcher task parked on
        # context.stopped() per call — cancellation is rare, the
        # per-call task was not
        def _on_stop() -> None:
            spawn(self._send_cancel(ch_id), name="transport-cancel")

        context.add_stop_callback(_on_stop)
        finished = False
        try:
            while True:
                msg = await q.get()
                if msg is None:
                    finished = True
                    raise StreamError("response stream died (worker lost)")
                kind = msg["kind"]
                if kind == "data":
                    payloads = msg.get("payloads")
                    if payloads is None:
                        yield msg["payload"]
                    else:
                        # fan a coalesced frame back out, item by item
                        for p in payloads:
                            yield p
                elif kind == "end":
                    finished = True
                    return
                elif kind == "err":
                    finished = True
                    code = msg.get("code")
                    if code == "unavailable":
                        raise ServiceUnavailable(
                            msg.get("error", "worker unavailable"),
                            retry_after_s=float(msg.get("retry_after") or 1.0),
                        )
                    if code == "over_quota":
                        raise OverQuota(
                            msg.get("error", "tenant over quota"),
                            retry_after_s=float(msg.get("retry_after") or 1.0),
                        )
                    if code == "deadline":
                        raise DeadlineExceeded(
                            msg.get("error", "deadline exceeded")
                        )
                    if code == "stream":
                        # handler-raised StreamError: retryable (the
                        # migration operator re-drives it elsewhere)
                        raise StreamError(
                            msg.get("error", "worker stream failed")
                        )
                    raise RuntimeError(msg.get("error", "remote error"))
        finally:
            context.remove_stop_callback(_on_stop)
            self._queues.pop(ch_id, None)
            q.release()  # never park the rx loop on an abandoned stream
            if not finished and not q.terminal_pending():
                # Consumer abandoned the stream (break / exception upstream):
                # tell the worker to stop generating. Fire-and-forget - we may
                # be inside GeneratorExit where awaiting is restricted; spawn
                # keeps the strong reference so GC can't cancel the send.
                # (If the terminal frame is already queued there is nothing
                # left to cancel — common when a consumer stops at the
                # finish-reason item with the end frame one read behind.)
                spawn(self._send_cancel(ch_id), name="transport-cancel")

    async def _send_cancel(self, ch_id: int) -> None:
        if self.connected:
            try:
                frame = {"kind": "cancel", "ch": ch_id}
                _note_frame("cancel")
                await self._fw.send(frame)
            except (ConnectionError, RuntimeError):
                pass

    async def close(self) -> None:
        self._closed = True
        if self._rx is not None:
            self._rx.cancel()
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionError, OSError):
                pass


async def call_local(
    handler: Handler, payload: Any, context: Context
) -> AsyncIterator[Any]:
    """In-process dispatch path (no serialization)."""
    async for item in handler(payload, context):
        yield item
