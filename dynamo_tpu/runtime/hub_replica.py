"""Replicated hub: WAL-shipping followers + deterministic failover.

The hub is the control plane's last single point of failure: EPP picks,
KV-router publishes, worker leases, and planner watches all die with one
process, even though hub_store.py already makes that process durable.
The reference design leans on etcd's replicated keyspace here; this
module gives the self-hosted hub the minimal Raft-shaped slice of that
(Ongaro & Ousterhout: a leader streaming committed log records to
followers that replay them into identical state machines) without the
quorum machinery:

- ONE leader serves writes and streams its committed WAL records (plus a
  snapshot bootstrap at the current state) to followers over the
  existing framed transport (``repl.sync`` → snapshot/append/hb frames);
- followers replay records into their own ``DurableHub`` — persisting
  locally, firing watch/subscribe notifications for their own clients —
  and answer reads while bouncing writes with a ``not_leader`` error
  naming the leader (hub_client.py follows the redirect);
- when a follower sees nothing from the leader for ``lease_s`` (the
  leader lease), the MOST-CAUGHT-UP live replica (highest replication
  epoch, then highest WAL position, ties broken by lowest address)
  promotes itself and bumps the replication epoch; everyone else
  re-syncs to it. Ranking by data before address matters: a crashed
  leader restarting with a wiped data dir must defer to followers that
  still hold the replicated state instead of re-electing itself empty
  and streaming that emptiness over everyone else's copy.

Identity is cluster-wide: a follower's bootstrap snapshot carries the
leader's ``boot_id``, ``wal_seq``, and per-subject seq counters, so
client seq baselines stay valid across a failover. Promotion advances
every subject seq by ``PROMOTION_SEQ_GAP`` so events minted by the new
leader always outrank anything the dead leader's subscribers saw, even
if the follower was a few records behind.

Consistency contract (documented, not hidden): replication is
asynchronous — an acked write that never reached a follower is lost if
the leader dies before shipping it. Publishers cover that window with
at-least-once retries + ``pub_id`` dedup (a retry that lands on the new
leader either re-applies the lost event or is dropped as a duplicate —
never double-counted), which is exactly the contract single-hub
reconnects already had. Follower reads may be a replication beat stale.
Under a full partition the best-ranked live replica on each side could
lead its side (no quorum): run replicas in one failure domain per zone
and size ``lease_s`` above worst-case GC/IO pauses.

Run: ``python -m dynamo_tpu.runtime.hub_replica --port P --peers
h1:p1,h2:p2,h3:p3 --data-dir DIR`` on each replica; point clients at the
full list (``DYN_HUB_ADDRESSES``).
"""

from __future__ import annotations

import argparse
import asyncio
import fnmatch
import logging
import time
import uuid
from collections import OrderedDict, deque
from pathlib import Path
from typing import Any

from dynamo_tpu.runtime import framing
from dynamo_tpu.runtime.hub import WatchEvent, _Lease
from dynamo_tpu.runtime.hub_server import HubServer
from dynamo_tpu.runtime.hub_store import DurableHub

log = logging.getLogger("dynamo.hub")

__all__ = ["ReplicatedHub", "ReplicatedHubServer", "HubReplica", "addr_key"]


def addr_key(addr: str) -> tuple[str, int]:
    """Numeric-port sort key: '10.0.0.1:9000' < '10.0.0.1:10000' must
    hold numerically (lexical comparison would invert it)."""
    host, _, port = addr.rpartition(":")
    try:
        return (host, int(port))
    except ValueError:
        return (addr, 0)


class ReplicatedHub(DurableHub):
    """DurableHub with a replication role: a follower replays the
    leader's records (never reaping leases or accepting direct writes);
    promotion turns it into a leader in place."""

    # added to every per-subject seq on promotion: new-leader events must
    # outrank anything the dead leader minted past our replication cursor
    PROMOTION_SEQ_GAP = 1 << 20

    def __init__(
        self, data_dir: str | Path, *, compact_every: int = 8192,
        fsync: bool | None = None, role: str = "follower",
    ) -> None:
        super().__init__(data_dir, compact_every=compact_every, fsync=fsync)
        self.role = role

    # -- role gating --------------------------------------------------------

    def _ensure_reaper(self) -> None:
        # keepalives are not replicated: only the leader may decide a
        # lease is dead (followers learn expiry from its revoke records)
        if self.role == "leader":
            super()._ensure_reaper()

    def reap_expired(self, now: float | None = None) -> list[int]:
        if self.role != "leader":
            return []
        return super().reap_expired(now)

    def _subject_seq_base(self) -> int:
        # a subject first seen in term E must mint seqs above every seq
        # any earlier term could have minted for it (same <2^20-events-
        # per-subject-per-term assumption the promotion gap makes):
        # subscribers that followed the dead leader keep valid baselines
        # even for subjects the promoted leader never learned
        return self.repl_epoch * self.PROMOTION_SEQ_GAP

    def _lease_snapshot_live(self, lease: Any, now: float) -> bool:
        # a follower's lease deadlines go stale by design (keepalives
        # are not replicated; expiry arrives as the leader's revoke
        # record), so its snapshots must keep every lease — dropping one
        # here would kill a live owner's keepalive after this follower
        # restarts and later promotes
        if self.role != "leader":
            return True
        return super()._lease_snapshot_live(lease, now)

    # -- promotion ----------------------------------------------------------

    def promote(self, epoch: int | None = None) -> int:
        """Become the leader: bump the epoch, reset lease deadlines to a
        full-TTL grace (recovery semantics — live owners keepalive, dead
        owners re-expire), gap the subject seqs, start reaping."""
        if self.role == "leader":
            return self.repl_epoch
        self.role = "leader"
        self.repl_epoch = (
            self.repl_epoch + 1 if epoch is None
            else max(int(epoch), self.repl_epoch + 1)
        )
        self.wal_seq = max(self.wal_seq, self.repl_cursor)
        now = time.monotonic()
        for lease in self._leases.values():
            lease.deadline = now + lease.ttl
        gap = self.PROMOTION_SEQ_GAP
        for subj in list(self._subject_seq):
            self._subject_seq[subj] += gap
        self._log({"op": "promote", "epoch": self.repl_epoch, "gap": gap})
        self._ensure_reaper()
        return self.repl_epoch

    def demote(self) -> None:
        """Step down (a competing leader outranks us); the replica's role
        loop re-syncs to the winner."""
        self.role = "follower"

    # -- follower replay ----------------------------------------------------

    def reset_from_snapshot(
        self, state: dict[str, Any], seq: int, epoch: int
    ) -> None:
        """Adopt a full leader snapshot: replace ALL local state (incl.
        boot_id — identity is cluster-wide), persist it as our own
        snapshot, and surface the change to locally connected watchers as
        synthetic events (puts are idempotent upserts for every consumer;
        keys gone from the new state get deletes)."""
        old_keys = set(self._kv)
        self._kv = {}
        self._key_lease = {}
        self._leases = {}
        self._retained = {}
        self._subject_seq = {}
        self._seen_pub_ids = OrderedDict()
        self._objects = {}
        # the catch-up backlog indexes the OLD seq space; a stale window
        # here could satisfy a peer's repl.sync with wrong records
        self._recent.clear()
        self._restore(state)
        self.repl_cursor = int(seq)
        self.repl_epoch = int(epoch)
        self.store.snapshot(self._state())
        for key in sorted(old_keys - set(self._kv)):
            self._notify(WatchEvent("delete", key))
        for key, value in sorted(self._kv.items()):
            self._notify(WatchEvent("put", key, value))

    async def apply_replicated(self, rec: dict[str, Any], seq: int) -> None:
        """Replay ONE leader WAL record: mutate state exactly as the
        leader did, fire local watch/subscribe notifications, and log the
        record (tagged with the leader seq, ``rsq``) to our own WAL so
        the replication cursor survives a follower restart."""
        seq = int(seq)
        if seq <= self.repl_cursor:
            return  # duplicate delivery (resync overlap)
        op = rec["op"]
        if op == "put":
            key, lid = rec["k"], rec.get("l")
            if lid is not None and lid in self._leases:
                self._leases[lid].keys.add(key)
                self._key_lease[key] = lid
            self._kv[key] = rec["v"]
            self._notify(WatchEvent("put", key, rec["v"]))
        elif op == "del":
            key = rec["k"]
            if self._kv.pop(key, None) is not None:
                lid = self._key_lease.pop(key, None)
                if lid is not None and lid in self._leases:
                    self._leases[lid].keys.discard(key)
                self._notify(WatchEvent("delete", key))
        elif op == "lease":
            lid, ttl = rec["id"], rec["ttl"]
            self._leases[lid] = _Lease(lid, ttl, time.monotonic() + ttl)
            self._next_lease = max(self._next_lease, lid + 1)
        elif op == "revoke":
            lease = self._leases.get(rec["id"])
            if lease is not None:
                self._drop_lease(lease)  # notifies the key deletes
        elif op == "pub":
            subj = rec["s"]
            if self._pub_id_fresh(rec.get("pid")):
                if subj not in self._retained:
                    self._retained[subj] = deque(
                        maxlen=self.RETAIN_PER_SUBJECT
                    )
                sseq = self._subject_seq.get(
                    subj, self._subject_seq_base()
                ) + 1
                self._subject_seq[subj] = sseq
                self._retained[subj].append((sseq, rec["p"]))
                for pattern, q in self._subs:
                    if fnmatch.fnmatchcase(subj, pattern):
                        q.put_nowait((subj, rec["p"], sseq))
        else:
            # purge / obj / objdel / promote: the recovery-replay body is
            # already notification-free and correct here
            self._apply(rec)
        self.repl_cursor = seq
        self._log(dict(rec, rsq=seq))


class ReplicatedHubServer(HubServer):
    """HubServer + replication RPCs; bounces writes while follower."""

    def __init__(
        self, replica: "HubReplica", host: str = "127.0.0.1", port: int = 0
    ):
        super().__init__(host, port, hub=replica.hub)
        self.replica = replica

    def _route(self, op: str) -> dict[str, Any] | None:
        if self.hub.role != "leader" and op in self.WRITE_OPS:
            return {"error": "not_leader", "leader": self.replica.leader_addr}
        return None

    async def _dispatch_repl(
        self, op: str, mid: int, msg: dict[str, Any], send, streams
    ) -> bool:
        hub: ReplicatedHub = self.hub
        if op == "repl.status":
            await send({"id": mid, "ok": True, "result": {
                "role": hub.role, "leader": self.replica.leader_addr,
                "epoch": hub.repl_epoch, "wal_seq": hub.wal_seq,
                "cursor": hub.repl_cursor, "boot_id": hub.boot_id,
                "addr": self.replica.advertise,
                "nonce": self.replica.nonce,
            }})
            return True
        if op == "repl.sync":
            if hub.role != "leader":
                await send({"id": mid, "ok": False, "error": "not_leader",
                            "leader": self.replica.leader_addr})
                return True
            # the follower self-identifies so the leader's logs can name
            # who is tailing (was a stray unread field until dynalint
            # DL007 flagged it)
            log.info(
                "hub replica %s: follower %s syncing from cursor %s",
                self.replica.advertise, msg.get("follower", "<unknown>"),
                msg.get("cursor", 0),
            )
            streams[mid] = asyncio.ensure_future(self._stream_repl(
                mid, int(msg.get("cursor", 0)), int(msg.get("epoch", -1)),
                msg.get("boot"), send,
            ))
            return True
        if op == "repl.append":
            # push-apply one record (admin/tooling path; the normal tail
            # rides the repl.sync stream)
            if hub.role == "leader":
                await send({"id": mid, "ok": False, "error": "is_leader"})
            elif int(msg.get("epoch", -1)) != hub.repl_epoch:
                await send({"id": mid, "ok": False,
                            "error": "epoch_mismatch",
                            "epoch": hub.repl_epoch})
            elif int(msg["seq"]) > hub.repl_cursor + 1:
                await send({"id": mid, "ok": False, "error": "gap",
                            "cursor": hub.repl_cursor})
            else:
                await hub.apply_replicated(msg["rec"], int(msg["seq"]))
                await send({"id": mid, "ok": True,
                            "result": hub.repl_cursor})
            return True
        if op == "repl.promote":
            epoch = hub.promote(msg.get("epoch"))
            self.replica.on_promoted()
            await send({"id": mid, "ok": True, "result": epoch})
            return True
        return False

    async def _stream_repl(
        self, mid: int, cursor: int, epoch: int, boot: str | None, send
    ) -> None:
        hub: ReplicatedHub = self.hub
        # bounded: a follower that stops draining (stalled TCP, wedged
        # process) marks the queue overflowed instead of growing leader
        # memory one record per mutation; the stream then ends and the
        # follower re-syncs from its durable cursor
        q: asyncio.Queue = asyncio.Queue(maxsize=hub.REPL_BACKLOG)
        q.repl_overflowed = False
        hub._repl_listeners.append(q)
        try:
            # listener registration, backlog slice, and snapshot capture
            # form one synchronous block — nothing can be logged between
            # them, so queue + what we send below cover the stream
            # exactly once with no gap and no duplicate
            recent = list(hub._recent)
            oldest = recent[0][0] if recent else hub.wal_seq + 1
            caught_up = (
                boot == hub.boot_id
                and epoch == hub.repl_epoch
                and cursor <= hub.wal_seq
                and cursor >= oldest - 1
            )
            if caught_up:
                for s, r in recent:
                    if s > cursor:
                        await send({"id": mid, "stream": {
                            "kind": "append", "rec": r, "seq": s}})
            else:
                await send({"id": mid, "stream": {
                    "kind": "snapshot", "state": hub._state(),
                    "seq": hub.wal_seq, "epoch": hub.repl_epoch}})
            while not q.repl_overflowed:
                try:
                    s, r = await asyncio.wait_for(
                        q.get(), self.replica.hb_interval_s
                    )
                except asyncio.TimeoutError:
                    if hub.role != "leader":
                        break  # demoted: end stream, follower rediscovers
                    await send({"id": mid, "stream": {
                        "kind": "hb", "seq": hub.wal_seq,
                        "epoch": hub.repl_epoch}})
                    continue
                await send({"id": mid, "stream": {
                    "kind": "append", "rec": r, "seq": s}})
        except asyncio.CancelledError:
            pass
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass
        finally:
            hub._repl_listeners.remove(q)


class HubReplica:
    """One replica: a ReplicatedHub + its server + the role loop
    (discover -> follow -> elect -> lead)."""

    def __init__(
        self, host: str, port: int, peers: list[str] | str,
        data_dir: str | Path, *, advertise: str | None = None,
        lease_s: float = 3.0, hb_interval_s: float | None = None,
        fsync: bool | None = None, compact_every: int = 8192,
    ):
        if isinstance(peers, str):
            peers = peers.split(",")
        self.peers = [p.strip() for p in peers if p.strip()]
        self.host, self.port = host, port
        self.advertise = advertise or f"{host}:{port}"
        self.lease_s = lease_s
        self.hb_interval_s = hb_interval_s or max(lease_s / 6.0, 0.05)
        self.hub = ReplicatedHub(
            data_dir, compact_every=compact_every, fsync=fsync
        )
        self.server = ReplicatedHubServer(self, host, port)
        # per-PROCESS identity for probe self-recognition: boot_id is
        # cluster-wide (followers adopt the leader's) and the advertise
        # string can be spelled differently from the peers list
        # (localhost vs 127.0.0.1), so neither can tell "that status is
        # me" reliably — a replica probing itself as a phantom peer
        # would defer elections to it forever
        self.nonce = uuid.uuid4().hex
        self.leader_addr: str | None = None
        self.stats = {
            "snapshots": 0, "appends": 0, "promotions": 0, "elections": 0,
        }
        self._task: asyncio.Task | None = None
        self._stopping = False
        self._live_peer_stats: list[dict[str, Any]] = []

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> tuple[str, int]:
        host, port = await self.server.start()
        self.host, self.port = host, port
        if self.advertise.endswith(":0"):
            self.advertise = f"{host}:{port}"
        self._task = asyncio.get_running_loop().create_task(
            self._role_loop()
        )
        return host, port

    async def stop(self) -> None:
        self._stopping = True
        if self._task is not None:
            # cancel-with-retry: on 3.10 asyncio.wait_for can swallow a
            # lone cancellation when its inner future completes in the
            # same tick (probes to dead peers complete constantly during
            # teardown, so the race is live here). The stopping flag
            # bounds every loop await to ~lease_s regardless.
            while not self._task.done():
                self._task.cancel()
                try:
                    await asyncio.wait_for(
                        asyncio.shield(self._task), 1.0
                    )
                except asyncio.TimeoutError:
                    continue
                except asyncio.CancelledError:
                    pass
            self._task = None
        await self.server.stop()

    def on_promoted(self) -> None:
        """External promotion (repl.promote RPC) landed on our hub."""
        if self.hub.role == "leader":
            self.leader_addr = self.advertise
            self.stats["promotions"] += 1

    # -- role loop ----------------------------------------------------------

    async def _role_loop(self) -> None:
        try:
            while not self._stopping:
                if self.hub.role == "leader":
                    self.leader_addr = self.advertise
                    await self._lead()
                    continue
                leader = await self._discover()
                if leader is None:
                    await self._elect()
                else:
                    await self._follow(leader)
        except asyncio.CancelledError:
            pass

    async def _probe(
        self, addr: str, timeout: float = 0.75
    ) -> dict[str, Any] | None:
        """repl.status of one peer; None when unreachable (or pre-
        replication: an old hub answers unknown-op, mapped to None)."""
        try:
            host, _, port = addr.rpartition(":")
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host or "127.0.0.1", int(port)),
                timeout,
            )
        except (OSError, asyncio.TimeoutError, ValueError):
            return None
        try:
            await framing.write_frame(
                writer, {"id": 1, "op": "repl.status"}
            )
            msg = await asyncio.wait_for(framing.read_frame(reader), timeout)
            if msg and msg.get("ok"):
                # rank by the address WE dialed (advertise mismatches
                # must not fork the ordering)
                return dict(msg["result"], addr=addr)
        except (OSError, asyncio.TimeoutError, ValueError):
            pass
        finally:
            writer.close()
        return None

    @staticmethod
    def _rank(status: dict[str, Any]) -> tuple:
        """Election sort key (ascending = better): highest epoch, then
        highest WAL position, then lowest address. Data outranks
        address so a wiped-and-restarted replica can never win against
        followers still holding the replicated state."""
        pos = max(int(status.get("wal_seq", 0)), int(status.get("cursor", 0)))
        return (-int(status.get("epoch", 0)), -pos, addr_key(status["addr"]))

    def _self_status(self) -> dict[str, Any]:
        return {
            "addr": self.advertise, "epoch": self.hub.repl_epoch,
            "wal_seq": self.hub.wal_seq, "cursor": self.hub.repl_cursor,
        }

    async def _discover(self) -> str | None:
        """Find the current leader among peers; None = nobody claims it
        (records the live peer statuses for the election)."""
        others = [p for p in self.peers if p != self.advertise]
        statuses = [
            s for s in await asyncio.gather(
                *(self._probe(p) for p in others)
            )
            # nonce, not addr: a peers-list spelling of our own address
            # (localhost vs 127.0.0.1) must not register us as a
            # phantom peer we then defer elections to
            if s and s.get("nonce") != self.nonce
        ]
        leaders = [s for s in statuses if s.get("role") == "leader"]
        self._live_peer_stats = statuses
        if not leaders:
            return None
        best = min(leaders, key=self._rank)
        return best["addr"]

    async def _elect(self) -> None:
        """Leader-lease expired and nobody claims leadership: the
        best-ranked live replica (_rank: epoch, WAL position, address)
        promotes itself; everyone else defers and re-probes (the
        deterministic promotion rule — no votes, no quorum)."""
        self.stats["elections"] += 1
        live = sorted(
            self._live_peer_stats + [self._self_status()], key=self._rank
        )
        if live[0]["addr"] == self.advertise:
            epoch = self.hub.promote()
            self.leader_addr = self.advertise
            self.stats["promotions"] += 1
            log.warning(
                "hub replica %s promoted to leader (epoch %d)",
                self.advertise, epoch,
            )
        else:
            self.leader_addr = None
            await asyncio.sleep(self.hb_interval_s * 2)

    async def _lead(self) -> None:
        """Leader steady state: repl.sync streams are served by the
        server; here we only heal accidental split-brain (a competing
        leader that outranks us per _rank — higher epoch, more data,
        lower address — wins; step down and re-sync to it)."""
        while self.hub.role == "leader" and not self._stopping:
            others = [p for p in self.peers if p != self.advertise]
            statuses = await asyncio.gather(
                *(self._probe(p) for p in others)
            )
            me = self._rank(self._self_status())
            for st in statuses:
                if st and st.get("nonce") == self.nonce:
                    continue  # our own status dialed via an alias
                if st and st.get("role") == "leader":
                    them = self._rank(st)
                    if them < me:
                        log.warning(
                            "hub replica %s stepping down: %s leads at "
                            "epoch %d", self.advertise, st["addr"],
                            st.get("epoch", 0),
                        )
                        self.hub.demote()
                        self.leader_addr = st["addr"]
                        return
            await asyncio.sleep(self.lease_s)

    async def _follow(self, leader: str) -> None:
        """Tail the leader's WAL until it dies (lease expiry), demotes,
        or we get promoted. Returning hands control back to the role
        loop (re-discover / elect)."""
        hub = self.hub
        self.leader_addr = leader
        try:
            host, _, port = leader.rpartition(":")
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host or "127.0.0.1", int(port)),
                2.0,
            )
        except (OSError, asyncio.TimeoutError, ValueError):
            self.leader_addr = None
            await asyncio.sleep(self.hb_interval_s)
            return
        # a demoted split-brain loser holds records past its replication
        # cursor (it led and logged its own writes); an append tail would
        # silently merge that divergence into the winner's history, so
        # request a full snapshot bootstrap instead
        diverged = hub.wal_seq > hub.repl_cursor
        try:
            await framing.write_frame(writer, {
                "id": 1, "op": "repl.sync",
                "cursor": 0 if diverged else hub.repl_cursor,
                "epoch": -1 if diverged else hub.repl_epoch,
                "boot": hub.boot_id, "follower": self.advertise,
            })
            while hub.role != "leader" and not self._stopping:
                try:
                    msg = await asyncio.wait_for(
                        framing.read_frame(reader), self.lease_s
                    )
                except asyncio.TimeoutError:
                    log.warning(
                        "hub replica %s: leader %s silent for %.1fs "
                        "(lease expired)", self.advertise, leader,
                        self.lease_s,
                    )
                    return
                if hub.role == "leader":
                    # promoted while the read was pending: the frame is
                    # from the OLD leader's stream — applying it now
                    # would merge its post-promotion writes into ours
                    return
                if msg is None:
                    return  # connection closed
                if not msg.get("ok", True):
                    if msg.get("error") == "not_leader":
                        self.leader_addr = msg.get("leader")
                    return
                item = msg.get("stream")
                if not item:
                    continue
                kind = item.get("kind")
                if kind == "snapshot":
                    hub.reset_from_snapshot(
                        item["state"], item["seq"], item["epoch"]
                    )
                    self.stats["snapshots"] += 1
                    # adopting a snapshot means locally connected
                    # subscribers missed whatever the snapshot delta
                    # contained; kick them so they re-converge through
                    # the client reconnect path (watch diff re-sync,
                    # replay-subscribe with per-subject seq dedup)
                    self.server.kick_clients()
                elif kind == "append":
                    seq = int(item["seq"])
                    if seq > hub.repl_cursor + 1:
                        log.warning(
                            "hub replica %s: replication gap (cursor %d,"
                            " got %d); resyncing", self.advertise,
                            hub.repl_cursor, seq,
                        )
                        return
                    await hub.apply_replicated(item["rec"], seq)
                    self.stats["appends"] += 1
                # hb: the read itself refreshed the leader lease
        except (ConnectionError, OSError):
            return
        finally:
            writer.close()


async def _amain(args: argparse.Namespace) -> None:
    replica = HubReplica(
        args.host, args.port, args.peers, args.data_dir,
        advertise=args.advertise, lease_s=args.lease_s,
        fsync=True if args.fsync else None,
    )
    host, port = await replica.start()
    print(f"DYNAMO_HUB={host}:{port}", flush=True)
    try:
        await replica.server.serve_forever()
    finally:
        await replica.stop()


def main() -> None:
    parser = argparse.ArgumentParser(
        description="dynamo-tpu replicated hub (one replica process)"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=6650)
    parser.add_argument("--peers", required=True,
                        help="comma-separated replica addresses "
                             "(including this one's advertise address)")
    parser.add_argument("--data-dir", required=True)
    parser.add_argument("--advertise", default=None,
                        help="address peers/clients reach us at "
                             "(default host:port)")
    parser.add_argument("--lease-s", type=float, default=3.0,
                        help="leader lease: silence past this promotes "
                             "a follower")
    parser.add_argument("--fsync", action="store_true",
                        help="fsync every WAL append")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    try:
        asyncio.run(_amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
