"""Replicated hub: WAL-shipping followers + Raft-lite quorum election.

The hub is the control plane's last single point of failure: EPP picks,
KV-router publishes, worker leases, and planner watches all die with one
process, even though hub_store.py already makes that process durable.
The reference design leans on etcd's Raft consensus here; this module
gives the self-hosted hub the Raft-shaped slice of it (Ongaro &
Ousterhout: elected leader, term numbers, majority commit) over the
existing framed transport:

- ONE leader per term serves writes and streams its WAL records (plus a
  snapshot bootstrap at the current state) to followers over the
  existing framed transport (``repl.sync`` -> snapshot/append/hb frames,
  every frame stamped with the leader's term);
- followers replay records into their own ``DurableHub`` — persisting
  locally, firing watch/subscribe notifications for their own clients —
  answer reads while bouncing writes with ``not_leader``, and ACK their
  replication cursor back on the sync connection (``repl.ack``);
- a write is acked to the client only once a STRICT MAJORITY of the
  configured replica set holds it (leader self + floor(n/2) follower
  acks): the committed prefix is on a majority, so any electable leader
  has it — committed writes are linearizable and survive any minority
  failure;
- when a follower hears nothing for the leader lease it campaigns:
  first a PRE-VOTE round (Raft §9.6 — would a majority elect me? no
  term change, so a flapping node cannot inflate terms or depose a
  healthy leader), then a real ``repl.request_vote`` round carrying
  ``(term, wal_seq, boot_id)``. A replica votes AT MOST ONCE per term
  (durably, ``hub.term`` file — a crash cannot double-vote), only for a
  candidate whose WAL is at least as caught up, and refuses candidates
  outright while it hears a live leader (leader stickiness). A strict
  majority of granted votes promotes the candidate; its term becomes
  the FENCING EPOCH stamped on every replicated record and checked by
  followers and by the store's commit hook — a deposed leader's
  in-flight writes are rejected (``HubFenced`` / stale-epoch bounce),
  never replayed.

Ranking by data happens in the vote rule: a crashed leader restarting
with a wiped data dir solicits votes at WAL position 0 and is refused by
every caught-up replica, so it can never re-elect itself empty and
stream that emptiness over everyone else's copy.

Consistency contract: acked writes are on a majority and survive any
minority of failures, including a full partition — the minority side
cannot elect (no quorum of votes) and cannot commit (no quorum of acks;
clients get a retryable ``no_quorum``), so there is never dual-lead
within a term and never a fork in the committed prefix. A deposed
leader's unacked tail (logged locally, never committed) is discarded on
heal via snapshot bootstrap from the winner. Publishers keep their
at-least-once retries + ``pub_id`` dedup, so a write that died with a
``no_quorum`` can be retried against the new leader without
double-counting. With n=2 the majority is 2: either replica failing
halts writes (reads keep serving) — run 3+ replicas for availability.

Identity is cluster-wide: a follower's bootstrap snapshot carries the
leader's ``boot_id``, ``wal_seq``, and per-subject seq counters, so
client seq baselines stay valid across a failover. Promotion advances
every subject seq by ``PROMOTION_SEQ_GAP`` so events minted by the new
leader always outrank anything the dead leader's subscribers saw.

Partition testing rides runtime/faults.py: the ``transport.partition``
site (``transport.partition:drop=A|B`` symmetric, ``A>B`` one-way) cuts
replica links at dial time, kills established sync streams at the next
frame, and drops follower acks — seeded, live-flippable, address-pair
scoped (tests/test_hub_replication.py drives the jepsen-style matrix).

Run: ``python -m dynamo_tpu.runtime.hub_replica --port P --peers
h1:p1,h2:p2,h3:p3 --data-dir DIR`` on each replica; the ``--peers`` list
IS the membership — quorum is computed from it, not from who is alive —
and must spell this replica's ``--advertise`` address identically. Point
clients at the full list (``DYN_HUB_ADDRESSES``).
"""

from __future__ import annotations

import argparse
import asyncio
import fnmatch
import logging
import os
import random
import time
import uuid
from collections import OrderedDict, deque
from pathlib import Path
from typing import Any

from dynamo_tpu.runtime import framing
from dynamo_tpu.runtime.faults import FAULTS
from dynamo_tpu.runtime.hub import NoQuorum, WatchEvent, _Lease
from dynamo_tpu.runtime.hub_server import HubServer
from dynamo_tpu.runtime.hub_store import DurableHub, HubFenced
from dynamo_tpu.runtime.metrics import MetricsRegistry, register_registry

log = logging.getLogger("dynamo.hub")

__all__ = ["ReplicatedHub", "ReplicatedHubServer", "HubReplica", "addr_key"]

# Election observability, appended to every /metrics surface: an alert on
# hub_elections_total churn catches a flapping control plane, and
# hub_term jumping without operator action means leadership is unstable.
_METRICS = MetricsRegistry()
ELECTIONS = _METRICS.counter(
    "hub_elections_total",
    "Hub replica election rounds by outcome.",
    ["outcome"],  # won | lost | pre_lost
)
TERM_GAUGE = _METRICS.gauge(
    "hub_term",
    "Current fencing epoch (election term) per hub replica.",
    ["replica"],
)
register_registry("hub_replica", _METRICS)


def addr_key(addr: str) -> tuple[str, int]:
    """Numeric-port sort key: '10.0.0.1:9000' < '10.0.0.1:10000' must
    hold numerically (lexical comparison would invert it)."""
    host, _, port = addr.rpartition(":")
    try:
        return (host, int(port))
    except ValueError:
        return (addr, 0)


class ReplicatedHub(DurableHub):
    """DurableHub with a replication role and durable election-term
    state: a follower replays the leader's records (never reaping leases
    or accepting direct writes); the commit hook fences writes minted by
    anything that is not the current leader."""

    # added to every per-subject seq on promotion: new-leader events must
    # outrank anything the dead leader minted past our replication cursor
    PROMOTION_SEQ_GAP = 1 << 20

    def __init__(
        self, data_dir: str | Path, *, compact_every: int = 8192,
        fsync: bool | None = None, role: str = "follower",
    ) -> None:
        # set BEFORE super().__init__: recovery replay (incl. the legacy
        # object import) logs records, and the fencing hook must see a
        # replay-permitted follower, not raise on our own recovery
        self.role = role
        self.voted_for: str | None = None
        self._replay_ok = True
        super().__init__(data_dir, compact_every=compact_every, fsync=fsync)
        self._replay_ok = False
        # the term file outranks the snapshot/WAL view of the epoch: a
        # vote granted after the last WAL record must survive restart
        term, voted = self.store.load_term()
        if term > self.repl_epoch:
            self.repl_epoch = term
            self.voted_for = voted
        elif term == self.repl_epoch:
            self.voted_for = voted

    # -- role gating ---------------------------------------------------------

    def _ensure_reaper(self) -> None:
        # keepalives are not replicated: only the leader may decide a
        # lease is dead (followers learn expiry from its revoke records)
        if self.role == "leader":
            super()._ensure_reaper()

    def reap_expired(self, now: float | None = None) -> list[int]:
        if self.role != "leader":
            return []
        return super().reap_expired(now)

    def _subject_seq_base(self) -> int:
        # a subject first seen in term E must mint seqs above every seq
        # any earlier term could have minted for it (same <2^20-events-
        # per-subject-per-term assumption the promotion gap makes):
        # subscribers that followed the dead leader keep valid baselines
        # even for subjects the promoted leader never learned
        return self.repl_epoch * self.PROMOTION_SEQ_GAP

    def _lease_snapshot_live(self, lease: Any, now: float) -> bool:
        # a follower's lease deadlines go stale by design (keepalives
        # are not replicated; expiry arrives as the leader's revoke
        # record), so its snapshots must keep every lease — dropping one
        # here would kill a live owner's keepalive after this follower
        # restarts and later promotes
        if self.role != "leader":
            return True
        return super()._lease_snapshot_live(lease, now)

    # -- fencing at commit time ----------------------------------------------

    def _commit_allowed(self, rec: dict[str, Any]) -> None:
        # hub_store commit hook: a record minted by this hub (not a
        # replicated replay) only commits while we hold the leadership —
        # a deposed leader's in-flight write dies HERE, not in the WAL
        if self.role != "leader" and not self._replay_ok:
            raise HubFenced(
                f"write {rec.get('op')!r} refused: replica role is "
                f"{self.role!r} at term {self.repl_epoch}"
            )

    def _log(self, rec: dict[str, Any]) -> int:
        # stamp the fencing epoch onto every leader-minted record; a
        # replicated replay keeps the minting leader's stamp
        if self.role == "leader" and "e" not in rec:
            rec = dict(rec, e=self.repl_epoch)
        seq = super()._log(rec)
        e = rec.get("e")
        if e is not None:
            self.last_rec_epoch = max(self.last_rec_epoch, int(e))
        return seq

    # -- term state (durable: hub.term) --------------------------------------

    def observe_term(self, term: int) -> bool:
        """Adopt a higher term seen on the wire (vote request, competing
        leader, replication stream): clears the vote, persists, demotes a
        leader — the cluster has moved past its regime. False if ``term``
        is not actually newer."""
        term = int(term)
        if term <= self.repl_epoch:
            return False
        self.repl_epoch = term
        self.voted_for = None
        if self.role == "leader":
            self.role = "follower"
        self.store.save_term(term, None)
        return True

    def record_vote(self, term: int, candidate: str) -> None:
        """Durably vote for ``candidate`` in ``term`` — persisted BEFORE
        the grant leaves this process, so a crash cannot double-vote."""
        term = int(term)
        if term < self.repl_epoch:
            raise ValueError(f"vote for past term {term} < {self.repl_epoch}")
        self.repl_epoch = term
        self.voted_for = candidate
        self.store.save_term(term, candidate)

    # -- promotion -----------------------------------------------------------

    def promote(self, epoch: int | None = None, addr: str | None = None) -> int:
        """Become the leader: adopt the winning term (or bump past the
        current one for the manual lever), reset lease deadlines to a
        full-TTL grace (recovery semantics — live owners keepalive, dead
        owners re-expire), gap the subject seqs, start reaping."""
        if self.role == "leader":
            return self.repl_epoch
        self.role = "leader"
        if epoch is not None and int(epoch) == self.repl_epoch and (
            addr is not None and self.voted_for == addr
        ):
            # the elected path: our durable self-vote already holds this
            # term — leading at it cannot collide with another leader
            pass
        else:
            # manual lever (repl.promote) or unowned term: ALWAYS move
            # strictly past the current term — seizing a term some
            # candidate may already hold a vote quorum for would mint two
            # leaders inside one fencing epoch
            self.repl_epoch = (
                self.repl_epoch + 1 if epoch is None
                else max(int(epoch), self.repl_epoch + 1)
            )
        # the leader's own durable vote for its term: without this, a
        # manually promoted leader (repl.promote bumps the term with
        # voted_for unset) could GRANT a real vote at its own term and
        # elect a second leader beside itself — dual-lead within a term
        if addr is not None:
            self.voted_for = addr
        self.store.save_term(self.repl_epoch, self.voted_for)
        self.wal_seq = max(self.wal_seq, self.repl_cursor)
        now = time.monotonic()
        for lease in self._leases.values():
            lease.deadline = now + lease.ttl
        gap = self.PROMOTION_SEQ_GAP
        for subj in list(self._subject_seq):
            self._subject_seq[subj] += gap
        self._log({
            "op": "promote", "epoch": self.repl_epoch, "gap": gap,
            "addr": addr,
        })
        self._ensure_reaper()
        return self.repl_epoch

    def demote(self) -> None:
        """Step down (a competing leader outranks us); the replica's role
        loop re-syncs to the winner."""
        self.role = "follower"

    # -- follower replay -----------------------------------------------------

    def reset_from_snapshot(
        self, state: dict[str, Any], seq: int, epoch: int
    ) -> None:
        """Adopt a full leader snapshot: replace ALL local state (incl.
        boot_id — identity is cluster-wide), persist it as our own
        snapshot, and surface the change to locally connected watchers as
        synthetic events (puts are idempotent upserts for every consumer;
        keys gone from the new state get deletes)."""
        old_keys = set(self._kv)
        self._kv = {}
        self._key_lease = {}
        self._leases = {}
        self._retained = {}
        self._subject_seq = {}
        self._seen_pub_ids = OrderedDict()
        self._objects = {}
        # the catch-up backlog indexes the OLD seq space; a stale window
        # here could satisfy a peer's repl.sync with wrong records
        self._recent.clear()
        # capture BEFORE _restore: it overwrites repl_epoch with the
        # snapshot's value, so comparing afterwards is always a no-op —
        # and a stale vote silently reinterpreted under the new term
        # would refuse legitimate candidates for a term we never voted in
        old_term = self.repl_epoch
        self._restore(state)
        self.repl_cursor = int(seq)
        self.repl_epoch = max(self.repl_epoch, int(epoch))
        if self.repl_epoch > old_term:
            # adopting a newer regime invalidates whatever vote we held
            self.voted_for = None
            self.store.save_term(self.repl_epoch, None)
        elif self.repl_epoch < old_term:
            # the durable term (possibly carrying our vote) never
            # regresses, even if a stale snapshot slips past the
            # stream-side epoch fence
            self.repl_epoch = old_term
        self.store.snapshot(self._state())
        for key in sorted(old_keys - set(self._kv)):
            self._notify(WatchEvent("delete", key))
        for key, value in sorted(self._kv.items()):
            self._notify(WatchEvent("put", key, value))

    async def apply_replicated(
        self, rec: dict[str, Any], seq: int, epoch: int | None = None
    ) -> None:
        """Replay ONE leader WAL record: mutate state exactly as the
        leader did, fire local watch/subscribe notifications, and log the
        record (tagged with the leader seq, ``rsq``) to our own WAL so
        the replication cursor survives a follower restart. ``epoch`` is
        the fencing check: a record from a deposed regime is refused."""
        seq = int(seq)
        if epoch is not None and int(epoch) < self.repl_epoch:
            raise HubFenced(
                f"replicated record seq {seq} carries stale epoch "
                f"{epoch} < {self.repl_epoch}"
            )
        if seq <= self.repl_cursor:
            return  # duplicate delivery (resync overlap)
        op = rec["op"]
        if op == "put":
            key, lid = rec["k"], rec.get("l")
            if lid is not None and lid in self._leases:
                self._leases[lid].keys.add(key)
                self._key_lease[key] = lid
            self._kv[key] = rec["v"]
            self._notify(WatchEvent("put", key, rec["v"]))
        elif op == "del":
            key = rec["k"]
            if self._kv.pop(key, None) is not None:
                lid = self._key_lease.pop(key, None)
                if lid is not None and lid in self._leases:
                    self._leases[lid].keys.discard(key)
                self._notify(WatchEvent("delete", key))
        elif op == "lease":
            lid, ttl = rec["id"], rec["ttl"]
            self._leases[lid] = _Lease(lid, ttl, time.monotonic() + ttl)
            self._next_lease = max(self._next_lease, lid + 1)
        elif op == "revoke":
            lease = self._leases.get(rec["id"])
            if lease is not None:
                self._drop_lease(lease)  # notifies the key deletes
        elif op == "pub":
            subj = rec["s"]
            if self._pub_id_fresh(rec.get("pid")):
                if subj not in self._retained:
                    self._retained[subj] = deque(
                        maxlen=self.RETAIN_PER_SUBJECT
                    )
                sseq = self._subject_seq.get(
                    subj, self._subject_seq_base()
                ) + 1
                self._subject_seq[subj] = sseq
                self._retained[subj].append((sseq, rec["p"]))
                for pattern, q in self._subs:
                    if fnmatch.fnmatchcase(subj, pattern):
                        q.put_nowait((subj, rec["p"], sseq))
        else:
            # purge / obj / objdel / promote: the recovery-replay body is
            # already notification-free and correct here
            self._apply(rec)
        self.repl_cursor = seq
        self._replay_ok = True
        try:
            self._log(dict(rec, rsq=seq))
        finally:
            self._replay_ok = False


class ReplicatedHubServer(HubServer):
    """HubServer + replication RPCs; bounces writes while follower and
    gates write acks on the majority-commit barrier while leader."""

    def __init__(
        self, replica: "HubReplica", host: str = "127.0.0.1", port: int = 0
    ):
        super().__init__(host, port, hub=replica.hub)
        self.replica = replica

    def _route(self, op: str) -> dict[str, Any] | None:
        if self.hub.role != "leader" and op in self.WRITE_OPS:
            return {"error": "not_leader", "leader": self.replica.leader_addr}
        return None

    def _leader_hint(self) -> str | None:
        return self.replica.leader_addr

    def _retry_after_hint(self) -> float | None:
        # quorum loss heals on the election/lease timescale: a partition
        # must first expire the old lease, then a pre-vote + vote round
        # completes within ~a heartbeat of it — so lease_s is the
        # earliest a retry can plausibly commit
        return max(self.replica.lease_s, 0.25)

    async def _commit_barrier(self, seq: int) -> None:
        # ack only once THIS op's records (up to its own post-log
        # position) are on a majority — never the live wal_seq, which
        # would couple the ack to neighbors' later writes
        await self.replica.wait_committed(seq)

    async def _dispatch_repl(
        self, op: str, mid: int, msg: dict[str, Any], send, streams
    ) -> bool:
        hub: ReplicatedHub = self.hub
        if op == "repl.status":
            await send({"id": mid, "ok": True, "result": {
                "role": hub.role, "leader": self.replica.leader_addr,
                "epoch": hub.repl_epoch, "wal_seq": hub.wal_seq,
                "cursor": hub.repl_cursor, "boot_id": hub.boot_id,
                "addr": self.replica.advertise,
                "nonce": self.replica.nonce,
                "commit": self.replica.commit_seq,
            }})
            return True
        if op == "repl.request_vote":
            result = self.replica.on_vote_request(
                term=int(msg["term"]),
                pos=int(msg.get("wal_seq", 0)),
                last_e=int(msg.get("last_e", 0)),
                boot=msg.get("boot"),
                candidate=msg.get("candidate", ""),
                pre=bool(msg.get("pre", False)),
            )
            await send({"id": mid, "ok": True, "result": result})
            return True
        if op == "repl.ack":
            # fire-and-forget: a follower's replication-cursor ack feeding
            # the leader's majority-commit barrier (no response frame —
            # it rides the repl.sync connection between stream frames)
            self.replica.note_ack(
                msg.get("follower", ""), int(msg.get("seq", 0)),
                int(msg.get("term", -1)),
            )
            return True
        if op == "repl.sync":
            if hub.role != "leader":
                await send({"id": mid, "ok": False, "error": "not_leader",
                            "leader": self.replica.leader_addr})
                return True
            # the follower self-identifies: the leader logs who is
            # tailing AND scopes partition checks + acks to that address
            follower = msg.get("follower", "<unknown>")
            log.info(
                "hub replica %s: follower %s syncing from cursor %s",
                self.replica.advertise, follower, msg.get("cursor", 0),
            )
            streams[mid] = asyncio.ensure_future(self._stream_repl(
                mid, int(msg.get("cursor", 0)), int(msg.get("epoch", -1)),
                int(msg.get("last_e", -1)), msg.get("boot"), follower, send,
            ))
            return True
        if op == "repl.append":
            # push-apply one record (admin/tooling path; the normal tail
            # rides the repl.sync stream)
            if hub.role == "leader":
                await send({"id": mid, "ok": False, "error": "is_leader"})
            elif int(msg.get("epoch", -1)) != hub.repl_epoch:
                await send({"id": mid, "ok": False,
                            "error": "epoch_mismatch",
                            "epoch": hub.repl_epoch})
            elif int(msg["seq"]) > hub.repl_cursor + 1:
                await send({"id": mid, "ok": False, "error": "gap",
                            "cursor": hub.repl_cursor})
            else:
                await hub.apply_replicated(msg["rec"], int(msg["seq"]))
                await send({"id": mid, "ok": True,
                            "result": hub.repl_cursor})
            return True
        if op == "repl.promote":
            # manual failover lever — runs a REAL vote round (skipping
            # only the pre-vote) rather than promoting unilaterally: a
            # unilateral term bump could seize the exact term an
            # in-flight candidate already holds a vote quorum for,
            # minting two leaders inside one fencing epoch. The optional
            # ``epoch`` is a floor for the campaign term.
            won = await self.replica.campaign(
                min_term=int(msg.get("epoch") or 0)
            )
            if won:
                await send({"id": mid, "ok": True, "result": hub.repl_epoch})
            else:
                await send({"id": mid, "ok": False, "error": "no_quorum",
                            "epoch": hub.repl_epoch})
            return True
        return False

    async def _stream_repl(
        self, mid: int, cursor: int, epoch: int, last_e: int,
        boot: str | None, follower: str, send,
    ) -> None:
        hub: ReplicatedHub = self.hub
        # bounded: a follower that stops draining (stalled TCP, wedged
        # process) marks the queue overflowed instead of growing leader
        # memory one record per mutation; the stream then ends and the
        # follower re-syncs from its durable cursor
        q: asyncio.Queue = asyncio.Queue(maxsize=hub.REPL_BACKLOG)
        q.repl_overflowed = False
        hub._repl_listeners.append(q)
        try:
            # listener registration, backlog slice, and snapshot capture
            # form one synchronous block — nothing can be logged between
            # them, so queue + what we send below cover the stream
            # exactly once with no gap and no duplicate
            recent = list(hub._recent)
            oldest = recent[0][0] if recent else hub.wal_seq + 1
            # LOG-MATCHING, not just current-term matching: the follower
            # may have adopted our term after replaying a dead leader's
            # uncommitted record at a seq we assigned to a DIFFERENT
            # record — its current epoch looks right while its log is
            # forked. Require the term stamp of OUR record at its cursor
            # to equal the term stamp of ITS last record (raft's
            # prevLogTerm check); any mismatch or out-of-window cursor
            # falls back to a snapshot bootstrap, which truncates the
            # follower's conflicting tail.
            rec_at_cursor = next(
                (r for s, r in recent if s == cursor), None
            )
            lineage_ok = (cursor == 0 and oldest == 1) or (
                rec_at_cursor is not None
                and int(rec_at_cursor.get("e", -1)) == last_e
            )
            caught_up = (
                boot == hub.boot_id
                and epoch == hub.repl_epoch
                and cursor <= hub.wal_seq
                and lineage_ok
            )
            if caught_up:
                for s, r in recent:
                    if s > cursor:
                        await send({"id": mid, "stream": {
                            "kind": "append", "rec": r, "seq": s,
                            "epoch": hub.repl_epoch}})
            else:
                await send({"id": mid, "stream": {
                    "kind": "snapshot", "state": hub._state(),
                    "seq": hub.wal_seq, "epoch": hub.repl_epoch}})
            while not q.repl_overflowed:
                if FAULTS.enabled and FAULTS.link_blocked(
                    "transport.partition", self.replica.advertise, follower
                ):
                    break  # live partition flip: the link to this follower died
                try:
                    s, r = await asyncio.wait_for(
                        q.get(), self.replica.hb_interval_s
                    )
                except asyncio.TimeoutError:
                    if hub.role != "leader":
                        break  # demoted: end stream, follower rediscovers
                    await send({"id": mid, "stream": {
                        "kind": "hb", "seq": hub.wal_seq,
                        "epoch": hub.repl_epoch}})
                    continue
                if hub.role != "leader":
                    break  # deposed with records queued: never stream a dead regime's tail
                await send({"id": mid, "stream": {
                    "kind": "append", "rec": r, "seq": s,
                    "epoch": hub.repl_epoch}})
        except asyncio.CancelledError:
            pass
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass
        finally:
            hub._repl_listeners.remove(q)


class HubReplica:
    """One replica: a ReplicatedHub + its server + the role loop
    (discover -> follow -> campaign -> lead) + the commit quorum."""

    def __init__(
        self, host: str, port: int, peers: list[str] | str,
        data_dir: str | Path, *, advertise: str | None = None,
        lease_s: float = 3.0, hb_interval_s: float | None = None,
        fsync: bool | None = None, compact_every: int = 8192,
        commit_timeout_s: float | None = None,
    ):
        if isinstance(peers, str):
            peers = peers.split(",")
        self.peers = [p.strip() for p in peers if p.strip()]
        self.host, self.port = host, port
        self.advertise = advertise or f"{host}:{port}"
        self.lease_s = lease_s
        self.hb_interval_s = hb_interval_s or max(lease_s / 6.0, 0.05)
        self.commit_timeout_s = commit_timeout_s or max(2.0, lease_s * 4)
        self.hub = ReplicatedHub(
            data_dir, compact_every=compact_every, fsync=fsync
        )
        self.server = ReplicatedHubServer(self, host, port)
        # per-PROCESS identity for probe self-recognition: boot_id is
        # cluster-wide (followers adopt the leader's) and the advertise
        # string can be spelled differently from the peers list
        # (localhost vs 127.0.0.1), so neither can tell "that status is
        # me" reliably — a replica probing itself as a phantom peer
        # would defer elections to it forever
        self.nonce = uuid.uuid4().hex
        self.leader_addr: str | None = None
        self.stats = {
            "snapshots": 0, "appends": 0, "promotions": 0, "elections": 0,
        }
        # commit quorum (leader side): highest acked cursor per follower,
        # and the resulting committed seq (on leader self + floor(n/2)
        # followers). The event is REPLACED on every ack, never cleared —
        # waiters grab it before re-checking, so no wakeup is ever lost.
        self.commit_seq = 0
        self._ack_seq: dict[str, int] = {}
        self._ack_event: asyncio.Event = asyncio.Event()
        self._warned_non_members: set[str] = set()
        self._member_cache: frozenset[str] = frozenset()
        self._members_for: str | None = None
        # election timer: last time we heard a CURRENT-term leader (frame
        # on the sync stream, discovery hit, or a vote we granted)
        self._last_leader_seen = 0.0
        self._task: asyncio.Task | None = None
        self._stopping = False

    # -- membership ----------------------------------------------------------

    @property
    def member_set(self) -> frozenset[str]:
        """The CONFIGURED membership (peers + self): quorum is computed
        from this set, never from who happens to be alive — that is the
        difference between surviving a partition and splitting on one.
        Cached per advertise spelling (finalized in start() for :0
        ports): the commit path consults it once per follower ack."""
        if self._members_for != self.advertise:
            self._member_cache = frozenset(self.peers) | {self.advertise}
            self._members_for = self.advertise
        return self._member_cache

    @property
    def replica_set(self) -> list[str]:
        return sorted(self.member_set, key=addr_key)

    @property
    def majority(self) -> int:
        return len(self.member_set) // 2 + 1

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> tuple[str, int]:
        host, port = await self.server.start()
        self.host, self.port = host, port
        if self.advertise.endswith(":0"):
            self.advertise = f"{host}:{port}"
        self._note_term()
        self._task = asyncio.get_running_loop().create_task(
            self._role_loop()
        )
        return host, port

    async def stop(self) -> None:
        self._stopping = True
        if self._task is not None:
            # cancel-with-retry: on 3.10 asyncio.wait_for can swallow a
            # lone cancellation when its inner future completes in the
            # same tick (probes to dead peers complete constantly during
            # teardown, so the race is live here). The stopping flag
            # bounds every loop await to ~lease_s regardless.
            while not self._task.done():
                self._task.cancel()
                try:
                    await asyncio.wait_for(
                        asyncio.shield(self._task), 1.0
                    )
                except asyncio.TimeoutError:
                    continue
                except asyncio.CancelledError:
                    pass
            self._task = None
        await self.server.stop()

    def on_promoted(self) -> None:
        """External promotion (repl.promote RPC) landed on our hub."""
        if self.hub.role == "leader":
            self.leader_addr = self.advertise
            self._ack_seq = {}
            self.stats["promotions"] += 1
            self._note_term()

    def _note_term(self) -> None:
        TERM_GAUGE.labels(self.advertise).set(self.hub.repl_epoch)

    # -- commit quorum (leader side) -----------------------------------------

    def note_ack(self, follower: str, seq: int, term: int) -> None:
        """A follower acked its replication cursor (``repl.ack``). Only
        current-term acks from MEMBERS of the configured replica set
        count: a partitioned-away follower still acking a dead regime, or
        a non-member (wrong --peers, advertise spelled differently from
        the membership list), must not advance the commit point — the
        majority contract is over the configured set, and a quorum padded
        with non-members could lose acked writes to a real election."""
        if not follower or self.hub.role != "leader":
            return
        if term != self.hub.repl_epoch:
            return
        if follower not in self.member_set or follower == self.advertise:
            if follower not in self._warned_non_members:
                # once per address: acks arrive at full replication rate
                self._warned_non_members.add(follower)
                log.warning(
                    "hub replica %s: ignoring repl.ack from non-member %s "
                    "(check --peers/--advertise spelling)",
                    self.advertise, follower,
                )
            return
        if seq <= self._ack_seq.get(follower, 0):
            return
        self._ack_seq[follower] = seq
        need = self.majority - 1
        if need > 0:
            acked = sorted(self._ack_seq.values(), reverse=True)
            if len(acked) >= need:
                # the need-th highest follower ack is on (need) followers
                # + the leader itself = a strict majority
                self.commit_seq = max(
                    self.commit_seq, min(self.hub.wal_seq, acked[need - 1])
                )
        ev, self._ack_event = self._ack_event, asyncio.Event()
        ev.set()

    async def wait_committed(self, seq: int) -> None:
        """Block until WAL position ``seq`` is on a strict majority of
        the replica set (leader + floor(n/2) follower acks). Raises
        NoQuorum on leadership loss, term change, or timeout — the write
        is then NOT committed and may be discarded on heal."""
        hub = self.hub
        term = hub.repl_epoch
        if self.majority <= 1:
            self.commit_seq = max(self.commit_seq, hub.wal_seq)
            return
        deadline = time.monotonic() + self.commit_timeout_s
        while True:
            if hub.role != "leader" or hub.repl_epoch != term:
                raise NoQuorum(
                    "leadership lost before the write reached a majority"
                )
            if self.commit_seq >= seq:
                return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise NoQuorum(
                    f"no majority ack for wal seq {seq} within "
                    f"{self.commit_timeout_s:.1f}s"
                )
            ev = self._ack_event
            try:
                await asyncio.wait_for(
                    ev.wait(), min(remaining, self.hb_interval_s)
                )
            except asyncio.TimeoutError:
                pass  # re-check role/term at heartbeat granularity

    # -- role loop -----------------------------------------------------------

    async def _role_loop(self) -> None:
        try:
            while not self._stopping:
                if self.hub.role == "leader":
                    self.leader_addr = self.advertise
                    await self._lead()
                    continue
                leader = await self._discover()
                if leader is None:
                    await self._elect()
                else:
                    await self._follow(leader)
        except asyncio.CancelledError:
            pass

    def _cut(self, peer: str) -> bool:
        """Request/response to ``peer`` impossible under the active
        partition set (either direction blocked — a framed RPC needs
        both)."""
        if not FAULTS.enabled:
            return False
        return (
            FAULTS.link_blocked("transport.partition", self.advertise, peer)
            or FAULTS.link_blocked("transport.partition", peer, self.advertise)
        )

    def leader_recent(self) -> bool:
        """Heard a current-term leader within the lease (election-timer
        state, also refreshed by granting a vote — raft stickiness)."""
        return (time.monotonic() - self._last_leader_seen) < self.lease_s

    async def _peer_call(
        self, addr: str, op: str, timeout: float = 0.75,
        **fields: Any,
    ) -> dict[str, Any] | None:
        """One framed request/response RPC to a peer replica; None when
        the peer is unreachable, cut by a partition, or errors (a
        pre-replication hub answering unknown-op maps to None too)."""
        if self._cut(addr):
            return None
        try:
            host, _, port = addr.rpartition(":")
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host or "127.0.0.1", int(port)),
                timeout,
            )
        except (OSError, asyncio.TimeoutError, ValueError):
            return None
        try:
            await framing.write_frame(writer, {"id": 1, "op": op, **fields})
            msg = await asyncio.wait_for(framing.read_frame(reader), timeout)
            if msg and msg.get("ok"):
                return msg["result"]
        except (OSError, asyncio.TimeoutError, ValueError):
            pass
        finally:
            writer.close()
        return None

    async def _probe(
        self, addr: str, timeout: float = 0.75
    ) -> dict[str, Any] | None:
        """repl.status of one peer; None when unreachable."""
        result = await self._peer_call(addr, "repl.status", timeout)
        if result is None:
            return None
        # rank by the address WE dialed (advertise mismatches must not
        # fork the ordering)
        return dict(result, addr=addr)

    @staticmethod
    def _rank(status: dict[str, Any]) -> tuple:
        """Competing-leader sort key (ascending = better): highest term,
        then highest WAL position, then lowest address. Used only to heal
        a forced/manual split-brain — elections themselves are decided by
        votes, not ranking."""
        pos = max(int(status.get("wal_seq", 0)), int(status.get("cursor", 0)))
        return (-int(status.get("epoch", 0)), -pos, addr_key(status["addr"]))

    def _self_status(self) -> dict[str, Any]:
        return {
            "addr": self.advertise, "epoch": self.hub.repl_epoch,
            "wal_seq": self.hub.wal_seq, "cursor": self.hub.repl_cursor,
        }

    async def _discover(self) -> str | None:
        """Find the current leader among peers; None = nobody (reachable)
        claims a leadership we could follow."""
        others = [p for p in self.peers if p != self.advertise]
        statuses = [
            s for s in await asyncio.gather(
                *(self._probe(p) for p in others)
            )
            # nonce, not addr: a peers-list spelling of our own address
            # (localhost vs 127.0.0.1) must not register us as a
            # phantom peer we then defer elections to
            if s and s.get("nonce") != self.nonce
        ]
        leaders = [
            s for s in statuses
            if s.get("role") == "leader"
            # never follow a leader of a term we have moved past: its
            # stream is fenced anyway, and treating it as live would
            # suppress the election that heals the cluster
            and int(s.get("epoch", 0)) >= self.hub.repl_epoch
        ]
        if not leaders:
            return None
        best = min(leaders, key=self._rank)
        self._last_leader_seen = time.monotonic()
        return best["addr"]

    # -- election (pre-vote + quorum vote) -----------------------------------

    async def _request_vote(
        self, addr: str, term: int, pos: int, pre: bool,
        timeout: float = 0.75,
    ) -> dict[str, Any] | None:
        """One ``repl.request_vote`` RPC; None when unreachable or cut."""
        return await self._peer_call(
            addr, "repl.request_vote", timeout,
            term=term, wal_seq=pos, last_e=self.hub.last_rec_epoch,
            boot=self.hub.boot_id, candidate=self.advertise, pre=pre,
        )

    def on_vote_request(
        self, *, term: int, pos: int, last_e: int = 0,
        boot: str | None, candidate: str, pre: bool,
    ) -> dict[str, Any]:
        """Voter side. Pre-vote: would we grant, with NO state change —
        a flapping candidate cannot inflate terms through us. Real vote:
        at most one durable grant per term, only for a candidate whose
        log is at least as up to date as ours, refused while we hear a
        live leader. 'Up to date' is the raft election restriction —
        (last record term, position), in that order: a deposed minority
        leader can pad its WAL arbitrarily long with no-quorum writes,
        but they are stamped with its dead term, so a shorter log holding
        a newer term's committed records still outranks it."""
        hub = self.hub
        mypos = max(hub.wal_seq, hub.repl_cursor)
        caught_up = (last_e, pos) >= (hub.last_rec_epoch, mypos)
        if pre:
            granted = (
                term > hub.repl_epoch
                and caught_up
                and hub.role != "leader"
                and not self.leader_recent()
            )
            return {"granted": granted, "term": hub.repl_epoch, "pre": True}
        if term < hub.repl_epoch:
            return {"granted": False, "term": hub.repl_epoch}
        if term > hub.repl_epoch:
            was_leader = hub.role == "leader"
            hub.observe_term(term)
            if was_leader:
                # a real vote round only starts after a pre-vote majority
                # saw us dead: we lost quorum, step aside
                self.leader_addr = None
            self._note_term()
        if hub.role == "leader":
            # we ARE the leader of this term (term == repl_epoch here):
            # never endorse a second leader beside ourselves
            return {"granted": False, "term": hub.repl_epoch}
        granted = hub.voted_for in (None, candidate) and caught_up
        if granted:
            hub.record_vote(term, candidate)
            # granting resets our election timer: don't immediately
            # campaign against the candidate we just endorsed
            self._last_leader_seen = time.monotonic()
        log.info(
            "hub replica %s: vote request from %s (term %d, pos %d, "
            "boot %s) -> %s", self.advertise, candidate, term, pos,
            boot, "granted" if granted else "refused",
        )
        return {"granted": granted, "term": hub.repl_epoch}

    async def _elect(self) -> None:
        """Leader lease expired and nobody reachable claims a current
        leadership: campaign. Pre-vote round first (no term change), then
        a durable self-vote + real round; a strict majority of the
        CONFIGURED replica set promotes us at the new term."""
        hub = self.hub
        self.stats["elections"] += 1
        others = [p for p in self.replica_set if p != self.advertise]
        pos = max(hub.wal_seq, hub.repl_cursor)
        term = hub.repl_epoch + 1
        if others:
            pre = [r for r in await asyncio.gather(
                *(self._request_vote(p, term, pos, True) for p in others)
            ) if r]
            for r in pre:
                if int(r.get("term", 0)) > hub.repl_epoch:
                    hub.observe_term(int(r["term"]))
                    self._note_term()
            if 1 + sum(1 for r in pre if r.get("granted")) < self.majority:
                ELECTIONS.labels("pre_lost").inc()
                await self._election_backoff()
                return
        if self.leader_recent():
            # a leader emerged — or we endorsed another candidate, which
            # refreshes the election timer — while our pre-vote round was
            # in flight: standing down here keeps a slow campaigner from
            # deposing the freshly elected leader one term later
            ELECTIONS.labels("pre_lost").inc()
            await self._election_backoff()
            return
        if await self.campaign():
            ELECTIONS.labels("won").inc()
        else:
            ELECTIONS.labels("lost").inc()
            await self._election_backoff()

    async def campaign(self, min_term: int = 0) -> bool:
        """One real vote round: durable self-vote at the next term (at
        least ``min_term``), then ``repl.request_vote`` to every member;
        a strict majority promotes us. Shared by elections (after a
        pre-vote majority) and by the manual ``repl.promote`` lever —
        because every path acquires the term through at-most-once-per-
        term votes, even a manual promotion racing an in-flight candidate
        cannot mint two leaders inside one fencing epoch."""
        hub = self.hub
        if hub.role == "leader":
            # already leading — bumping our own term here would strand us
            # leading at a term we hold only a self-vote for, colliding
            # with whoever wins the real election at that term
            return True
        others = [p for p in self.replica_set if p != self.advertise]
        pos = max(hub.wal_seq, hub.repl_cursor)
        term = max(hub.repl_epoch + 1, int(min_term))
        hub.record_vote(term, self.advertise)
        self._note_term()
        votes = [r for r in await asyncio.gather(
            *(self._request_vote(p, term, pos, False) for p in others)
        ) if r]
        maxterm = max([term] + [int(r.get("term", 0)) for r in votes])
        if maxterm > term:
            hub.observe_term(maxterm)
            self._note_term()
            return False
        if hub.repl_epoch != term or hub.voted_for != self.advertise:
            # a concurrent higher-term campaign moved us while the round
            # was in flight: our majority (if any) is for a dead term
            return False
        granted = 1 + sum(1 for r in votes if r.get("granted"))
        if granted < self.majority:
            return False
        epoch = hub.promote(term, addr=self.advertise)
        self.on_promoted()  # one home for the promotion bookkeeping
        log.warning(
            "hub replica %s elected leader for term %d (%d/%d votes)",
            self.advertise, epoch, granted, len(self.member_set),
        )
        return True

    async def _election_backoff(self) -> None:
        """Randomized backoff between failed rounds: breaks the symmetric
        split-vote livelock (everyone self-voting forever)."""
        self.leader_addr = None
        await asyncio.sleep(self.hb_interval_s * (0.5 + random.random() * 1.5))

    # -- leading / following -------------------------------------------------

    async def _lead(self) -> None:
        """Leader steady state: repl.sync streams are served by the
        server; here we only heal forced/manual split-brain (a competing
        leader that outranks us per _rank — higher term, more data,
        lower address — wins; step down and re-sync to it). An elected
        competitor always carries a higher term, so this also retires a
        deposed leader that missed its own deposition."""
        while self.hub.role == "leader" and not self._stopping:
            others = [p for p in self.peers if p != self.advertise]
            statuses = await asyncio.gather(
                *(self._probe(p) for p in others)
            )
            me = self._rank(self._self_status())
            for st in statuses:
                if st and st.get("nonce") == self.nonce:
                    continue  # our own status dialed via an alias
                if st and st.get("role") == "leader":
                    them = self._rank(st)
                    if them < me:
                        log.warning(
                            "hub replica %s stepping down: %s leads at "
                            "epoch %d", self.advertise, st["addr"],
                            st.get("epoch", 0),
                        )
                        self.hub.observe_term(int(st.get("epoch", 0)))
                        self.hub.demote()
                        self._note_term()
                        self.leader_addr = st["addr"]
                        return
            await asyncio.sleep(self.lease_s)

    async def _send_ack(self, writer, leader: str) -> None:
        """Report our replication cursor to the leader (feeds its commit
        quorum). Rides the sync connection; a one-way partition that cuts
        our uplink silently eats the ack — exactly a real cut link."""
        if FAULTS.enabled and FAULTS.link_blocked(
            "transport.partition", self.advertise, leader
        ):
            return
        await framing.write_frame(writer, {
            "id": 0, "op": "repl.ack", "seq": self.hub.repl_cursor,
            "follower": self.advertise, "term": self.hub.repl_epoch,
        })

    async def _follow(self, leader: str) -> None:
        """Tail the leader's WAL until it dies (lease expiry), demotes,
        is fenced by a newer term, or we get promoted. Returning hands
        control back to the role loop (re-discover / campaign)."""
        hub = self.hub
        self.leader_addr = leader
        if self._cut(leader):
            self.leader_addr = None
            await asyncio.sleep(self.hb_interval_s)
            return
        try:
            host, _, port = leader.rpartition(":")
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host or "127.0.0.1", int(port)),
                2.0,
            )
        except (OSError, asyncio.TimeoutError, ValueError):
            self.leader_addr = None
            await asyncio.sleep(self.hb_interval_s)
            return
        # a deposed split-brain loser holds records past its replication
        # cursor (it led and logged its own writes); an append tail would
        # silently merge that divergence into the winner's history, so
        # request a full snapshot bootstrap instead
        diverged = hub.wal_seq > hub.repl_cursor
        try:
            await framing.write_frame(writer, {
                "id": 1, "op": "repl.sync",
                "cursor": 0 if diverged else hub.repl_cursor,
                "epoch": -1 if diverged else hub.repl_epoch,
                "last_e": -1 if diverged else hub.last_rec_epoch,
                "boot": hub.boot_id, "follower": self.advertise,
            })
            while hub.role != "leader" and not self._stopping:
                try:
                    msg = await asyncio.wait_for(
                        framing.read_frame(reader), self.lease_s
                    )
                except asyncio.TimeoutError:
                    log.warning(
                        "hub replica %s: leader %s silent for %.1fs "
                        "(lease expired)", self.advertise, leader,
                        self.lease_s,
                    )
                    return
                if hub.role == "leader":
                    # promoted while the read was pending: the frame is
                    # from the OLD leader's stream — applying it now
                    # would merge its post-promotion writes into ours
                    return
                if msg is None:
                    return  # connection closed
                if not msg.get("ok", True):
                    if msg.get("error") == "not_leader":
                        self.leader_addr = msg.get("leader")
                    return
                item = msg.get("stream")
                if not item:
                    continue
                if FAULTS.enabled and FAULTS.link_blocked(
                    "transport.partition", leader, self.advertise
                ):
                    return  # live partition flip: the downlink died under us
                kind = item.get("kind")
                ep = int(item.get("epoch", -1))
                if ep >= 0:
                    if ep < hub.repl_epoch:
                        # fencing: a deposed leader's stream — its frames
                        # must never land after we adopted a newer term
                        log.warning(
                            "hub replica %s: dropping stale-epoch stream "
                            "from %s (epoch %d < term %d)",
                            self.advertise, leader, ep, hub.repl_epoch,
                        )
                        return
                    if ep > hub.repl_epoch:
                        hub.observe_term(ep)
                        self._note_term()
                # only a current-term leader refreshes the election timer
                self._last_leader_seen = time.monotonic()
                if kind == "snapshot":
                    hub.reset_from_snapshot(
                        item["state"], item["seq"], item["epoch"]
                    )
                    self.stats["snapshots"] += 1
                    # adopting a snapshot means locally connected
                    # subscribers missed whatever the snapshot delta
                    # contained; kick them so they re-converge through
                    # the client reconnect path (watch diff re-sync,
                    # replay-subscribe with per-subject seq dedup)
                    self.server.kick_clients()
                    await self._send_ack(writer, leader)
                elif kind == "append":
                    seq = int(item["seq"])
                    if seq > hub.repl_cursor + 1:
                        log.warning(
                            "hub replica %s: replication gap (cursor %d,"
                            " got %d); resyncing", self.advertise,
                            hub.repl_cursor, seq,
                        )
                        return
                    await hub.apply_replicated(
                        item["rec"], seq, epoch=ep if ep >= 0 else None
                    )
                    self.stats["appends"] += 1
                    await self._send_ack(writer, leader)
                # hb: the read itself refreshed the leader lease
        except HubFenced:
            return  # stale-epoch record refused: rediscover the real leader
        except (ConnectionError, OSError):
            return
        finally:
            writer.close()


async def _amain(args: argparse.Namespace) -> None:
    replica = HubReplica(
        args.host, args.port, args.peers, args.data_dir,
        advertise=args.advertise, lease_s=args.lease_s,
        fsync=True if args.fsync else None,
        commit_timeout_s=args.commit_timeout_s,
    )
    host, port = await replica.start()
    print(f"DYNAMO_HUB={host}:{port}", flush=True)
    try:
        await replica.server.serve_forever()
    finally:
        await replica.stop()


def main() -> None:
    parser = argparse.ArgumentParser(
        description="dynamo-tpu replicated hub (one replica process)"
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=6650)
    parser.add_argument("--peers",
                        default=os.environ.get("DYN_HUB_PEERS", ""),
                        help="comma-separated replica addresses — the "
                             "MEMBERSHIP: quorum size is len(peers), and "
                             "this replica's advertise address must "
                             "appear in it spelled identically (env "
                             "DYN_HUB_PEERS)")
    parser.add_argument("--data-dir", required=True)
    parser.add_argument("--advertise", default=None,
                        help="address peers/clients reach us at "
                             "(default host:port)")
    parser.add_argument("--lease-s", type=float, default=3.0,
                        help="leader lease: silence past this starts an "
                             "election")
    parser.add_argument("--commit-timeout-s", type=float, default=None,
                        help="max wait for a write to reach a majority "
                             "before bouncing it as no_quorum (default "
                             "max(2s, 4x lease)")
    parser.add_argument("--fsync", action="store_true",
                        help="fsync every WAL append")
    args = parser.parse_args()
    if not args.peers:
        parser.error("--peers (or DYN_HUB_PEERS) is required")
    logging.basicConfig(level=logging.INFO)
    try:
        asyncio.run(_amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
