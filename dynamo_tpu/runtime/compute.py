"""Compute pool: CPU-bound work off the event loop.

Role of the reference's rayon<->tokio bridge (lib/runtime/src/compute/,
pool.rs:156): tokenization and chat-template rendering are CPU-bound and
must not stall the serving event loop. A bounded thread pool is the Python
analogue (the GIL releases inside HF tokenizers' Rust core, so real
parallelism where it matters)."""

from __future__ import annotations

import asyncio
import functools
import os
from concurrent.futures import ThreadPoolExecutor

__all__ = ["ComputePool"]


class ComputePool:
    def __init__(self, max_workers: int | None = None):
        if max_workers is None:
            max_workers = min(8, (os.cpu_count() or 2))
        self._ex = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="dyn-compute"
        )

    async def run(self, fn, *args, **kwargs):
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._ex, functools.partial(fn, *args, **kwargs)
        )

    def shutdown(self) -> None:
        self._ex.shutdown(wait=False, cancel_futures=True)
