"""TCP server exposing an InMemoryHub to many processes.

Run as ``python -m dynamo_tpu.runtime.hub_server [--port N]`` - this is the
deployment's single coordination process, playing the role etcd + NATS play
for the reference (SURVEY.md section 2.4). Without ``--data-dir`` state is
in-memory (like NATS core); with it the hub is DURABLE (hub_store.py): every
mutation is WAL-logged + periodically snapshotted, and a restarted hub
recovers its full state — model cards, instance keys, leases, retained event
streams with their seq counters, object buckets — the way etcd and JetStream
recover from disk (ref lib/runtime/src/transports/etcd.rs, nats.rs:132-243).

Protocol: framing.py frames. Request: ``{"id": n, "op": str, ...}`` ->
response ``{"id": n, "ok": bool, "result"/"error": ...}``. Streaming ops
(``watch``, ``subscribe``) emit ``{"id": n, "stream": item}`` frames until the
client sends ``{"op": "cancel", "target": n}``.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
from typing import Any

from dynamo_tpu.runtime import framing
from dynamo_tpu.runtime.context import spawn
from dynamo_tpu.runtime.hub import InMemoryHub, KeyExists, NoQuorum
from dynamo_tpu.runtime.hub_store import HubFenced

log = logging.getLogger("dynamo.hub")


class HubServer:
    # ops that mutate hub state — a replicated follower bounces these with
    # a ``not_leader`` error naming the current leader (hub_replica.py)
    WRITE_OPS = frozenset({
        "put", "create", "delete", "grant_lease", "keepalive",
        "revoke_lease", "publish", "purge_subject", "put_object",
        "delete_object",
    })

    def __init__(
        self, host: str = "127.0.0.1", port: int = 0,
        data_dir: str | None = None, *,
        hub: InMemoryHub | None = None, fsync: bool | None = None,
    ):
        if hub is not None:
            self.hub: InMemoryHub = hub
        elif data_dir:
            from dynamo_tpu.runtime.hub_store import DurableHub

            self.hub = DurableHub(data_dir, fsync=fsync)
        else:
            self.hub = InMemoryHub()
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None
        self._conns: set[asyncio.StreamWriter] = set()

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        sock = self._server.sockets[0]
        self.host, self.port = sock.getsockname()[:2]
        # recovered leases must be reaped when their owners stay gone;
        # the reaper normally starts on the first grant_lease, which may
        # never come on a restarted hub serving only old leases
        self.hub._ensure_reaper()
        log.info("hub listening on %s:%d", self.host, self.port)
        return self.host, self.port

    async def serve_forever(self) -> None:
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        # close peer connections: on 3.12+ wait_closed() blocks until every
        # client connection handler has finished.
        for w in list(self._conns):
            w.close()
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=5)
            except asyncio.TimeoutError:  # pragma: no cover
                pass
        await self.hub.close()

    def kick_clients(self) -> None:
        """Close every client connection (clients auto-reconnect). Used
        by a replication follower after adopting a snapshot bootstrap:
        mid-stream subscribers would otherwise silently miss the events
        inside the snapshot gap, while the reconnect path re-syncs
        watches by diff and re-opens replay subscriptions with
        per-subject seq dedup."""
        for w in list(self._conns):
            w.close()

    # -- per-connection ----------------------------------------------------

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        streams: dict[int, asyncio.Task] = {}
        conn_leases: set[int] = set()
        write_lock = asyncio.Lock()
        self._conns.add(writer)

        async def send(msg: dict[str, Any]) -> None:
            # dynalint: disable=DL009 -- deliberate: response/stream frames
            # to ONE client must serialize (interleaving corrupts framing);
            # scope is per-connection, so one slow client only stalls its
            # own dispatch tasks, never other connections
            async with write_lock:
                await framing.write_frame(writer, msg)

        try:
            while True:
                msg = await framing.read_frame(reader)
                if msg is None:
                    break
                # spawn: strong ref + crash logging — a GC'd dispatch task
                # would silently drop the RPC (client hangs to timeout)
                spawn(
                    self._dispatch(msg, send, streams, conn_leases),
                    name="hub-dispatch",
                )
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            for t in streams.values():
                t.cancel()
            # leases are NOT revoked on disconnect: clients may reconnect and
            # keepalive; expiry is governed solely by TTL (like etcd).
            self._conns.discard(writer)
            writer.close()

    async def _dispatch(
        self,
        msg: dict[str, Any],
        send,
        streams: dict[int, asyncio.Task],
        conn_leases: set[int],
    ) -> None:
        op = msg.get("op")
        mid = msg.get("id")
        hub = self.hub
        try:
            bounce = self._route(op)
            if bounce is not None:
                await send({"id": mid, "ok": False, **bounce})
                return
            if await self._dispatch_repl(op, mid, msg, send, streams):
                return
            # WAL position before the op: a replicated leader acks a write
            # only after the records it logged past this point are on a
            # majority (_commit_barrier); ops that logged nothing skip it
            pre_seq = getattr(hub, "wal_seq", 0)
            if op == "put":
                await hub.put(msg["key"], msg["value"], msg.get("lease"))
                result: Any = True
            elif op == "create":
                await hub.create(msg["key"], msg["value"], msg.get("lease"))
                result = True
            elif op == "get":
                result = await hub.get(msg["key"])
            elif op == "delete":
                result = await hub.delete(msg["key"])
            elif op == "get_prefix":
                result = await hub.get_prefix(msg["prefix"])
            elif op == "grant_lease":
                result = await hub.grant_lease(msg["ttl"])
                conn_leases.add(result)
            elif op == "keepalive":
                result = await hub.keepalive(msg["lease"])
            elif op == "revoke_lease":
                await hub.revoke_lease(msg["lease"])
                result = True
            elif op == "publish":
                # pub_id: client idempotency id — a retried publish whose
                # ack was lost dedups instead of minting a duplicate seq;
                # the applied/deduplicated bool is relayed to the client
                result = await hub.publish(
                    msg["subject"], msg["payload"],
                    pub_id=msg.get("pub_id"),
                )
            elif op == "purge_subject":
                result = await hub.purge_subject(
                    msg["subject"], msg.get("keep_last", 0),
                    up_to_seq=msg.get("up_to_seq"),
                )
            elif op == "put_object":
                await hub.put_object(msg["bucket"], msg["name"], msg["data"])
                result = True
            elif op == "get_object":
                result = await hub.get_object(msg["bucket"], msg["name"])
            elif op == "delete_object":
                await hub.delete_object(msg["bucket"], msg["name"])
                result = True
            elif op == "watch":
                streams[mid] = asyncio.ensure_future(
                    self._stream_watch(
                        mid, msg["prefix"], msg.get("initial", True),
                        msg.get("sync", False), send,
                    )
                )
                return  # stream frames only; no immediate ack
            elif op == "boot_id":
                result = await self.hub.get_boot_id()
            elif op == "subscribe":
                streams[mid] = asyncio.ensure_future(
                    self._stream_subscribe(
                        mid, msg["subject"], msg.get("replay", False), send
                    )
                )
                return
            elif op == "cancel":
                t = streams.pop(msg["target"], None)
                if t:
                    t.cancel()
                result = True
            elif op == "ping":
                result = "pong"
            else:
                raise ValueError(f"unknown op {op!r}")
            if op in self.WRITE_OPS:
                # capture the post-op position HERE (no await since the op
                # body finished): waiting on anything later would couple
                # this write's ack to neighbors' replication
                post_seq = getattr(hub, "wal_seq", 0)
                if post_seq > pre_seq:
                    await self._commit_barrier(post_seq)
            await send({"id": mid, "ok": True, "result": result})
        except KeyExists as e:
            await send({"id": mid, "ok": False, "error": "key_exists", "key": str(e)})
        except NoQuorum as e:
            # the write is logged locally but NOT majority-replicated: the
            # client must treat it as not-committed and retry elsewhere.
            # retry_after: the server's own estimate of when quorum can
            # plausibly be back (election/lease scale) — clients honor it
            # before their own jittered exponential backoff.
            log.warning("hub write %r failed commit quorum: %s", op, e)
            bounce: dict[str, Any] = {
                "id": mid, "ok": False, "error": "no_quorum",
            }
            hint = self._retry_after_hint()
            if hint is not None:
                bounce["retry_after"] = hint
            await send(bounce)
        except HubFenced:
            # fenced at commit time: this replica was deposed while the
            # write was in flight — bounce like any follower would
            await send({
                "id": mid, "ok": False, "error": "not_leader",
                "leader": self._leader_hint(),
            })
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 - serve errors to the client
            await send({"id": mid, "ok": False, "error": repr(e)})

    def _route(self, op: str) -> dict[str, Any] | None:
        """Hook: return an error payload to bounce ``op`` instead of
        serving it (replicated followers bounce WRITE_OPS with
        ``not_leader``). None = serve normally."""
        return None

    def _leader_hint(self) -> str | None:
        """Hook: current leader address for not_leader bounces (the
        replicated server reports its replica's view)."""
        return None

    def _retry_after_hint(self) -> float | None:
        """Hook: seconds until a ``no_quorum`` bounce is worth retrying
        (the replicated server derives it from its election/lease
        scale). None = send no hint; clients use their own backoff."""
        return None

    async def _commit_barrier(self, seq: int) -> None:
        """Hook: called after a WRITE_OPS op logged records up to WAL
        position ``seq``, before the ack is sent. The base server commits
        locally (no-op); the replicated leader blocks until ``seq`` is on
        a majority of the replica set (hub_replica.py), raising NoQuorum
        when it cannot be."""

    async def _dispatch_repl(
        self, op: str, mid: int, msg: dict[str, Any], send, streams
    ) -> bool:
        """Hook: handle replication ops (``repl.*``); True = handled.
        The base server has none — hub_replica.py overrides."""
        return False

    async def _stream_watch(
        self, mid: int, prefix: str, initial: bool, sync: bool, send
    ) -> None:
        try:
            async for ev in self.hub.watch_prefix(
                prefix, initial=initial, sync_marker=sync
            ):
                await send(
                    {"id": mid, "stream": {"kind": ev.kind, "key": ev.key, "value": ev.value}}
                )
        except asyncio.CancelledError:
            pass
        except (ConnectionResetError, BrokenPipeError):
            pass

    async def _stream_subscribe(self, mid: int, subject: str, replay: bool, send) -> None:
        try:
            async for subj, payload, seq in self.hub.subscribe(
                subject, replay=replay, with_seq=True
            ):
                await send({"id": mid, "stream": {
                    "subject": subj, "payload": payload, "seq": seq}})
        except asyncio.CancelledError:
            pass
        except (ConnectionResetError, BrokenPipeError):
            pass


async def _amain(args: argparse.Namespace) -> None:
    server = HubServer(
        args.host, args.port, args.data_dir,
        fsync=True if args.fsync else None,
    )
    await server.start()
    print(f"DYNAMO_HUB={server.host}:{server.port}", flush=True)
    await server.serve_forever()


def main() -> None:
    parser = argparse.ArgumentParser(description="dynamo-tpu hub (coordination service)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=6650)
    parser.add_argument("--data-dir", default=None)
    parser.add_argument("--fsync", action="store_true",
                        help="fsync every WAL append (survive power loss, "
                             "not just process death); default follows "
                             "DYNAMO_HUB_FSYNC=1")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    try:
        asyncio.run(_amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
