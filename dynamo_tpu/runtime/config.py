"""Layered runtime configuration.

Precedence (low to high): dataclass defaults < YAML file at ``DYN_CONFIG`` <
``DYN_*`` environment variables. Mirrors the reference's figment-based
RuntimeConfig (lib/runtime/src/config.rs:75, env prefixes at :219-265).
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

import yaml

_PREFIX = "DYN_"


def _coerce(value: str, typ: Any) -> Any:
    if typ is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    return value


@dataclass
class RuntimeConfig:
    """Process-level runtime knobs (env prefix ``DYN_``)."""

    # identity / cluster
    namespace: str = "dynamo"
    hub_address: str = ""  # "host:port" of the hub service; empty = in-memory
    # replicated hub: comma-separated replica addresses (DYN_HUB_ADDRESSES);
    # takes precedence over hub_address — clients fail over across the list
    hub_addresses: str = ""
    static: bool = False  # static mode: no discovery, fixed peers (ref lib.rs:205)

    # data plane
    host: str = "127.0.0.1"  # address workers advertise for their TCP listener
    request_timeout_s: float = 600.0
    connect_timeout_s: float = 5.0
    # pre-dial worker channels on instance discovery (DYN_PREWARM_DIALS):
    # the first request to a fresh worker doesn't pay the TCP dial
    prewarm_dials: bool = True
    # directory for workers' unix-socket listeners (DYN_UDS_DIR): when set,
    # each EndpointServer also listens on a socket there and co-located
    # clients dial it instead of TCP; empty = TCP only. Coalescing/corking
    # knobs (DYN_STREAM_COALESCE / DYN_STREAM_CORK) live in transport.py.
    uds_dir: str = ""

    # leases / health
    lease_ttl_s: float = 10.0
    keepalive_interval_s: float = 3.0
    health_check_interval_s: float = 30.0
    health_check_timeout_s: float = 10.0
    # graceful drain (worker SIGTERM / k8s preStop): max seconds to let
    # in-flight requests finish before force-cancelling and exiting; keep
    # terminationGracePeriodSeconds comfortably above this
    drain_timeout_s: float = 30.0
    # per-endpoint withdrawal grace (DYN_WITHDRAW_GRACE_S): after the
    # instance key is deleted, the handler keeps serving this long so a
    # router that picked inside the watch-propagation window still lands
    # on a live worker instead of a corpse (scale-down drain contract).
    # Default covers in-process/LAN watch propagation; raise it on
    # clusters where router watch fan-out takes longer than this.
    withdraw_grace_s: float = 0.01

    # http frontend
    http_port: int = 8000
    system_port: int = 9090  # liveness/readiness/metrics server

    # logging
    log_level: str = "INFO"
    log_jsonl: bool = False

    # engine-side compute
    block_size: int = 64  # KV cache block granularity (tokens/block)
    # speculative decoding defaults for engine workers (DYN_SPEC_MODE /
    # DYN_SPEC_K_MAX; engine/spec.py): explicit --spec CLI flags win,
    # empty/0 falls through to the EngineConfig defaults ("off" / 8)
    spec_mode: str = ""
    spec_k_max: int = 0
    # guided decoding default for engine workers (DYN_GUIDED_MODE;
    # guided/): explicit --guided CLI flags win, empty falls through to
    # the EngineConfig default ("auto")
    guided_mode: str = ""
    # persistent XLA compilation cache dir (DYN_COMPILE_CACHE_DIR): a
    # restarted worker reloads its serving programs from disk instead of
    # paying cold-start TTFT recompiling them; empty = off. Honored by
    # every engine process (engine/compile_cache.py).
    compile_cache_dir: str = ""
    # per-tenant fairness quotas for engine workers (DYN_TENANT_QUOTAS;
    # engine/tenancy.py grammar:
    # "tenantA:weight=4,rate=1000,burst=2000;*:rate=200"). Explicit
    # --tenant-quotas CLI flags win; empty = unmetered equal weights.
    tenant_quotas: str = ""

    extra: dict[str, Any] = field(default_factory=dict)

    def hub_target(self) -> str:
        """The address string to hand connect_hub: the replica list when
        configured, else the single hub address (possibly empty =
        in-memory)."""
        return self.hub_addresses or self.hub_address

    def override_hub(self, address: str) -> "RuntimeConfig":
        """CLI ``--hub`` beats env: route hub_target() at ``address``
        (single ``host:port`` or a comma-separated replica list). One
        helper so every entry point applies the same precedence."""
        self.hub_address = self.hub_addresses = address
        return self

    @classmethod
    def from_env(cls, env: dict[str, str] | None = None) -> "RuntimeConfig":
        env = dict(os.environ if env is None else env)
        layers: dict[str, Any] = {}

        cfg_path = env.get(_PREFIX + "CONFIG")
        if cfg_path and Path(cfg_path).exists():
            loaded = yaml.safe_load(Path(cfg_path).read_text()) or {}
            if not isinstance(loaded, dict):
                raise ValueError(f"config file {cfg_path} must be a mapping")
            layers.update(loaded)

        fields = {f.name: f for f in dataclasses.fields(cls)}
        for key, raw in env.items():
            if not key.startswith(_PREFIX):
                continue
            name = key[len(_PREFIX) :].lower()
            if name != "extra" and name != "config":
                layers[name] = raw  # known keys coerced below via default's type

        known = {k: v for k, v in layers.items() if k in fields and k != "extra"}
        extra = {k: v for k, v in layers.items() if k not in fields}
        # dataclasses stores declared types as strings under future annotations;
        # coerce via the default value's type instead.
        defaults = cls()
        for k, v in list(known.items()):
            if isinstance(v, str):
                known[k] = _coerce(v, type(getattr(defaults, k)))
        return cls(**known, extra=extra)


def config_from_env() -> RuntimeConfig:
    return RuntimeConfig.from_env()
