"""PushRouter: client-side request distribution across worker instances.

Modes mirror the reference RouterMode (pipeline/network/egress/
push_router.rs:71): random, round_robin, direct(instance_id). The KV-aware
mode lives in kv_router/ (it wraps this router and picks the instance by
radix overlap + load). On NoInstances/stream death the caller (migration op)
decides whether to retry.
"""

from __future__ import annotations

from contextlib import aclosing

import enum
import random
from typing import Any, AsyncIterator

from dynamo_tpu.runtime.component import Client
from dynamo_tpu.runtime.context import Context, StreamError


class RouterMode(enum.Enum):
    RANDOM = "random"
    ROUND_ROBIN = "round_robin"
    DIRECT = "direct"
    KV = "kv"


class NoInstancesError(StreamError):
    """No live instances to route to (retryable; migration op backs off)."""


class PushRouter:
    def __init__(self, client: Client, mode: RouterMode = RouterMode.ROUND_ROBIN):
        self.client = client
        self.mode = mode
        self._rr = 0

    @classmethod
    async def from_endpoint(
        cls, endpoint, mode: RouterMode = RouterMode.ROUND_ROBIN
    ) -> "PushRouter":
        client = await endpoint.client().start()
        return cls(client, mode)

    def select(self, instance_id: int | None = None) -> int:
        ids = self.client.instance_ids()
        if not ids:
            raise NoInstancesError(f"no instances for {self.client.endpoint.path}")
        if instance_id is not None:
            if instance_id not in ids:
                raise NoInstancesError(
                    f"instance {instance_id:x} not live for {self.client.endpoint.path}"
                )
            return instance_id
        if self.mode is RouterMode.RANDOM:
            return random.choice(ids)
        # round-robin default
        self._rr = (self._rr + 1) % len(ids)
        return ids[self._rr]

    async def generate(
        self,
        request: Any,
        context: Context,
        *,
        instance_id: int | None = None,
    ) -> AsyncIterator[Any]:
        """Route and stream. ``instance_id`` forces direct mode for this call
        (ref: PreprocessedRequest.backend_instance_id override)."""
        target = self.select(instance_id)
        stream = self.client.call_instance(target, request, context)
        async with aclosing(stream):
            async for item in stream:
                yield item
