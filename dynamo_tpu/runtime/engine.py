"""The AsyncEngine abstraction: a streaming request -> response trait.

Everything that serves requests in this framework - the JAX engine, the
mocker, each pipeline operator (preprocessor, backend, migration, routers) -
implements this one interface, so operators compose into pipelines and any
stage can be moved across a process boundary. Ref: lib/runtime/src/engine.rs:201
``AsyncEngine<SingleIn<Req>, ManyOut<Resp>, Error>``.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Protocol, runtime_checkable

from dynamo_tpu.runtime.context import Context


@runtime_checkable
class AsyncEngine(Protocol):
    """Streaming engine: one request in, many responses out."""

    def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[Any]:  # pragma: no cover - protocol
        ...


class Annotated(dict):
    """Response envelope: either a data item or an out-of-band event.

    Ref: lib/llm/src/protocols Annotated<T> - carries ``data`` plus optional
    ``event``/``comment`` used for annotations (e.g. routing metadata,
    health-check probes) without polluting the data type.
    """

    @classmethod
    def from_data(cls, data: Any) -> "Annotated":
        return cls(data=data)

    @classmethod
    def from_event(cls, event: str, data: Any = None) -> "Annotated":
        return cls(event=event, data=data)

    @property
    def data(self) -> Any:
        return self.get("data")

    @property
    def event(self) -> str | None:
        return self.get("event")

    def is_error(self) -> bool:
        return self.get("event") == "error"


async def collect(stream: AsyncIterator[Any]) -> list[Any]:
    """Drain a response stream into a list (test/CLI helper)."""
    return [item async for item in stream]
