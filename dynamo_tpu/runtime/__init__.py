"""Distributed runtime: the foundation layer.

TPU-first re-design of the reference ``dynamo-runtime`` crate
(lib/runtime/src/): a single asyncio process runtime instead of dual tokio
runtimes; a self-hosted "hub" service (lease-based KV store + prefix watches +
pub/sub + object store) instead of requiring etcd + NATS; and a direct-TCP
request/response data plane instead of NATS push + call-home TCP.

Public surface:
  Runtime / DistributedRuntime  - process + cluster handles (ref lib.rs:72,:184)
  Namespace / Component / Endpoint / Instance / Client (ref component.rs)
  AsyncEngine protocol + Context cancellation (ref engine.rs:201,:112)
  PushRouter with RouterMode (ref pipeline/network/egress/push_router.rs:33)
  Hub implementations: InMemoryHub, RemoteHub + hub server (ref transports/{etcd,nats}.rs)
"""

from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.context import Context, StreamError
from dynamo_tpu.runtime.engine import AsyncEngine, Annotated
from dynamo_tpu.runtime.hub import Hub, InMemoryHub, WatchEvent
from dynamo_tpu.runtime.hub_client import RemoteHub
from dynamo_tpu.runtime.component import (
    Client,
    Component,
    Endpoint,
    Instance,
    Namespace,
)
from dynamo_tpu.runtime.distributed import DistributedRuntime, Runtime
from dynamo_tpu.runtime.push import PushRouter, RouterMode

__all__ = [
    "RuntimeConfig",
    "Context",
    "StreamError",
    "AsyncEngine",
    "Annotated",
    "Hub",
    "InMemoryHub",
    "RemoteHub",
    "WatchEvent",
    "Namespace",
    "Component",
    "Endpoint",
    "Instance",
    "Client",
    "Runtime",
    "DistributedRuntime",
    "PushRouter",
    "RouterMode",
]
