"""Flight recorder: a bounded per-worker ring of per-request event
timelines — the "why was THIS request slow" tool.

Every request the engine admits gets a timeline: admission, phase
transitions (prefill chunks, first token, spec verifies, disagg
events, fault trips), and the finish reason, each stamped with a
monotonic offset from enqueue and carrying the request's trace_id.
The step thread records events with one lock + append (coalescing
repeats, bounded per timeline), so the hot path stays cheap.

Retention is TAIL-BIASED: besides the most-recent ring, errored
timelines and the slowest requests survive eviction in their own
buckets — the interesting requests are exactly the ones a plain ring
would have rotated out by the time an operator asks.

Live queries: worker admin ``{"op": "timeline"}`` (engine/worker.py)
and the frontend's ``GET /debug/timeline`` fan-out (frontend/http.py).

At finish, the timeline is also the source for the worker-side spans
(``worker.request`` / ``engine.queue_wait`` / ``engine.prefill`` /
``engine.decode`` / ``engine.spec``, joined to the caller's trace via
the span context the engine bound at admission) — one cross-process
trace per request without the step thread ever touching contextvars.
"""

from __future__ import annotations

import heapq
import time
from typing import Any

from dynamo_tpu.runtime import race, tracing

__all__ = ["FlightRecorder", "Timeline", "FLIGHT", "emit_request_spans"]

# per-timeline event cap: spec verifies / prefill chunks coalesce, but a
# pathological event storm must stay bounded (drops are counted)
MAX_EVENTS = 96


class Timeline:
    """One request's recorded lifecycle. Not thread-safe on its own —
    the recorder's lock guards all mutation."""

    __slots__ = (
        "request_id", "trace_id", "span_id", "parent_span_id", "sampled",
        "t0_wall_ns", "t0", "attrs", "events", "dropped_events",
        "finish_reason", "error", "ended_t", "seq",
    )

    def __init__(self, request_id: str, attrs: dict[str, Any]):
        self.request_id = request_id
        self.trace_id: str | None = None
        self.span_id: str | None = None
        self.parent_span_id: str | None = None
        self.sampled = True
        self.t0_wall_ns = time.time_ns()
        self.t0 = time.monotonic()
        self.attrs = attrs
        # [{"name", "t", "t_last", "n", **attrs}] — repeats of the SAME
        # name coalesce in place (n++, t_last advances), so per-token /
        # per-verify chatter costs one entry, not one per occurrence
        self.events: list[dict[str, Any]] = []
        self.dropped_events = 0
        self.finish_reason: str | None = None
        self.error: str | None = None
        self.ended_t: float | None = None
        self.seq = 0  # heap tiebreak

    @property
    def duration_s(self) -> float:
        end = self.ended_t if self.ended_t is not None else time.monotonic()
        return end - self.t0

    def first(self, name: str) -> dict[str, Any] | None:
        for ev in self.events:
            if ev["name"] == name:
                return ev
        return None

    def last(self, name: str) -> dict[str, Any] | None:
        for ev in reversed(self.events):
            if ev["name"] == name:
                return ev
        return None

    def to_dict(self) -> dict[str, Any]:
        return {
            "request_id": self.request_id,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "started_unix_ns": self.t0_wall_ns,
            "duration_ms": round(self.duration_s * 1e3, 3),
            "finish_reason": self.finish_reason,
            "error": self.error,
            "live": self.ended_t is None,
            "dropped_events": self.dropped_events,
            **self.attrs,
            "events": [
                {k: (round(v, 6) if isinstance(v, float) else v)
                 for k, v in ev.items()}
                for ev in self.events
            ],
        }

    def summary(self) -> dict[str, Any]:
        return {
            "request_id": self.request_id,
            "trace_id": self.trace_id,
            "duration_ms": round(self.duration_s * 1e3, 3),
            "finish_reason": self.finish_reason,
            "error": self.error,
            "live": self.ended_t is None,
        }


class FlightRecorder:
    """Bounded in-memory store of request timelines (active + retained)."""

    def __init__(self, capacity: int = 128, keep_errors: int = 32,
                 keep_slow: int = 32):
        self._lock = race.Lock("flight.lock")
        self._active: dict[str, Timeline] = {}
        self._recent: list[Timeline] = []
        self._capacity = capacity
        self._errors: list[Timeline] = []
        self._keep_errors = keep_errors
        # min-heap of (duration, seq, timeline): the slowest keep_slow
        # finished requests survive even when the recent ring rotates
        self._slow: list[tuple[float, int, Timeline]] = []
        self._keep_slow = keep_slow
        self._seq = 0

    # -- recording (any thread) -------------------------------------------

    def start(self, request_id: str, *, trace: "tracing.TraceContext | None"
              = None, parent_span_id: str | None = None,
              **attrs: Any) -> Timeline:
        tl = Timeline(request_id, attrs)
        if trace is not None:
            tl.trace_id = trace.trace_id
            tl.span_id = trace.span_id
            tl.sampled = trace.sampled
            tl.parent_span_id = parent_span_id
        with self._lock:
            race.write("flight.timeline")
            self._seq += 1
            tl.seq = self._seq
            self._active[request_id] = tl
        return tl

    def event(self, request_id: str, name: str, **attrs: Any) -> None:
        """Record one lifecycle event; unknown request ids no-op (the
        caller may be a step-thread path racing a finished stream)."""
        now = time.monotonic()
        with self._lock:
            race.write("flight.timeline")
            tl = self._active.get(request_id)
            if tl is None:
                return
            t = now - tl.t0
            if tl.events and tl.events[-1]["name"] == name:
                ev = tl.events[-1]
                ev["n"] += 1
                ev["t_last"] = t
                ev.update(attrs)
                return
            if len(tl.events) >= MAX_EVENTS:
                tl.dropped_events += 1
                return
            tl.events.append({"name": name, "t": t, "t_last": t, "n": 1,
                              **attrs})

    def finish(self, request_id: str, reason: str | None,
               error: str | None = None, **attrs: Any) -> Timeline | None:
        """Close a timeline and move it into retention. Returns the
        closed timeline (None when the id is unknown / already closed)."""
        now = time.monotonic()
        with self._lock:
            race.write("flight.timeline")
            tl = self._active.pop(request_id, None)
            if tl is None:
                return None
            tl.ended_t = now  # absolute monotonic end
            tl.finish_reason = reason
            tl.error = error
            tl.attrs.update(attrs)
            self._recent.append(tl)
            if len(self._recent) > self._capacity:
                self._recent.pop(0)
            if error or reason == "error":
                self._errors.append(tl)
                if len(self._errors) > self._keep_errors:
                    self._errors.pop(0)
            item = (tl.duration_s, tl.seq, tl)
            if len(self._slow) < self._keep_slow:
                heapq.heappush(self._slow, item)
            elif item[0] > self._slow[0][0]:
                heapq.heapreplace(self._slow, item)
            return tl

    # -- queries (event loop / admin) -------------------------------------

    def _lookup_locked(self, request_id: str) -> Timeline | None:
        tl = self._active.get(request_id)
        if tl is not None:
            return tl
        for bucket in (self._recent, self._errors,
                       [t for _d, _s, t in self._slow]):
            for tl in reversed(bucket):
                if tl.request_id == request_id:
                    return tl
        return None

    def lookup(self, request_id: str) -> Timeline | None:
        """Find a timeline by id. An ACTIVE result is still being
        mutated by the step thread — callers that serialize it must use
        :meth:`snapshot`, which renders under the recorder lock."""
        with self._lock:
            return self._lookup_locked(request_id)

    def snapshot(self, request_id: str | None = None,
                 n: int = 16) -> dict[str, Any]:
        """Admin-op payload: one full timeline (by request id), or the
        summary view (active + recent tail + retained errors/slowest).

        The by-id render happens UNDER the recorder lock: an active
        timeline's event list (and the coalesced tail event's dict) is
        still being mutated by the step thread, so serializing it
        outside the lock races ``event()`` — ``dict.update`` on the
        tail entry while ``to_dict`` iterates it can raise and, short
        of that, tears the event. (This was a real pre-dynarace bug.)
        """
        if request_id:
            with self._lock:
                race.read("flight.timeline")
                tl = self._lookup_locked(request_id)
                if tl is None:
                    return {"found": False, "request_id": request_id}
                return {"found": True, "timeline": tl.to_dict()}
        with self._lock:
            race.read("flight.timeline")
            slowest = sorted(self._slow, key=lambda it: -it[0])
            return {
                "active": [t.summary() for t in self._active.values()],
                "recent": [t.summary() for t in self._recent[-n:]],
                "errors": [t.summary() for t in self._errors[-n:]],
                "slowest": [t.summary() for _d, _s, t in slowest[:n]],
            }

    def clear(self) -> None:
        with self._lock:
            self._active.clear()
            self._recent.clear()
            self._errors.clear()
            self._slow.clear()


# process-wide recorder: the engine records into it, the worker admin op
# and the frontend debug route read from it
FLIGHT = FlightRecorder()


def emit_request_spans(tl: Timeline) -> None:
    """Derive the worker-side span tree from a finished timeline and
    emit it under the request's trace: ``worker.request`` (child of the
    caller's transport span) with ``engine.queue_wait`` / ``engine.
    prefill`` / ``engine.decode`` / ``engine.spec`` children. Phases the
    request never reached are simply absent."""
    if tl.trace_id is None or tl.span_id is None or tl.ended_t is None:
        return
    wr = tracing.TraceContext(tl.trace_id, tl.span_id, tl.sampled)

    def ns(rel_s: float) -> int:
        return tl.t0_wall_ns + int(rel_s * 1e9)

    def child_tc() -> "tracing.TraceContext":
        return tracing.TraceContext(
            tl.trace_id, tracing.new_span_id(), tl.sampled
        )

    end_rel = tl.ended_t - tl.t0
    admit = tl.first("admit")
    first_tok = tl.first("first_token") or tl.first("disagg_resume")
    if admit is not None:
        tracing.emit_span(
            "engine.queue_wait", child_tc(), parent_span_id=tl.span_id,
            start_ns=ns(0.0), end_ns=ns(admit["t"]),
        )
        if first_tok is not None:
            chunks = tl.first("prefill_chunk")
            tracing.emit_span(
                "engine.prefill", child_tc(), parent_span_id=tl.span_id,
                start_ns=ns(admit["t"]), end_ns=ns(first_tok["t"]),
                attrs={"chunks": chunks["n"]} if chunks else None,
            )
            tracing.emit_span(
                "engine.decode", child_tc(), parent_span_id=tl.span_id,
                start_ns=ns(first_tok["t"]), end_ns=ns(end_rel),
                attrs={"tokens": tl.attrs.get("generated")},
            )
    spec = tl.first("spec_verify")
    if spec is not None:
        tracing.emit_span(
            "engine.spec", child_tc(), parent_span_id=tl.span_id,
            start_ns=ns(spec["t"]),
            end_ns=ns(tl.last("spec_verify")["t_last"]),
            attrs={"verifies": spec["n"]},
        )
    attrs = {"request_id": tl.request_id, **tl.attrs}
    if tl.finish_reason:
        attrs["finish_reason"] = tl.finish_reason
    tracing.emit_span(
        "worker.request", wr, parent_span_id=tl.parent_span_id,
        start_ns=tl.t0_wall_ns, end_ns=ns(end_rel), attrs=attrs,
        error=tl.error,
    )
