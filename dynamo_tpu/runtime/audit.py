"""Request/response audit bus (ref lib/llm/src/audit/ — bus + sinks).

Every completed request on the serving surface can emit one audit
record — who asked for what, what came back, how long it took — to
pluggable sinks. Records are emitted AFTER the response finishes (audit
must never sit on the request path); a slow sink drops records rather
than applying backpressure.

Sinks: JSONL file (greppable, the recorder's format family) and hub
subject (retained, so an auditor can attach late). ``DYN_AUDIT_PATH``
env enables the file sink process-wide.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Any

log = logging.getLogger("dynamo.audit")

AUDIT_SUBJECT = "audit/{namespace}/requests"


class AuditRecord(dict):
    """One request's audit entry (a dict; keys stay wire-stable)."""

    @classmethod
    def make(
        cls,
        *,
        route: str,
        model: str | None,
        request_id: str,
        request: dict[str, Any],
        status: int,
        finish_reason: str | None = None,
        output_tokens: int = 0,
        duration_ms: float = 0.0,
        error: str | None = None,
    ) -> "AuditRecord":
        rec = cls(
            ts=time.time(),
            route=route,
            model=model,
            request_id=request_id,
            status=status,
            finish_reason=finish_reason,
            output_tokens=output_tokens,
            duration_ms=round(duration_ms, 3),
            # request essentials only: prompts can be huge and sensitive;
            # sinks get sizes + sampling knobs, not content (the reference
            # gates content capture the same way)
            request={
                "messages_count": len(request.get("messages") or []),
                "prompt_chars": len(str(request.get("prompt") or "")),
                "max_tokens": request.get("max_tokens"),
                "temperature": request.get("temperature"),
                "stream": bool(request.get("stream")),
                "tools": len(request.get("tools") or []),
            },
        )
        if error:
            rec["error"] = error
        return rec


class JsonlSink:
    """File sink with a writer thread: emit() only enqueues, so a slow
    or network-mounted disk never stalls the serving event loop (the
    module contract). A full queue drops records."""

    def __init__(self, path: str, *, max_queue: int = 1024):
        import queue as _queue
        import threading

        self._f = open(path, "a")
        # records dropped because the queue was full (observable: silent
        # audit loss under backpressure is itself an audit failure)
        self.dropped = 0
        self._q: "_queue.Queue" = _queue.Queue(maxsize=max_queue)
        self._stop = object()
        self._thread = threading.Thread(
            target=self._run, name="audit-jsonl", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while True:
            rec = self._q.get()
            if rec is self._stop:
                self._f.close()
                return
            try:
                self._f.write(json.dumps(rec) + "\n")
                self._f.flush()
            except Exception:  # noqa: BLE001
                log.warning("audit jsonl write failed", exc_info=True)

    def emit(self, rec: AuditRecord) -> None:
        try:
            self._q.put_nowait(rec)
        # dynalint: disable=DL003 -- drop-don't-block is the module
        # contract; the drop is counted, not silent
        except Exception:  # noqa: BLE001
            self.dropped += 1  # full queue: drop, never block serving

    def flush(self, timeout: float = 5.0) -> None:
        """Blocking drain for tests and process shutdown ONLY — the
        serving path never calls it (emit() is enqueue-and-return)."""
        import time as _time

        deadline = _time.monotonic() + timeout
        while not self._q.empty() and _time.monotonic() < deadline:
            # dynalint: disable=DL001 -- test/shutdown helper, never on
            # the event loop; emit() is the serving-path surface
            _time.sleep(0.01)

    def close(self) -> None:
        self._q.put(self._stop)
        self._thread.join(timeout=5)


class HubSink:
    """Publish to a retained hub subject (fire-and-forget)."""

    def __init__(self, hub, namespace: str = "dynamo"):
        self.hub = hub
        self.subject = AUDIT_SUBJECT.format(namespace=namespace)
        # the loop holds only weak task refs: keep publishes alive
        self._tasks: set = set()

    def emit(self, rec: AuditRecord) -> None:
        task = asyncio.ensure_future(self.hub.publish(self.subject, dict(rec)))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def close(self) -> None:
        pass


class AuditBus:
    def __init__(self) -> None:
        self.sinks: list = []
        self.emitted = 0
        path = (os.environ.get("DYN_AUDIT_PATH") or "").strip()
        if path:
            self.sinks.append(JsonlSink(path))

    @property
    def enabled(self) -> bool:
        return bool(self.sinks)

    def add_sink(self, sink) -> "AuditBus":
        self.sinks.append(sink)
        return self

    def emit(self, rec: AuditRecord) -> None:
        for sink in self.sinks:
            try:
                sink.emit(rec)
            except Exception:  # noqa: BLE001
                log.warning("audit sink failed (record dropped)",
                            exc_info=True)
        self.emitted += 1

    def close(self) -> None:
        for sink in self.sinks:
            try:
                sink.close()
            except Exception:  # noqa: BLE001
                # shutdown fan-out: one sink's close failure must not stop
                # the others from closing
                log.warning("audit sink close failed", exc_info=True)
