"""Length-prefixed msgpack framing shared by all TCP planes.

Wire format: 4-byte big-endian unsigned length, then a msgpack-encoded map.
Used by the hub protocol (hub_server/hub_client) and the request/response
data plane (transport.py). Ref: the reference's two-part codec in
lib/runtime/src/pipeline/network/codec.rs.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any, Callable

import msgpack

_LEN = struct.Struct(">I")
MAX_FRAME = 512 * 1024 * 1024  # object-store blobs can be large


def pack(msg: dict[str, Any]) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    return _LEN.pack(len(body)) + body


async def read_frame(reader: asyncio.StreamReader) -> dict[str, Any] | None:
    """Read one frame; None on clean EOF."""
    got = await read_frame_sized(reader)
    return None if got is None else got[0]


async def read_frame_sized(
    reader: asyncio.StreamReader,
) -> tuple[dict[str, Any], int] | None:
    """Read one frame and its on-wire size (header + body) for rx
    accounting; None on clean EOF."""
    try:
        header = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    try:
        body = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return msgpack.unpackb(body, raw=False), _LEN.size + length


async def write_frame(writer: asyncio.StreamWriter, msg: dict[str, Any]) -> None:
    writer.write(pack(msg))
    await writer.drain()


class FrameFeeder:
    """Incremental frame parser for chunked socket reads.

    ``feed(chunk)`` returns every complete frame (with its on-wire size)
    buffered so far; a partial frame tail is held until the next chunk.
    This is the receive-side dual of the corked ``FrameWriter``: the send
    path batches many frames into one TCP segment, so the rx loop should
    pay ONE ``reader.read()`` await per segment — not two ``readexactly``
    coroutine hops per frame, which dominate rx cost under coalescing.

    Raises ``ValueError`` on an oversize length prefix (same contract as
    ``read_frame_sized``: length-prefixed framing cannot resync, the
    caller must drop the connection).
    """

    __slots__ = ("_buf",)

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, chunk: bytes) -> list[tuple[Any, int]]:
        buf = self._buf
        buf += chunk
        out: list[tuple[Any, int]] = []
        pos = 0
        n = len(buf)
        while n - pos >= _LEN.size:
            length = int.from_bytes(buf[pos : pos + _LEN.size], "big")
            if length > MAX_FRAME:
                raise ValueError(f"frame too large: {length}")
            end = pos + _LEN.size + length
            if end > n:
                break
            out.append((
                msgpack.unpackb(bytes(buf[pos + _LEN.size : end]), raw=False),
                _LEN.size + length,
            ))
            pos = end
        if pos:
            del buf[:pos]
        return out

    @property
    def pending_bytes(self) -> int:
        """Bytes of partial frame currently held (torn-frame visibility)."""
        return len(self._buf)


class FrameWriter:
    """Corked frame writer: the data plane's batched send path.

    ``feed()`` appends a packed frame to a user-space buffer; the buffer is
    written to the transport once per event-loop tick (or immediately when
    it crosses ``high_water`` bytes), so a burst of N frames — e.g. one
    decode step across 64 concurrent streams — costs one writev-shaped
    ``transport.write`` instead of N write+drain round-trips. ``drain()``
    is awaited only when the kernel-side write buffer reports backpressure
    (``drain_above`` bytes), which is what bounds memory against a stalled
    peer without paying a coroutine suspension per frame.

    With ``cork=False`` every frame is written and drained immediately —
    the pre-corking behavior, kept for A/B benchmarking (stream_bench) and
    as an escape hatch (``DYN_STREAM_CORK=0``).
    """

    def __init__(
        self,
        writer: asyncio.StreamWriter,
        *,
        cork: bool = True,
        high_water: int = 64 * 1024,
        drain_above: int = 256 * 1024,
        stats: dict[str, int] | None = None,
        on_flush: Callable[[int], None] | None = None,
    ) -> None:
        self._writer = writer
        self.cork = cork
        self.high_water = high_water
        self.drain_above = drain_above
        self._buf = bytearray()
        self._tick_scheduled = False
        self._stats = stats
        self._on_flush = on_flush
        # per-writer counters (module-wide aggregation rides ``stats``)
        self.frames = 0
        self.flushes = 0
        self.drains = 0
        self.bytes_out = 0

    def feed(self, msg: dict[str, Any]) -> None:
        """Buffer one frame; written at end of tick / high water. Callers
        that can await should follow up with ``pump()``."""
        self._buf += pack(msg)
        self.frames += 1
        if not self.cork:
            self._write_out()
            return
        if not self._tick_scheduled:
            self._tick_scheduled = True
            asyncio.get_running_loop().call_soon(self._tick)

    async def send(self, msg: dict[str, Any]) -> None:
        """feed + pump in one call."""
        self.feed(msg)
        await self.pump()

    async def pump(self) -> None:
        """Write out if over high water; drain only on backpressure."""
        if not self.cork:
            self.drains += 1
            if self._stats is not None:
                self._stats["drains"] += 1
            await self._writer.drain()
            return
        if len(self._buf) >= self.high_water:
            self._write_out()
        transport = self._writer.transport
        if (
            transport is not None
            and transport.get_write_buffer_size() > self.drain_above
        ):
            self.drains += 1
            if self._stats is not None:
                self._stats["drains"] += 1
            await self._writer.drain()

    async def flush(self) -> None:
        """Force the buffer onto the transport now (still corked for the
        kernel: drain only on backpressure)."""
        self._write_out()
        transport = self._writer.transport
        if (
            transport is not None
            and transport.get_write_buffer_size() > self.drain_above
        ):
            self.drains += 1
            if self._stats is not None:
                self._stats["drains"] += 1
            await self._writer.drain()

    def _tick(self) -> None:
        self._tick_scheduled = False
        self._write_out()

    def _write_out(self) -> None:
        if not self._buf:
            return
        n = len(self._buf)
        if self._writer.is_closing():
            self._buf.clear()
            return
        self._writer.write(bytes(self._buf))
        self._buf.clear()
        self.flushes += 1
        self.bytes_out += n
        if self._stats is not None:
            self._stats["flushes"] += 1
            self._stats["bytes_out"] += n
        if self._on_flush is not None:
            self._on_flush(n)
