"""Length-prefixed msgpack framing shared by all TCP planes.

Wire format: 4-byte big-endian unsigned length, then a msgpack-encoded map.
Used by the hub protocol (hub_server/hub_client) and the request/response
data plane (transport.py). Ref: the reference's two-part codec in
lib/runtime/src/pipeline/network/codec.rs.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Any

import msgpack

_LEN = struct.Struct(">I")
MAX_FRAME = 512 * 1024 * 1024  # object-store blobs can be large


def pack(msg: dict[str, Any]) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    return _LEN.pack(len(body)) + body


async def read_frame(reader: asyncio.StreamReader) -> dict[str, Any] | None:
    """Read one frame; None on clean EOF."""
    try:
        header = await reader.readexactly(_LEN.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    try:
        body = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return msgpack.unpackb(body, raw=False)


async def write_frame(writer: asyncio.StreamWriter, msg: dict[str, Any]) -> None:
    writer.write(pack(msg))
    await writer.drain()
