"""Event recording + deterministic replay (ref lib/llm/src/recorder.rs:30).

The reference's router benchmarks and regression workflow run against
RECORDED event streams (mocker sessions captured to JSONL, replayed
without the fleet). Same here: ``EventRecorder`` taps hub subjects and
writes one JSONL line per event; ``replay_events`` republishes a capture
in order — a KvRouter subscribed to the same subjects rebuilds the exact
radix state the live session produced, so routing behavior is
regression-testable from a file.

Record format, one line per event:
    {"t": <seconds since capture start>, "subject": "...", "seq": N,
     "payload": {...}}
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import TextIO

__all__ = ["EventRecorder", "replay_events", "load_recording"]


class EventRecorder:
    """Tap hub subjects to a JSONL sink.

    ``replay=True`` captures retained history first, so a recorder
    attached after a session still produces the full stream (the hub's
    JetStream-style retention is what makes late capture sound).
    """

    def __init__(self, hub, subject: str, sink: TextIO, *, replay: bool = True):
        self.hub = hub
        self.subject = subject
        self.sink = sink
        self.replay = replay
        self.count = 0
        self._t0 = time.monotonic()
        self._task: asyncio.Task | None = None

    async def _run(self) -> None:
        async for subj, payload, seq in self.hub.subscribe(
            self.subject, replay=self.replay, with_seq=True
        ):
            self.sink.write(json.dumps({
                "t": round(time.monotonic() - self._t0, 6),
                "subject": subj,
                "seq": seq,
                "payload": payload,
            }) + "\n")
            self.count += 1

    def start(self) -> "EventRecorder":
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def close(self) -> None:
        if self._task is not None:
            # let queued events drain to the sink before cancelling
            await asyncio.sleep(0)
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        self.sink.flush()


def load_recording(path: str) -> list[dict]:
    return [json.loads(ln) for ln in open(path) if ln.strip()]


async def replay_events(
    hub, path: str, *, speed: float = 0.0, subject_map=None
) -> int:
    """Republish a capture in recorded order. ``speed`` > 0 dilates the
    original timing by that factor (1.0 = real time); 0 replays as fast
    as the hub accepts. ``subject_map(subject) -> subject`` rewrites
    destinations (e.g. replay one worker's stream into a test namespace).
    Returns the number of events republished."""
    records = load_recording(path)
    t0 = time.monotonic()
    n = 0
    for rec in records:
        if speed > 0:
            delay = rec["t"] / speed - (time.monotonic() - t0)
            if delay > 0:
                await asyncio.sleep(delay)
        subject = rec["subject"]
        if subject_map is not None:
            subject = subject_map(subject)
        await hub.publish(subject, rec["payload"])
        n += 1
    return n
