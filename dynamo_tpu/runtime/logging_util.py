"""Structured logging setup (ref lib/runtime/src/logging.rs).

``DYN_LOG_LEVEL`` sets the level, ``DYN_LOG_JSONL=1`` switches to one-JSON-
object-per-line output for log shippers. Request ids propagate via the
``extra={"request_id": ...}`` convention.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(time.time(), 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        for key in ("request_id", "instance_id", "model"):
            val = getattr(record, key, None)
            if val is not None:
                entry[key] = val
        return json.dumps(entry)


def setup_logging(level: str | None = None, jsonl: bool | None = None) -> None:
    level = level or os.environ.get("DYN_LOG_LEVEL", "INFO")
    if jsonl is None:
        jsonl = os.environ.get("DYN_LOG_JSONL", "") in ("1", "true")
    handler = logging.StreamHandler(sys.stderr)
    if jsonl:
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname).1s %(name)s %(message)s")
        )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(level.upper())
