"""Hub durability: snapshot + write-ahead log.

The reference rides etcd's disk persistence and NATS JetStream file
storage (ref: lib/runtime/src/transports/etcd.rs leases/KV,
nats.rs:132-243 JetStream stream config): a frontend or router restart
recovers model cards, instance keys, and event-stream positions from
the transports, and a restarted etcd/NATS node recovers its own state
from disk. This module gives the self-hosted hub the same property:

- every mutation appends ONE msgpack record to a write-ahead log
  (length-prefixed, same framing as the wire protocol) and the file is
  flushed before the mutation is acknowledged — a SIGKILL'd hub process
  loses nothing that was acked (OS page cache survives process death;
  set DYNAMO_HUB_FSYNC=1 to also survive kernel/power loss);
- a threshold-triggered snapshot (every ``compact_every`` records,
  written by a background task off the mutation path) bounds replay
  time and WAL growth;
- recovery rebuilds the FULL hub state — KV + lease bindings, leases,
  retained subjects with their per-subject seq counters, object
  buckets — and preserves ``boot_id``, so consumers' persisted seq
  baselines (e.g. the KV router's radix snapshot, kv_router/router.py)
  remain valid across a hub restart.

Leases are restored with deadlines reset to now+ttl: a live owner keeps
them alive via keepalive (lease ids are stable across the restart); a
dead owner's lease re-expires one TTL later — etcd's lease-recovery
semantics. Keepalives are NOT logged (they would dominate the WAL);
re-expiry replaces them.

File layout under ``data_dir``:
  hub.snap      msgpack snapshot, atomically replaced; carries ``gen``
  hub.wal.<g>   records appended since snapshot generation ``g``
On load, only the WAL whose generation matches the snapshot's is
replayed (an older WAL's records are already inside the snapshot — the
crash window between snapshot replace and WAL rotation is covered by
the generation check, never by double-apply). A torn final record
(crash mid-append) is detected and the tail discarded.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import struct
import time
from collections import deque
from pathlib import Path
from typing import Any

import msgpack

from dynamo_tpu.runtime import race
from dynamo_tpu.runtime.context import spawn
from dynamo_tpu.runtime.faults import FAULTS
from dynamo_tpu.runtime.hub import InMemoryHub, _Lease
from dynamo_tpu.runtime.metrics import MetricsRegistry, register_registry

log = logging.getLogger("dynamo.hub")

_LEN = struct.Struct(">I")
_MAX_REC = 512 * 1024 * 1024

# Process-wide hub-store metrics, appended to every /metrics surface.
# Background snapshot-compaction failures were previously only visible in
# logs; the counter makes "the WAL is growing because compaction keeps
# failing" alertable before the disk fills.
_METRICS = MetricsRegistry()
COMPACTION_FAILURES = _METRICS.counter(
    "hub_compaction_failures_total",
    "Hub snapshot-compaction failures (serving continues on the "
    "uncompacted WAL).",
)
register_registry("hub_store", _METRICS)


class HubFenced(RuntimeError):
    """A WAL commit was refused by the fencing check: the hub minting the
    record is no longer the leader of the epoch it is writing under
    (hub_replica.py sets the policy via ``_commit_allowed``). The
    in-flight write of a deposed leader dies here instead of being
    replayed into a history the cluster has moved past."""


class HubStore:
    """Disk half of the durable hub: WAL append + snapshot rotation.

    ``fsync`` forces an fsync per WAL append (survives kernel/power loss,
    not just process death); default follows ``DYNAMO_HUB_FSYNC=1``.
    """

    def __init__(self, data_dir: str | Path, *, fsync: bool | None = None):
        self.dir = Path(data_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.gen = 0
        self._wal = None
        self._tmp_ids = itertools.count(1)
        # stale temp snapshots/term files (crash mid-write, or a discarded
        # stale background capture) are dead weight — clear them
        for pattern in ("hub.snap.tmp*", "hub.term.tmp*"):
            for p in self.dir.glob(pattern):
                try:
                    p.unlink()
                except OSError:
                    pass
        self._fsync = (
            os.environ.get("DYNAMO_HUB_FSYNC") == "1" if fsync is None
            else fsync
        )
        self.records_since_snapshot = 0

    @property
    def snap_path(self) -> Path:
        return self.dir / "hub.snap"

    @property
    def term_path(self) -> Path:
        return self.dir / "hub.term"

    def wal_path(self, gen: int) -> Path:
        return self.dir / f"hub.wal.{gen}"

    # -- election term (raft-style durable vote state) ----------------------

    def load_term(self) -> tuple[int, str | None]:
        """(term, voted_for) from the term file; (0, None) when absent or
        torn. Kept OUT of the WAL deliberately: a vote grant must not look
        like replicated-state divergence to the resync path."""
        try:
            data = msgpack.unpackb(self.term_path.read_bytes(), raw=False)
            return int(data.get("term", 0)), data.get("voted_for")
        except (OSError, ValueError, msgpack.exceptions.ExtraData):
            return 0, None

    def save_term(self, term: int, voted_for: str | None) -> None:
        """Atomically persist (term, voted_for). Always fsynced regardless
        of the WAL fsync knob: voting twice in one term after a crash
        breaks election safety outright, while a lost WAL tail only costs
        acked-but-unreplicated data the contract already concedes.
        Deliberately synchronous on the caller's thread (it runs on the
        event loop from vote handling): the grant must be durable BEFORE
        the response frame leaves the process, and term changes happen
        once per election — not per write — so the stall is rare and
        bounded, unlike the per-append path that earned a background
        thread."""
        tmp = Path(f"{self.term_path}.tmp{next(self._tmp_ids)}")
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(
                {"term": int(term), "voted_for": voted_for},
                use_bin_type=True,
            ))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.term_path)
        # the rename itself must be durable before the grant leaves this
        # process: without the directory fsync a power loss can resurrect
        # the OLD term file and let the restarted replica vote a second
        # time in the same term
        dirfd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)

    # -- load --------------------------------------------------------------

    def load(self) -> tuple[dict[str, Any] | None, list[dict[str, Any]]]:
        """(snapshot state or None, WAL records after it)."""
        state = None
        if self.snap_path.exists():
            try:
                state = msgpack.unpackb(
                    self.snap_path.read_bytes(), raw=False
                )
                self.gen = int(state.get("gen", 0))
            except (ValueError, msgpack.exceptions.ExtraData) as e:
                # torn snapshot can only mean a failed atomic replace
                # that never committed — fall back to empty + WAL
                log.error("hub snapshot unreadable (%s); ignoring", e)
                state = None
        records = self._read_wal(self.wal_path(self.gen))
        return state, records

    def _read_wal(self, path: Path) -> list[dict[str, Any]]:
        if not path.exists():
            return []
        data = path.read_bytes()
        records: list[dict[str, Any]] = []
        off = 0
        while off + _LEN.size <= len(data):
            (n,) = _LEN.unpack_from(data, off)
            if n > _MAX_REC or off + _LEN.size + n > len(data):
                break  # torn tail record: crash mid-append
            try:
                records.append(
                    msgpack.unpackb(
                        data[off + _LEN.size: off + _LEN.size + n], raw=False
                    )
                )
            except ValueError:
                break
            off += _LEN.size + n
        if off != len(data):
            log.warning(
                "hub WAL %s: discarding torn tail (%d bytes)",
                path.name, len(data) - off,
            )
            # truncate so the torn bytes don't prefix future appends
            with open(path, "r+b") as f:
                f.truncate(off)
        return records

    # -- append ------------------------------------------------------------

    def open_wal(self, append: bool = True) -> None:
        mode = "ab" if append else "wb"
        if self._wal is not None:
            self._wal.close()
        self._wal = open(self.wal_path(self.gen), mode)

    def append(self, rec: dict[str, Any]) -> None:
        if FAULTS.enabled:
            # hub.wal_append error = failed disk write (acked mutations
            # must not be lost — the caller surfaces the failure);
            # hub.fsync delay = slow disk at the durability point
            FAULTS.fire_sync("hub.wal_append")
        if self._wal is None:
            self.open_wal()
        body = msgpack.packb(rec, use_bin_type=True)
        self._wal.write(_LEN.pack(len(body)) + body)
        self._wal.flush()
        if FAULTS.enabled:
            FAULTS.fire_sync("hub.fsync")
        if self._fsync:
            os.fsync(self._wal.fileno())
        self.records_since_snapshot += 1

    # -- snapshot ----------------------------------------------------------

    def snapshot(self, state: dict[str, Any]) -> None:
        """Atomically replace the snapshot and rotate the WAL (inline)."""
        tmp, new_gen = self.write_snapshot_tmp(state)
        self.commit_snapshot(tmp, new_gen, [])

    def write_snapshot_tmp(
        self, state: dict[str, Any]
    ) -> tuple[Path, int]:
        """Serialize + fsync the snapshot to a temp file. Does NOT touch
        the live snapshot or the WAL, so it is safe to run in a worker
        thread while the event loop keeps appending to the current WAL
        (DurableHub background compaction). The temp name is UNIQUE per
        call: an inline hard-bound snapshot may race an in-flight
        background write, and a shared name would let the background
        thread keep writing through its fd into an inode the inline
        path already renamed onto hub.snap — corrupting the live
        snapshot."""
        race.acquire(self, "hub.snapshot")
        new_gen = self.gen + 1
        state = dict(state, gen=new_gen)
        # NOT with_suffix: that would REPLACE ".snap" ("hub.tmp7") and
        # the crash-cleanup glob for "hub.snap.tmp*" would never match
        tmp = Path(f"{self.snap_path}.tmp{next(self._tmp_ids)}")
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(state, use_bin_type=True))
            f.flush()
            if FAULTS.enabled:
                # the snapshot's own durability point: a failing disk here
                # is a compaction failure, not a serving failure — the
                # caller counts it and keeps serving on the old WAL. A
                # DISTINCT site from the per-append hub.fsync: this runs
                # in a compaction worker thread, and sharing one seeded
                # decision stream across threads would make the schedule
                # interleaving-dependent (the determinism faults.py
                # promises).
                FAULTS.fire_sync("hub.snap_fsync")
            os.fsync(f.fileno())
        return tmp, new_gen

    def commit_snapshot(
        self, tmp: Path, new_gen: int,
        pending: list[dict[str, Any]],
    ) -> None:
        """Publish a prepared snapshot: start the new-generation WAL,
        re-append ``pending`` records (mutations logged AFTER the state
        was captured — they are in the old-gen WAL, which the new
        snapshot's generation check will ignore), then atomically replace
        the snapshot. Crash-safe in both orders: before the replace the
        old snapshot + old WAL are authoritative; after it the new
        snapshot + new WAL already hold the pending tail."""
        old_gen = self.gen
        self.gen = new_gen
        self.open_wal(append=False)
        self.records_since_snapshot = 0
        for rec in pending:
            self.append(rec)
        os.replace(tmp, self.snap_path)
        for p in self.dir.glob("hub.wal.*"):
            try:
                if int(p.name.rsplit(".", 1)[1]) < new_gen:
                    p.unlink()
            except (ValueError, OSError):
                pass
        log.info(
            "hub snapshot gen %d written (%d bytes, %d pending re-appended),"
            " wal rotated from gen %d",
            new_gen, self.snap_path.stat().st_size, len(pending), old_gen,
        )

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
            self._wal = None


class DurableHub(InMemoryHub):
    """InMemoryHub + HubStore persistence: every mutation WAL-logged,
    full state (incl. boot_id and per-subject seqs) recovered on
    construction. The etcd-disk + JetStream-file-store durability role.

    Snapshot compaction is a threshold-triggered BACKGROUND task: once
    ``compact_every`` records accumulate, the state is captured
    synchronously but serialized + fsynced in a worker thread, and
    mutations keep flowing to the old-generation WAL meanwhile (they are
    re-appended to the new generation at commit). The mutating call never
    pays the snapshot latency — replication bootstrap (hub_replica.py)
    can request snapshots without blocking the serving path. A hard
    bound (4x the threshold) falls back to an inline snapshot so a loop
    that never yields still cannot grow the WAL unboundedly.

    Replication taps: every logged record gets a global ``wal_seq``; the
    last ``REPL_BACKLOG`` records are kept in memory so a follower can
    catch up mid-WAL, and listener queues registered in
    ``_repl_listeners`` receive every ``(seq, record)`` as it commits.
    """

    # in-memory (seq, record) window a reconnecting follower can resume
    # from without a snapshot bootstrap
    REPL_BACKLOG = 8192

    def __init__(
        self, data_dir: str | Path, *, compact_every: int = 8192,
        fsync: bool | None = None,
    ) -> None:
        super().__init__()
        self.compact_every = compact_every
        self.store = HubStore(data_dir, fsync=fsync)
        # replication stream position: total records ever logged by the
        # leader lineage this hub's state descends from
        self.wal_seq = 0
        # leadership term; bumped by hub_replica promotion
        self.repl_epoch = 0
        # fencing epoch of the LAST record in the log: the raft election
        # restriction compares (last record term, position), so a deposed
        # leader's uncommitted tail — long, but stamped with a dead term —
        # can never outrank a shorter log holding newer-term records
        self.last_rec_epoch = 0
        # follower-side: last leader wal_seq applied (0 = never synced)
        self.repl_cursor = 0
        self._recent: deque = deque(maxlen=self.REPL_BACKLOG)
        self._repl_listeners: list[asyncio.Queue] = []
        self._compacting = False
        # when set, _log also mirrors records here (compaction capture)
        self._capture_log: list[dict[str, Any]] | None = None
        state, records = self.store.load()
        if state is not None:
            self._restore(state)
        for rec in records:
            self._apply(rec)
            # records minted after the replication PR carry their global
            # stream seq ("sq") — prefer it so recovery lands on exactly
            # the position the record was logged at; the increment covers
            # pre-stamp WALs
            self.wal_seq = max(int(rec.get("sq", 0)), self.wal_seq + 1)
            self._recent.append((self.wal_seq, rec))
        self.store.records_since_snapshot = len(records)
        self._import_legacy_objects()
        if state is None and not records:
            # first boot: persist boot_id immediately — a crash before the
            # first compaction must not mint a new identity (consumers'
            # seq baselines key off it)
            self.store.snapshot(self._state())
        self.store.open_wal()

    def _import_legacy_objects(self) -> None:
        """In-place upgrade path: earlier hub versions persisted ONLY the
        object store, as ``data_dir/<bucket>/<file>`` blobs. Import any
        such blob absent from the recovered state so router snapshots /
        model cards written by the old layout survive the upgrade. (The
        old layout flattened '/' in names to '_'; blobs are imported
        under the flattened name, matching how the old server read them
        back from disk.)"""
        imported = 0
        for bucket_dir in sorted(self.store.dir.iterdir()):
            if not bucket_dir.is_dir():
                continue
            for f in sorted(bucket_dir.iterdir()):
                key = (bucket_dir.name, f.name)
                if f.is_file() and key not in self._objects:
                    data = f.read_bytes()
                    self._objects[key] = data
                    self._log(
                        {"op": "obj", "b": key[0], "n": key[1], "d": data}
                    )
                    imported += 1
        if imported:
            log.info("hub: imported %d legacy object blobs", imported)

    # -- state <-> snapshot ------------------------------------------------

    def _state(self) -> dict[str, Any]:
        now = time.monotonic()
        return {
            "boot_id": self.boot_id,
            # replication identity: stream position + leadership term. A
            # follower bootstrapping from this snapshot adopts all three
            # (boot_id included), making identity CLUSTER-wide so client
            # seq baselines stay valid across a failover.
            "wal_seq": self.wal_seq,
            "repl_epoch": self.repl_epoch,
            "repl_cursor": self.repl_cursor,
            "last_e": self.last_rec_epoch,
            "kv": dict(self._kv),
            "key_lease": dict(self._key_lease),
            "leases": [
                # remaining ttl not persisted: restore resets to full ttl
                {"id": l.lease_id, "ttl": l.ttl}
                for l in self._leases.values()
                if self._lease_snapshot_live(l, now)
            ],
            "next_lease": self._next_lease,
            "subject_seq": dict(self._subject_seq),
            # publish-dedup window: persists so a client retry landing
            # after restart+compaction still dedups
            "pub_ids": list(self._seen_pub_ids),
            "retained": {
                subj: list(dq) for subj, dq in self._retained.items()
            },
            "objects": [
                [b, n, d] for (b, n), d in self._objects.items()
            ],
        }

    def _lease_snapshot_live(self, lease: Any, now: float) -> bool:
        """Should this lease survive into a snapshot? The local deadline
        is authoritative on a single (or leader) hub; replication
        followers override — their deadlines are stale by design, since
        keepalives are never replicated and expiry arrives as the
        leader's revoke record."""
        return lease.deadline > now

    def _restore(self, state: dict[str, Any]) -> None:
        from collections import deque

        self.boot_id = state["boot_id"]
        # .get: pre-replication snapshots carry none of these
        self.wal_seq = int(state.get("wal_seq", 0))
        self.repl_epoch = int(state.get("repl_epoch", 0))
        self.repl_cursor = int(state.get("repl_cursor", 0))
        # pre-election snapshots: the minting leader's epoch is the best
        # available bound for its last record's term
        self.last_rec_epoch = int(state.get("last_e", self.repl_epoch))
        self._kv = dict(state["kv"])
        self._key_lease = dict(state["key_lease"])
        now = time.monotonic()
        for rec in state["leases"]:
            self._leases[rec["id"]] = _Lease(
                rec["id"], rec["ttl"], now + rec["ttl"]
            )
        # leases own their keys again (snapshot stores the binding map)
        for key, lid in self._key_lease.items():
            if lid in self._leases:
                self._leases[lid].keys.add(key)
        self._next_lease = state["next_lease"]
        self._subject_seq = dict(state["subject_seq"])
        from collections import OrderedDict

        # .get: pre-dedup snapshots have no pub_ids entry
        self._seen_pub_ids = OrderedDict(
            (pid, None) for pid in state.get("pub_ids", ())
        )
        self._retained = {
            subj: deque(
                (tuple(item) for item in items),
                maxlen=self.RETAIN_PER_SUBJECT,
            )
            for subj, items in state["retained"].items()
        }
        self._objects = {(b, n): d for b, n, d in state["objects"]}

    # -- WAL replay --------------------------------------------------------

    def _apply(self, rec: dict[str, Any]) -> None:
        """Re-apply one WAL record. Mirrors the mutation bodies exactly,
        minus logging/notification (no watchers or subscribers exist at
        recovery time) and minus anything needing a running loop."""
        op = rec["op"]
        # follower-logged records carry the leader wal_seq they replicate
        # ("rsq", hub_replica.py) so the replication cursor survives a
        # follower restart even for records not yet inside a snapshot
        rsq = rec.get("rsq")
        if rsq is not None:
            self.repl_cursor = max(self.repl_cursor, int(rsq))
        e = rec.get("e")
        if e is not None:
            self.last_rec_epoch = max(self.last_rec_epoch, int(e))
        if op == "put":
            key, lid = rec["k"], rec.get("l")
            if lid is not None and lid in self._leases:
                self._leases[lid].keys.add(key)
                self._key_lease[key] = lid
            self._kv[key] = rec["v"]
        elif op == "del":
            key = rec["k"]
            self._kv.pop(key, None)
            lid = self._key_lease.pop(key, None)
            if lid is not None and lid in self._leases:
                self._leases[lid].keys.discard(key)
        elif op == "lease":
            lid, ttl = rec["id"], rec["ttl"]
            self._leases[lid] = _Lease(lid, ttl, time.monotonic() + ttl)
            self._next_lease = max(self._next_lease, lid + 1)
        elif op == "revoke":
            lease = self._leases.get(rec["id"])
            if lease is not None:
                self._drop_lease(lease)
        elif op == "pub":
            subj = rec["s"]
            if not self._pub_id_fresh(rec.get("pid")):
                return  # replayed duplicate (same pid logged twice)
            if subj not in self._retained:
                from collections import deque

                self._retained[subj] = deque(maxlen=self.RETAIN_PER_SUBJECT)
            seq = self._subject_seq.get(subj, self._subject_seq_base()) + 1
            self._subject_seq[subj] = seq
            self._retained[subj].append((seq, rec["p"]))
        elif op == "purge":
            import fnmatch

            for subj in list(self._retained):
                if not fnmatch.fnmatchcase(subj, rec["s"]):
                    continue
                dq = self._retained[subj]
                upto = rec.get("upto")
                if upto is not None:
                    while dq and dq[0][0] <= upto:
                        dq.popleft()
                else:
                    while len(dq) > rec.get("keep", 0):
                        dq.popleft()
        elif op == "obj":
            self._objects[(rec["b"], rec["n"])] = rec["d"]
        elif op == "objdel":
            self._objects.pop((rec["b"], rec["n"]), None)
        elif op == "promote":
            # leadership transition (hub_replica.py): adopt the term and
            # re-apply the promotion seq gap so per-subject seqs stay
            # ahead of anything the dead leader might have minted
            self.repl_epoch = int(rec["epoch"])
            gap = int(rec.get("gap", 0))
            if gap:
                for subj in list(self._subject_seq):
                    self._subject_seq[subj] += gap
        else:  # forward-compat: ignore unknown records
            log.warning("hub WAL: unknown record op %r ignored", op)

    # -- logged mutations --------------------------------------------------

    def _commit_allowed(self, rec: dict[str, Any]) -> None:
        """Commit-time fencing hook: raise HubFenced to refuse logging
        ``rec``. The plain durable hub commits everything; the replicated
        hub (hub_replica.py) refuses records minted by a deposed leader."""

    def _log(self, rec: dict[str, Any]) -> int:
        self._commit_allowed(rec)
        if "sq" not in rec:
            # stamp the record's global stream position so a WAL is
            # self-describing for recovery and the replication invariant
            # checker (followers keep the leader's stamp: rsq == sq)
            rec = dict(rec, sq=self.wal_seq + 1)
        self.store.append(rec)
        self.wal_seq += 1
        self._recent.append((self.wal_seq, rec))
        if self._capture_log is not None:
            race.write("hub.capture_log")
            self._capture_log.append(rec)
        for q in self._repl_listeners:
            try:
                q.put_nowait((self.wal_seq, rec))
            except asyncio.QueueFull:
                # a stalled follower stream must not grow leader memory
                # without bound: mark it overflowed — the stream ends and
                # the follower re-syncs from its cursor (or a snapshot)
                q.repl_overflowed = True
        self._maybe_compact()
        return self.wal_seq

    # -- snapshot compaction ------------------------------------------------

    def _maybe_compact(self) -> None:
        since = self.store.records_since_snapshot
        if since < self.compact_every or self._closed:
            return
        if since >= self.compact_every * 4:
            # hard bound: a caller that never yields to the loop (or no
            # loop at all) must still get its WAL rotated eventually
            self._snapshot_inline()
            return
        if self._compacting:
            return
        try:
            asyncio.get_running_loop()  # probe: background mode needs a loop
        except RuntimeError:
            self._snapshot_inline()
            return
        self._compacting = True
        # spawn: the loop's weak task ref is not enough — a GC'd compaction
        # task would leave _compacting latched True and the WAL unbounded
        spawn(self._compact_bg(), name="hub-compact")

    def _snapshot_inline(self) -> None:
        """Inline snapshot on the mutation path (no-loop / hard-bound
        fallback): a compaction failure must not fail the mutation that
        tripped it — count it and keep serving on the uncompacted WAL."""
        try:
            self.store.snapshot(self._state())
        except Exception as e:  # noqa: BLE001 - counted + logged, survivable
            COMPACTION_FAILURES.inc()
            log.error("hub snapshot compaction failed (inline): %s", e)

    async def _compact_bg(self) -> None:
        """Background compaction: capture state synchronously, serialize +
        fsync it in a worker thread while mutations keep landing in the
        old-generation WAL, then commit (rotate + re-append the records
        captured during the write). The mutation path never blocks on
        snapshot I/O. A failure (disk error at the snapshot fsync, fault
        injection at ``hub.fsync``) is counted in
        ``dynamo_hub_compaction_failures_total`` and serving continues on
        the uncompacted WAL; the next threshold crossing retries."""
        try:
            while (
                not self._closed
                and self.store.records_since_snapshot >= self.compact_every
            ):
                state = self._state()
                pending: list[dict[str, Any]] = []
                race.write("hub.capture_log")
                self._capture_log = pending
                # the to_thread dispatch is the HB edge carrying the
                # captured ``state`` into the snapshot worker thread
                race.release(self.store, "hub.snapshot")
                try:
                    tmp, new_gen = await asyncio.to_thread(
                        self.store.write_snapshot_tmp, state
                    )
                    if self._closed or new_gen != self.store.gen + 1:
                        # closed, or the inline hard-bound snapshot
                        # rotated the gen while we serialized: our
                        # capture is stale (its pending records are
                        # already inside the newer snapshot) — discard
                        tmp.unlink(missing_ok=True)
                        if self._closed:
                            return
                        continue
                    self.store.commit_snapshot(tmp, new_gen, pending)
                except Exception as e:  # noqa: BLE001 - counted + logged:
                    # the WAL still holds every acked record, keep serving
                    COMPACTION_FAILURES.inc()
                    log.error(
                        "hub snapshot compaction failed (background): %s "
                        "— serving continues on the uncompacted WAL", e,
                    )
                    return
                finally:
                    self._capture_log = None
        finally:
            self._compacting = False

    def reap_expired(self, now: float | None = None) -> list[int]:
        # expiry IS logged (as a revoke): replication followers never run
        # the reaper — keepalives are not replicated, so only the leader
        # may decide a lease is dead — and they learn expiry from this
        # record. Recovery semantics are unchanged: a lease that expired
        # pre-crash is revoked by replay instead of re-expiring one TTL
        # after restart.
        expired = super().reap_expired(now)
        for lid in expired:
            self._log({"op": "revoke", "id": lid})
        return expired

    # Every mutator fences BEFORE touching state (the _log recheck is the
    # belt): raising after super() mutated would bounce the client while
    # local readers and watchers keep seeing a value that is in no WAL —
    # a crash-restart and a non-restart would then disagree.

    async def put(self, key: str, value: Any, lease_id: int | None = None) -> None:
        self._commit_allowed({"op": "put"})
        await super().put(key, value, lease_id)
        self._log({"op": "put", "k": key, "v": value, "l": lease_id})

    async def delete(self, key: str) -> bool:
        self._commit_allowed({"op": "del"})
        existed = await super().delete(key)
        if existed:
            self._log({"op": "del", "k": key})
        return existed

    async def grant_lease(self, ttl_s: float) -> int:
        self._commit_allowed({"op": "lease"})
        lid = await super().grant_lease(ttl_s)
        self._log({"op": "lease", "id": lid, "ttl": ttl_s})
        return lid

    async def revoke_lease(self, lease_id: int) -> None:
        self._commit_allowed({"op": "revoke"})
        existed = lease_id in self._leases
        await super().revoke_lease(lease_id)
        if existed:
            self._log({"op": "revoke", "id": lease_id})
        # lease EXPIRY is also logged as a revoke (see reap_expired): the
        # replication stream must carry it, since followers never reap

    async def publish(
        self, subject: str, payload: Any, pub_id: str | None = None
    ) -> bool:
        self._commit_allowed({"op": "pub"})
        applied = await super().publish(subject, payload, pub_id)
        if applied:
            # pid rides in the WAL so a retry that lands AFTER a hub
            # restart (which replayed the original record) still dedups
            rec = {"op": "pub", "s": subject, "p": payload}
            if pub_id is not None:
                rec["pid"] = pub_id
            self._log(rec)
        return applied

    async def purge_subject(
        self, subject: str, keep_last: int = 0,
        up_to_seq: int | None = None,
    ) -> int:
        self._commit_allowed({"op": "purge"})
        dropped = await super().purge_subject(
            subject, keep_last, up_to_seq=up_to_seq
        )
        if dropped:
            self._log({
                "op": "purge", "s": subject, "keep": keep_last,
                "upto": up_to_seq,
            })
        return dropped

    async def put_object(self, bucket: str, name: str, data: bytes) -> None:
        self._commit_allowed({"op": "obj"})
        await super().put_object(bucket, name, data)
        self._log({"op": "obj", "b": bucket, "n": name, "d": bytes(data)})

    async def delete_object(self, bucket: str, name: str) -> None:
        self._commit_allowed({"op": "objdel"})
        existed = (bucket, name) in self._objects
        await super().delete_object(bucket, name)
        if existed:
            self._log({"op": "objdel", "b": bucket, "n": name})

    async def close(self) -> None:
        await super().close()
        self.store.close()
