"""Endpoint picker (EPP): cluster-native KV-aware routing decisions for
an inference gateway.

The reference integrates with the Kubernetes Gateway API inference
extension by patching the upstream EPP with a ``dyn-kv`` plugin whose
decision comes from the dynamo router (ref
deploy/inference-gateway/README.md + epp-patches/ — the plugin's selling
point over the stock EPP is MODEL-AWARE tokenization: the router runs
the deployed model's tokenizer inline instead of a generic
approximation). Here the picker IS the router, served over HTTP:

  POST /pick   {"model": ..., "prompt": ...}        (or "token_ids")
        -> 200 {"worker_id": ..., "endpoint": "host:port",
                "overlap_blocks": N}
           + x-gateway-destination-endpoint: host:port   (GIE header
           convention — ext-proc based gateways copy it onto the
           upstream route)
  GET  /healthz -> 200

The prompt tokenizes with the TARGET MODEL's tokenizer (discovered from
its model card), the KV router scores workers by radix overlap + load,
and the instance registry resolves the winner's serving address. Run as
``python -m dynamo_tpu.gateway --hub ... --component backend``;
deploy/inference-gateway/ has the manifests wiring it behind an
HTTPRoute/InferencePool.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
from typing import Any

from aiohttp import web

from dynamo_tpu.kv_router.protocols import RouterConfig
from dynamo_tpu.kv_router.router import KvRouter
from dynamo_tpu.runtime.component import INSTANCE_ROOT, Instance

log = logging.getLogger("dynamo.gateway.epp")


class EndpointPicker:
    def __init__(
        self,
        drt,
        *,
        namespace: str = "dynamo",
        target_component: str = "backend",
        target_endpoint: str = "generate",
        config: RouterConfig | None = None,
        host: str = "0.0.0.0",
        port: int = 9002,
    ):
        self.drt = drt
        self.namespace = namespace
        self.target_component = target_component
        self.target_endpoint = target_endpoint
        self.config = config
        self.host = host
        self.port = port
        self.kv: KvRouter | None = None
        self._tokenizers: dict[str, Any] = {}
        self._runner: web.AppRunner | None = None
        self.picks = 0

    async def start(self) -> "EndpointPicker":
        self.kv = await KvRouter(
            self.drt.hub,
            f"{self.namespace}/{self.target_component}",
            self.config,
        ).start()
        app = web.Application()
        app.router.add_post("/pick", self._pick)
        app.router.add_get("/healthz", self._healthz)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            self.port = s._server.sockets[0].getsockname()[1]
        log.info("EPP listening on %s:%d (target %s/%s)",
                 self.host, self.port, self.namespace,
                 self.target_component)
        return self

    # -- helpers -----------------------------------------------------------

    async def _tokenizer_for(self, model: str | None):
        """The deployed model's OWN tokenizer, from its model card — the
        dyn-kv plugin's advantage over generic-tokenizer EPPs. A NAMED
        model with no matching card returns None (the route 404s): a
        typo'd name must not silently tokenize with the mock fallback
        and return confidently wrong block hashes/overlap estimates.
        Only an OMITTED model may fall back to the first card (or the
        mock tokenizer when no cards exist yet)."""
        from dynamo_tpu.frontend.model_card import MDC_ROOT
        from dynamo_tpu.frontend.tokenizer import load_tokenizer

        cards = await self.drt.hub.get_prefix(MDC_ROOT + "/")
        card = None
        for _key, value in sorted(cards.items()):
            if model is None or value.get("name") == model:
                card = value
                break
        if model is not None and card is None:
            return None  # unknown model: the caller 404s
        tok_name = (card or {}).get("tokenizer", "mock")
        if tok_name not in self._tokenizers:
            self._tokenizers[tok_name] = load_tokenizer(tok_name)
        return self._tokenizers[tok_name]

    async def _endpoint_of(self, worker_id: int) -> str | None:
        prefix = (
            f"{INSTANCE_ROOT}/{self.namespace}/{self.target_component}/"
            f"{self.target_endpoint}/"
        )
        entries = await self.drt.hub.get_prefix(prefix)
        for _key, raw in entries.items():
            inst = Instance.from_dict(raw)
            if inst.instance_id == worker_id:
                return f"{inst.host}:{inst.port}"
        return None

    # -- routes ------------------------------------------------------------

    async def _healthz(self, _req: web.Request) -> web.Response:
        return web.json_response({"status": "ok", "picks": self.picks})

    async def _pick(self, req: web.Request) -> web.Response:
        try:
            body = await req.json()
        # dynalint: disable=DL003 -- mapped to a typed 400 response; the
        # client sees exactly what failed, nothing is swallowed
        except Exception:  # noqa: BLE001
            return web.json_response(
                {"error": "body must be JSON"}, status=400
            )
        token_ids = body.get("token_ids")
        if token_ids is None:
            prompt = body.get("prompt")
            if not isinstance(prompt, str):
                return web.json_response(
                    {"error": "one of token_ids or prompt is required"},
                    status=400,
                )
            tok = await self._tokenizer_for(body.get("model"))
            if tok is None:
                return web.json_response(
                    {"error": f"no model card named "
                              f"{body.get('model')!r}"},
                    status=404,
                )
            token_ids = tok.encode(prompt)
        rid = body.get("request_id", "epp")
        try:
            # decision-only probe: find + free, like the router service's
            # best_worker endpoint (kv_router/service.py)
            worker_id, overlap = self.kv.find_best_match(
                rid, list(token_ids)
            )
            self.kv.free(rid)
        except Exception as e:  # noqa: BLE001 — no workers yet
            return web.json_response(
                {"error": f"no routable worker: {e}"}, status=503
            )
        endpoint = await self._endpoint_of(worker_id)
        if endpoint is None:
            return web.json_response(
                {"error": f"worker {worker_id:x} has no registered "
                          "instance"},
                status=503,
            )
        self.picks += 1
        return web.json_response(
            {
                "worker_id": worker_id,
                "endpoint": endpoint,
                "overlap_blocks": overlap,
            },
            headers={"x-gateway-destination-endpoint": endpoint},
        )

    async def close(self) -> None:
        if self.kv is not None:
            await self.kv.save_snapshot()
            await self.kv.close()
        if self._runner is not None:
            await self._runner.cleanup()


async def _amain(args: argparse.Namespace) -> None:
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.hub_client import connect_hub

    rcfg = RuntimeConfig.from_env()
    if args.hub:
        rcfg.override_hub(args.hub)
    drt = DistributedRuntime(await connect_hub(rcfg.hub_target()), rcfg)
    epp = await EndpointPicker(
        drt,
        namespace=args.namespace,
        target_component=args.component,
        target_endpoint=args.endpoint,
        config=RouterConfig(block_size=args.block_size),
        host=args.host,
        port=args.port,
    ).start()
    print(f"DYNAMO_EPP={epp.host}:{epp.port}", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await epp.close()
        await drt.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser("dynamo-tpu endpoint picker (EPP)")
    p.add_argument("--hub", default=None)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="backend")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9002)
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    try:
        asyncio.run(_amain(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
