"""Endpoint picker (EPP): cluster-native KV-aware routing decisions for
an inference gateway.

The reference integrates with the Kubernetes Gateway API inference
extension by patching the upstream EPP with a ``dyn-kv`` plugin whose
decision comes from the dynamo router (ref
deploy/inference-gateway/README.md + epp-patches/ — the plugin's selling
point over the stock EPP is MODEL-AWARE tokenization: the router runs
the deployed model's tokenizer inline instead of a generic
approximation). Here the picker IS the router, served over HTTP:

  POST /pick   {"model": ..., "prompt": ...}        (or "token_ids")
        -> 200 {"worker_id": ..., "endpoint": "host:port",
                "overlap_blocks": N}
           + x-gateway-destination-endpoint: host:port   (GIE header
           convention — ext-proc based gateways copy it onto the
           upstream route)
  GET  /healthz -> 200

The prompt tokenizes with the TARGET MODEL's tokenizer (discovered from
its model card), the KV router scores workers by radix overlap + load,
and the instance registry resolves the winner's serving address. Run as
``python -m dynamo_tpu.gateway --hub ... --component backend``;
deploy/inference-gateway/ has the manifests wiring it behind an
HTTPRoute/InferencePool.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import time
from typing import Any

from aiohttp import web

from dynamo_tpu.gateway.breaker import BreakerBoard, BreakerConfig
from dynamo_tpu.kv_router.protocols import RouterConfig
from dynamo_tpu.kv_router.router import KvRouter
from dynamo_tpu.kv_router.sharding import shards_from_env
from dynamo_tpu.runtime import tracing
from dynamo_tpu.runtime.context import TENANT_HEADER
from dynamo_tpu.runtime.component import INSTANCE_ROOT, Instance
from dynamo_tpu.runtime.faults import FAULTS
from dynamo_tpu.runtime.health import DegradationDetector, is_quarantined
from dynamo_tpu.runtime.metrics import MetricsRegistry

log = logging.getLogger("dynamo.gateway.epp")


class _PrefixCache:
    """Hub-watch-invalidated snapshot of one key prefix, with a TTL
    backstop (ROADMAP #7 EPP slice: steady-state picks must do ZERO hub
    round-trips — at 100s of instances the per-pick ``get_prefix`` scan
    was the pick-latency floor). A watch event clears the snapshot
    immediately (new/removed cards and instances land within one hub
    watch delivery); the TTL bounds staleness when the watch stream is
    down and the hub only answers plain RPCs."""

    def __init__(self, hub, prefix: str, ttl_s: float, on_lookup=None):
        self.hub = hub
        self.prefix = prefix
        self.ttl_s = ttl_s
        # observability hook: called with "hit" | "miss" per get() (the
        # EPP bridges it into dynamo_epp_cache_lookups_total)
        self.on_lookup = on_lookup
        self._snap: dict[str, Any] | None = None
        self._expiry = 0.0
        # invalidation generation: a watch event arriving WHILE a scan
        # is in flight must not be overwritten by that scan's (pre-event)
        # result — the refill only installs its snapshot if no
        # invalidate() happened since it started
        self._gen = 0
        # single-flight refill: N concurrent picks missing the cache
        # share ONE hub scan instead of issuing a burst of N
        self._refill: asyncio.Task | None = None
        self.scans = 0  # hub round-trips actually paid (observability)

    async def get(self) -> dict[str, Any]:
        hit = self._snap is not None and time.monotonic() < self._expiry
        if self.on_lookup is not None:
            self.on_lookup("hit" if hit else "miss")
        if hit:
            return self._snap
        if self._refill is None or self._refill.done():
            self._refill = asyncio.get_running_loop().create_task(
                self._do_refill()
            )
        return await self._refill

    async def _do_refill(self) -> dict[str, Any]:
        gen = self._gen
        snap = await self.hub.get_prefix(self.prefix)
        self.scans += 1
        if gen == self._gen:
            self._snap = snap
            self._expiry = time.monotonic() + self.ttl_s
        # an invalidation raced the scan: serve this (possibly pre-event)
        # snapshot to the waiters but do NOT cache it — the next get()
        # re-scans and sees the post-event state
        return snap

    def invalidate(self) -> None:
        self._gen += 1
        self._snap = None

    async def watch(self) -> None:
        """Invalidation loop (spawned by start()): any event under the
        prefix drops the snapshot. A dead hub ends the loop — the TTL
        keeps answers bounded-stale until the process is restarted or
        the hub returns on the RPC path."""
        try:
            async for _ev in self.hub.watch_prefix(
                self.prefix, initial=False
            ):
                self.invalidate()
        except (ConnectionError, RuntimeError) as e:
            log.warning(
                "EPP watch on %r ended (%s); falling back to the %.1fs "
                "TTL", self.prefix, e, self.ttl_s,
            )


class EndpointPicker:
    def __init__(
        self,
        drt,
        *,
        namespace: str = "dynamo",
        target_component: str = "backend",
        target_endpoint: str = "generate",
        config: RouterConfig | None = None,
        host: str = "0.0.0.0",
        port: int = 9002,
        card_ttl_s: float = 2.0,
        breaker_config: "BreakerConfig | None" = None,
        pick_port: int | None = None,
        shard_id: int = 0,
        shards: int = 1,
    ):
        self.drt = drt
        self.namespace = namespace
        self.target_component = target_component
        self.target_endpoint = target_endpoint
        self.config = config
        self.host = host
        self.port = port
        # pickline fast path: persistent-connection newline-JSON picks
        # (gateway/pickline.py); None = disabled, 0 = ephemeral port
        self.pick_port = pick_port
        self._pickline = None
        # prefix-hash sharding (kv_router/sharding.py ShardMap): which
        # shard of the routing data plane this process serves — purely
        # observational here (the map lives at the dispatcher), exported
        # as the dynamo_router_shard_id gauge
        self.shard_id = shard_id
        self.shards = shards
        self.kv: KvRouter | None = None
        self._tokenizers: dict[str, Any] = {}
        self._runner: web.AppRunner | None = None
        self.picks = 0
        # pick-path telemetry (complements PR 9's hub_scans healthz
        # field): pick latency histogram + per-cache hit/miss counters,
        # served on this process's /metrics route
        self.metrics = MetricsRegistry()
        self._m_pick = self.metrics.histogram(
            "epp_pick_seconds", "EPP pick-path latency",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0),
        )
        self._m_cache = self.metrics.counter(
            "epp_cache_lookups_total",
            "pick-path prefix-cache lookups", ["cache", "outcome"],
        )
        # per-instance circuit breakers (gateway/breaker.py): rolling
        # error/latency scoring over reported pick outcomes; OPEN
        # instances are excluded from picks, half-open probes re-admit
        # recovered workers. State gauge: 0 closed / 1 half-open / 2 open.
        self._m_breaker = self.metrics.gauge(
            "epp_breaker_state",
            "per-instance circuit-breaker state "
            "(0 closed, 1 half-open, 2 open)", ["instance"],
        )
        self.breakers = BreakerBoard(
            breaker_config or BreakerConfig(),
            on_state=lambda iid, st: self._m_breaker.labels(
                f"{iid:x}"
            ).set(st),
            on_forget=self._drop_breaker_series,
        )
        # pick-path caches: model cards (tokenizer resolution) and
        # instance records (winner address) — both watch-invalidated
        # with a TTL backstop, so a steady-state pick touches the hub
        # zero times (tests/test_gateway_epp.py micro-benchmark)
        from dynamo_tpu.frontend.model_card import MDC_ROOT

        self._cards = _PrefixCache(
            drt.hub, MDC_ROOT + "/", card_ttl_s,
            on_lookup=lambda o: self._m_cache.labels("cards", o).inc(),
        )
        self._instances = _PrefixCache(
            drt.hub,
            f"{INSTANCE_ROOT}/{namespace}/{target_component}/"
            f"{target_endpoint}/",
            card_ttl_s,
            on_lookup=lambda o: self._m_cache.labels("instances", o).inc(),
        )
        # per-snapshot endpoint memo (see _endpoint_of)
        self._ep_snapshot: dict | None = None
        self._ep_map: dict[int, str] = {}
        # gray-failure plane: quarantined instance ids (from the card
        # metadata the health plane flips) and peer-relative straggler
        # scoring over the step_time_ms fingerprints workers publish —
        # both join the breaker exclude= set, inheriting its fail-open
        self._quarantined_ids: set[int] = set()
        self.degradation = DegradationDetector()
        self.degradation.export_metrics()
        self._watch_tasks: list[asyncio.Task] = []

    async def start(self) -> "EndpointPicker":
        from dynamo_tpu.kv_router.router import ROUTER_SHARD_GAUGE
        from dynamo_tpu.runtime.context import spawn

        self.kv = await KvRouter(
            self.drt.hub,
            f"{self.namespace}/{self.target_component}",
            self.config,
        ).start()
        ROUTER_SHARD_GAUGE.set(self.shard_id)
        self._watch_tasks = [
            spawn(self._cards.watch(), name="epp-cards-watch"),
            spawn(self._instances.watch(), name="epp-instances-watch"),
        ]
        if self.pick_port is not None:
            from dynamo_tpu.gateway.pickline import PickLineServer

            self._pickline = await PickLineServer(
                self, host=self.host, port=self.pick_port,
            ).start()
            self.pick_port = self._pickline.port
        app = web.Application()
        app.router.add_post("/pick", self._pick)
        app.router.add_post("/report", self._report)
        app.router.add_get("/healthz", self._healthz)
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            self.port = s._server.sockets[0].getsockname()[1]
        log.info("EPP listening on %s:%d (target %s/%s)",
                 self.host, self.port, self.namespace,
                 self.target_component)
        return self

    # -- helpers -----------------------------------------------------------

    async def _tokenizer_for(self, model: str | None):
        """The deployed model's OWN tokenizer, from its model card — the
        dyn-kv plugin's advantage over generic-tokenizer EPPs. A NAMED
        model with no matching card returns None (the route 404s): a
        typo'd name must not silently tokenize with the mock fallback
        and return confidently wrong block hashes/overlap estimates.
        Only an OMITTED model may fall back to the first card (or the
        mock tokenizer when no cards exist yet).

        The card scan is served from the watch-invalidated cache: a new
        or removed card lands within one watch delivery (TTL-bounded
        when the watch is down), and a steady-state pick pays zero hub
        round-trips here."""
        from dynamo_tpu.frontend.tokenizer import load_tokenizer

        cards = await self._cards.get()
        card = None
        for _key, value in sorted(cards.items()):
            if model is None or value.get("name") == model:
                card = value
                break
        if model is not None and card is None:
            return None  # unknown model: the caller 404s
        tok_name = (card or {}).get("tokenizer", "mock")
        if tok_name not in self._tokenizers:
            self._tokenizers[tok_name] = load_tokenizer(tok_name)
        return self._tokenizers[tok_name]

    def _refresh_instance_memo(self, entries: dict) -> None:
        # memoized per snapshot object: re-parsing every Instance dict on
        # every pick made endpoint resolution an O(instances) tax on the
        # decision hot path. The same parse harvests quarantine flags.
        if entries is self._ep_snapshot:
            return
        self._ep_map = {}
        self._quarantined_ids = set()
        for raw in entries.values():
            inst = Instance.from_dict(raw)
            self._ep_map[inst.instance_id] = f"{inst.host}:{inst.port}"
            if is_quarantined(inst):
                self._quarantined_ids.add(inst.instance_id)
        self._ep_snapshot = entries

    async def _gray_excluded(self) -> set[int]:
        """Soft-withdrawn capacity: quarantined instance cards plus
        workers the DegradationDetector scores as stragglers. Joined to
        the breaker exclusions, so the scheduler's fail-open (serve
        SOMEONE rather than no one) covers gray failures too."""
        self._refresh_instance_memo(await self._instances.get())
        if self.kv is not None:
            for w in self.kv.scheduler.workers():
                self.degradation.observe(w.worker_id, w.metrics.step_time_ms)
        return self._quarantined_ids | set(self.degradation.degraded())

    async def _endpoint_of(self, worker_id: int) -> str | None:
        # second attempt after a forced re-scan: the router may know a
        # winner the cached snapshot predates (fresh worker between
        # watch deliveries) — one refetch before answering 503
        for attempt in range(2):
            self._refresh_instance_memo(await self._instances.get())
            endpoint = self._ep_map.get(worker_id)
            if endpoint is not None:
                return endpoint
            if attempt == 0:
                self._instances.invalidate()
        return None

    # -- routes ------------------------------------------------------------

    async def _healthz(self, _req: web.Request) -> web.Response:
        return web.json_response({
            "status": "ok",
            "picks": self.picks,
            # hub round-trips actually paid for cards/instances: with
            # the pick-path caches warm this stays flat while picks grow
            "hub_scans": self._cards.scans + self._instances.scans,
            "shard": self.shard_id,
            "shards": self.shards,
            "pick_port": self.pick_port,
        })

    async def _metrics(self, _req: web.Request) -> web.Response:
        return web.Response(
            body=self.metrics.exposition(),
            content_type="text/plain",
            charset="utf-8",
        )

    def observe_pick(self, seconds: float) -> None:
        """Record one pick-path latency (shared with the pickline
        transport, which has no aiohttp middleware to hook)."""
        self._m_pick.observe(seconds)

    async def _pick(self, req: web.Request) -> web.Response:
        """One routing decision. Joined to the caller's W3C trace when a
        ``traceparent`` header rides along (the GIE ext-proc forwards
        request headers), so the pick shows up in the same trace as the
        completion it routed; latency lands in dynamo_epp_pick_seconds
        either way."""
        t0 = time.monotonic()
        tracing.bind_trace(req.headers)
        with tracing.span("epp.pick"):
            try:
                return await self._pick_inner(req)
            finally:
                self._m_pick.observe(time.monotonic() - t0)

    async def _pick_inner(self, req: web.Request) -> web.Response:
        try:
            body = await req.json()
        # dynalint: disable=DL003 -- mapped to a typed 400 response; the
        # client sees exactly what failed, nothing is swallowed
        except Exception:  # noqa: BLE001
            return web.json_response(
                {"error": "body must be JSON"}, status=400
            )
        status, payload, headers = await self.pick_decision(body)
        return web.json_response(payload, status=status, headers=headers)

    async def pick_decision(
        self, body: dict
    ) -> tuple[int, dict, dict]:
        """ONE routing decision from a parsed /pick body — the shared
        core of the aiohttp route and the pickline fast path. Returns
        (http_status, response_payload, response_headers)."""
        token_ids = body.get("token_ids")
        if token_ids is None:
            prompt = body.get("prompt")
            if not isinstance(prompt, str):
                return 400, {
                    "error": "one of token_ids or prompt is required"
                }, {}
            tok = await self._tokenizer_for(body.get("model"))
            if tok is None:
                return 404, {
                    "error": f"no model card named {body.get('model')!r}"
                }, {}
            token_ids = tok.encode(prompt)
        rid = body.get("request_id", "epp")
        try:
            # decision-only probe: find + free, like the router service's
            # best_worker endpoint (kv_router/service.py). Breaker-
            # ejected instances are excluded from the candidate set;
            # a HALF-OPEN winner consumes a probe slot via allow() and,
            # when its probe budget is spent, the pick re-runs with it
            # excluded too (fail open when exclusions empty the pool).
            if self.picks and self.picks % 256 == 0:
                # periodic breaker GC: drop state (and gauge series) for
                # instances that left the fleet, so worker churn cannot
                # grow the board without bound
                self.breakers.forget(self._live_instance_ids())
            excluded = set(self.breakers.ejected()) | (
                await self._gray_excluded()
            )
            # enough attempts to walk past every breaker-limited
            # instance before fail-open kicks in — a constant cap would
            # route to a disallowed worker while healthy ones remain
            attempts = max(3, len(self._live_instance_ids()) + 1)
            # tenant tag for cluster-level steering: an explicit body
            # field wins, else the forwarded request headers (the GIE
            # ext-proc sends them along). Absent tag = no steering.
            tenant = (
                body.get("tenant")
                or (body.get("headers") or {}).get(TENANT_HEADER)
                or None
            )
            for _attempt in range(attempts):
                worker_id, overlap = self.kv.find_best_match(
                    rid, list(token_ids), exclude=excluded or None,
                    tenant=tenant,
                )
                self.kv.free(rid)
                if worker_id in excluded or self.breakers.allow(worker_id):
                    # in `excluded` means the exclusion was overridden
                    # (it would have emptied the pool): serve fail-open
                    break
                excluded = set(excluded) | {worker_id}
        except Exception as e:  # noqa: BLE001 — no workers yet
            return 503, {"error": f"no routable worker: {e}"}, {}
        if FAULTS.enabled:
            try:
                # chaos hook: an injected error at epp.breaker records a
                # FAILURE outcome against the picked instance — a sick
                # worker simulated at the scoring layer, so schedules can
                # prove eject -> brownout -> half-open -> recovery
                # without a genuinely broken engine
                await FAULTS.fire("epp.breaker")
            except Exception as e:  # noqa: BLE001 - injected outcome
                log.warning(
                    "epp.breaker fault: recording failure against %x "
                    "(%s)", worker_id, e,
                )
                self.breakers.record(worker_id, ok=False)
        endpoint = await self._endpoint_of(worker_id)
        if endpoint is None:
            return 503, {
                "error": f"worker {worker_id:x} has no registered "
                         "instance"
            }, {}
        self.picks += 1
        payload = {
            "worker_id": worker_id,
            "endpoint": endpoint,
            "overlap_blocks": overlap,
        }
        if self.shards > 1:
            payload["shard"] = self.shard_id
        return 200, payload, {"x-gateway-destination-endpoint": endpoint}

    def _drop_breaker_series(self, iid: int) -> None:
        """Remove a departed instance's epp_breaker_state series — a
        phantom 'open' gauge for a worker that no longer exists would
        mislead every dashboard built on it."""
        try:
            self._m_breaker.remove(f"{iid:x}")
        except KeyError:
            pass  # series never materialized for this instance

    def _live_instance_ids(self) -> set[int]:
        """Worker ids the router currently schedules over (its instance
        watch keeps this current) — the breaker board's membership."""
        if self.kv is None:
            return set()
        return {w.worker_id for w in self.kv.scheduler.workers()}

    async def _report(self, req: web.Request) -> web.Response:
        """Outcome feedback for the circuit breakers: the gateway (or
        any caller that acted on a /pick) posts what actually happened
        to the routed request::

            POST /report {"worker_id": N | "hex", "ok": bool,
                          "latency_ms": float}

        Errors and over-SLO latencies push the instance toward OPEN
        (ejected from picks); successes close a half-open breaker."""
        try:
            body = await req.json()
        # dynalint: disable=DL003 -- mapped to a typed 400 response
        except Exception:  # noqa: BLE001
            return web.json_response(
                {"error": "body must be JSON"}, status=400
            )
        raw = body.get("worker_id")
        try:
            worker_id = int(raw, 16) if isinstance(raw, str) else int(raw)
        except (TypeError, ValueError):
            return web.json_response(
                {"error": "worker_id must be an int or hex string"},
                status=400,
            )
        ok = body.get("ok")
        if not isinstance(ok, bool):
            return web.json_response(
                {"error": "ok must be a boolean"}, status=400
            )
        try:
            latency_s = float(body.get("latency_ms") or 0.0) / 1000.0
        except (TypeError, ValueError):
            return web.json_response(
                {"error": "latency_ms must be a number"}, status=400
            )
        if (
            worker_id not in self._live_instance_ids()
            and not self.breakers.knows(worker_id)
        ):
            # reports only mint breaker state for instances the router
            # actually knows (or already-tracked ones mid-deregistration)
            # — arbitrary caller-supplied ids must not grow the board
            return web.json_response(
                {"error": f"unknown worker {worker_id:x}"}, status=404
            )
        self.breakers.record(worker_id, ok, latency_s)
        return web.json_response({
            "worker_id": worker_id,
            "state": self.breakers.state_name(worker_id),
        })

    async def close(self) -> None:
        for t in self._watch_tasks:
            t.cancel()
        if self._pickline is not None:
            await self._pickline.close()
        if self.kv is not None:
            await self.kv.save_snapshot()
            await self.kv.close()
        if self._runner is not None:
            await self._runner.cleanup()


async def _amain(args: argparse.Namespace) -> None:
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.hub_client import connect_hub

    rcfg = RuntimeConfig.from_env()
    if args.hub:
        rcfg.override_hub(args.hub)
    drt = DistributedRuntime(await connect_hub(rcfg.hub_target()), rcfg)
    epp = await EndpointPicker(
        drt,
        namespace=args.namespace,
        target_component=args.component,
        target_endpoint=args.endpoint,
        config=RouterConfig(block_size=args.block_size),
        host=args.host,
        port=args.port,
        pick_port=args.pick_port if args.pick_port >= 0 else None,
        shard_id=args.shard_id or 0,
        shards=args.shards,
    ).start()
    print(f"DYNAMO_EPP={epp.host}:{epp.port}", flush=True)
    if epp.pick_port is not None:
        print(f"DYNAMO_EPP_PICK={epp.host}:{epp.pick_port}", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await epp.close()
        await drt.close()


def shard_child_argv(args: argparse.Namespace, shard_id: int) -> list[str]:
    """argv for one spawned shard sibling: same deployment knobs, its
    own --shard-id, and ports offset by shard id (0 stays 0 =
    ephemeral). Split out so the supervisor's fan-out is unit-testable
    without spawning anything."""
    import sys

    argv = [
        sys.executable, "-m", "dynamo_tpu.gateway",
        "--namespace", args.namespace,
        "--component", args.component,
        "--endpoint", args.endpoint,
        "--block-size", str(args.block_size),
        "--host", args.host,
        "--port", str(args.port + shard_id if args.port else 0),
        "--shards", str(args.shards),
        "--shard-id", str(shard_id),
    ]
    if args.hub:
        argv += ["--hub", args.hub]
    if args.pick_port >= 0:
        argv += ["--pick-port",
                 str(args.pick_port + shard_id if args.pick_port else 0)]
    return argv


def _run_shard_supervisor(args: argparse.Namespace) -> int:
    """``--shards N`` with no explicit --shard-id: spawn one EPP process
    per shard (each running the FULL router state off the same hub
    watch; dispatchers map picks to shards with
    kv_router.sharding.ShardMap) and babysit them — one dying takes the
    set down so the deployment restarts it whole. SIGTERM/SIGINT tear
    the children down too: SIGTERM's default disposition would kill
    only the supervisor and orphan the shards (observed live — orphans
    held the ports and wedged the next deployment)."""
    import signal
    import subprocess

    def _bail(_sig, _frm):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _bail)
    signal.signal(signal.SIGINT, _bail)
    # spawn INSIDE the try: a Popen failing mid-fan-out (ENOMEM, exec
    # error) must still tear down the shards already started, or they
    # orphan holding the ports — the exact wedge this supervisor's
    # SIGTERM handling exists to prevent
    procs: list = []
    rc = 0
    try:
        for i in range(args.shards):
            procs.append(subprocess.Popen(shard_child_argv(args, i)))
        while True:
            for p in procs:
                code = p.poll()
                if code is not None:
                    rc = code or 1
                    raise KeyboardInterrupt
            # dynalint: disable=DL001 -- supervisor entrypoint: runs
            # INSTEAD of asyncio.run (no event loop exists in this
            # process), purely babysitting shard subprocesses
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            # dynalint: disable=DL003 -- last-resort teardown: a shard
            # that ignores SIGTERM for 10s gets SIGKILLed; escalation IS
            # the handling
            except Exception:  # noqa: BLE001
                p.kill()
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser("dynamo-tpu endpoint picker (EPP)")
    p.add_argument("--hub", default=None)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="backend")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9002)
    p.add_argument("--pick-port", type=int, default=-1,
                   help="pickline fast-path port (0 = ephemeral; "
                        "omit to disable)")
    p.add_argument("--shards", type=int,
                   default=shards_from_env(),
                   help="prefix-hash shard count (DYN_ROUTER_SHARDS); "
                        ">1 without --shard-id spawns one EPP process "
                        "per shard")
    p.add_argument("--shard-id", type=int, default=None,
                   help="which shard THIS process serves (0-based)")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.shards > 1 and args.shard_id is None:
        return _run_shard_supervisor(args)
    from dynamo_tpu.runtime.eventloop import maybe_install_uvloop

    maybe_install_uvloop()
    try:
        asyncio.run(_amain(args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
