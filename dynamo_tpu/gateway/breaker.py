"""Per-instance circuit breakers for the pick path (EPP / routers).

A sick worker — crashing handlers, pathological latency, a wedged step
thread — keeps its instance key alive as long as its lease holds, so
pure liveness-based routing feeds it every Nth request until the lease
reaper or a human notices. The breaker closes that gap with the classic
three-state machine driven by OBSERVED OUTCOMES (error/latency scoring
over a rolling window), so a sick worker browns out within a window's
worth of traffic and is re-admitted by probes once it recovers:

  CLOSED     normal routing; outcomes recorded into the rolling window.
             Trips OPEN when the window holds >= ``min_samples`` and the
             failure score (errors + over-SLO latencies, each weighted
             1.0) exceeds ``failure_threshold``.
  OPEN       excluded from picks for ``open_cooldown_s``; after the
             cooldown the breaker moves to HALF-OPEN.
  HALF-OPEN  up to ``half_open_probes`` picks are allowed through as
             probes. A failure re-opens (fresh cooldown); enough
             successes (``close_after`` consecutive) close the breaker
             and clear the window.

State is exported as ``dynamo_epp_breaker_state{instance}`` (0 closed,
1 half-open, 2 open) so dashboards can see a brownout AS a brownout.
The ``epp.breaker`` fault site (fired per recorded outcome at the
owning picker) lets chaos schedules force outcomes without a genuinely
sick worker.
"""

from __future__ import annotations

import collections
import logging
import time
from dataclasses import dataclass

log = logging.getLogger("dynamo.gateway.breaker")

CLOSED, HALF_OPEN, OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", HALF_OPEN: "half_open", OPEN: "open"}


@dataclass
class BreakerConfig:
    window: int = 32  # rolling outcome window per instance (count)
    window_s: float = 60.0  # outcomes older than this age out
    min_samples: int = 8  # no verdicts off tiny samples
    failure_threshold: float = 0.5  # failure score fraction that trips
    latency_slo_s: float = 0.0  # >SLO latency counts as a failure; 0 = off
    open_cooldown_s: float = 10.0  # OPEN hold before half-open probing
    half_open_probes: int = 2  # concurrent-ish probes allowed half-open
    close_after: int = 2  # consecutive probe successes that close
    # a half-open probe whose outcome is never reported (the /report
    # feedback is best-effort: the caller may crash or just not report)
    # expires after this long, releasing its slot — without it a couple
    # of unreported probes would wedge the breaker HALF-OPEN forever
    probe_timeout_s: float = 30.0


class CircuitBreaker:
    """One instance's breaker. Single-threaded (event-loop) use."""

    def __init__(self, config: BreakerConfig | None = None):
        self.config = config or BreakerConfig()
        self._window: collections.deque = collections.deque(
            maxlen=self.config.window
        )  # (ts, failed)
        self._state = CLOSED
        self._opened_at = 0.0
        self._probes_inflight: list[float] = []  # admission timestamps
        self._probe_successes = 0

    # -- scoring -----------------------------------------------------------

    def _prune(self, now: float) -> None:
        horizon = now - self.config.window_s
        while self._window and self._window[0][0] < horizon:
            self._window.popleft()

    def _failure_frac(self, now: float) -> float:
        self._prune(now)
        if not self._window:
            return 0.0
        return sum(f for _t, f in self._window) / len(self._window)

    # -- transitions -------------------------------------------------------

    def record(
        self, ok: bool, latency_s: float = 0.0, now: float | None = None
    ) -> None:
        """Feed one observed outcome (a completed request, a failed
        dispatch, an injected chaos outcome)."""
        now = time.monotonic() if now is None else now
        cfg = self.config
        failed = (not ok) or (
            cfg.latency_slo_s > 0 and latency_s > cfg.latency_slo_s
        )
        if self._state == HALF_OPEN:
            if self._probes_inflight:
                self._probes_inflight.pop(0)
            if failed:
                # a failing probe re-opens with a fresh cooldown
                self._state = OPEN
                self._opened_at = now
                self._probe_successes = 0
                return
            self._probe_successes += 1
            if self._probe_successes >= cfg.close_after:
                self._state = CLOSED
                self._window.clear()
                self._probe_successes = 0
            return
        self._window.append((now, 1 if failed else 0))
        if self._state == CLOSED:
            if (
                len(self._window) >= cfg.min_samples
                and self._failure_frac(now) >= cfg.failure_threshold
            ):
                self._state = OPEN
                self._opened_at = now
                self._probe_successes = 0

    def allow(self, now: float | None = None) -> bool:
        """May this instance be picked right now? OPEN past its cooldown
        transitions to HALF-OPEN here (probe admission)."""
        now = time.monotonic() if now is None else now
        if self._state == CLOSED:
            return True
        if self._state == OPEN:
            if now - self._opened_at < self.config.open_cooldown_s:
                return False
            self._state = HALF_OPEN
            self._probes_inflight = []
            self._probe_successes = 0
        # HALF_OPEN: bounded probe admission; unreported probes expire
        # (feedback is best-effort) so the breaker can never wedge here
        horizon = now - self.config.probe_timeout_s
        self._probes_inflight = [
            t for t in self._probes_inflight if t >= horizon
        ]
        if len(self._probes_inflight) < self.config.half_open_probes:
            self._probes_inflight.append(now)
            return True
        return False

    @property
    def state(self) -> int:
        return self._state

    @property
    def state_name(self) -> str:
        return _STATE_NAMES[self._state]


class BreakerBoard:
    """All instances' breakers for one picker, plus the gauge bridge."""

    def __init__(
        self,
        config: BreakerConfig | None = None,
        *,
        on_state: "callable | None" = None,
        on_forget: "callable | None" = None,
    ):
        self.config = config or BreakerConfig()
        self._breakers: dict[int, CircuitBreaker] = {}
        # gauge hooks: on_state(instance_id, state_int) on every record/
        # allow touch, on_forget(instance_id) when a breaker is GC'd —
        # the EPP bridges them into dynamo_epp_breaker_state{instance}
        # (set / remove), so a departed worker's series disappears
        # instead of reporting a phantom state forever
        self.on_state = on_state
        self.on_forget = on_forget

    def _get(self, instance_id: int) -> CircuitBreaker:
        b = self._breakers.get(instance_id)
        if b is None:
            b = self._breakers[instance_id] = CircuitBreaker(self.config)
        return b

    def _publish(self, instance_id: int, b: CircuitBreaker) -> None:
        if self.on_state is not None:
            self.on_state(instance_id, b.state)

    def record(self, instance_id: int, ok: bool, latency_s: float = 0.0) -> None:
        b = self._get(instance_id)
        prev = b.state
        b.record(ok, latency_s)
        if b.state != prev:
            log.warning(
                "breaker %x: %s -> %s",
                instance_id, _STATE_NAMES[prev], b.state_name,
            )
        self._publish(instance_id, b)

    def allow(self, instance_id: int) -> bool:
        b = self._get(instance_id)
        out = b.allow()
        self._publish(instance_id, b)
        return out

    def state(self, instance_id: int) -> int:
        return self._get(instance_id).state

    def state_name(self, instance_id: int) -> str:
        return self._get(instance_id).state_name

    def knows(self, instance_id: int) -> bool:
        """True when this board already tracks the instance (without
        minting state for it — the /report membership guard)."""
        return instance_id in self._breakers

    def ejected(self) -> set[int]:
        """Instances currently excluded outright (OPEN inside cooldown).
        Half-open instances are NOT here — probes must reach them."""
        now = time.monotonic()
        return {
            iid for iid, b in self._breakers.items()
            if b.state == OPEN
            and now - b._opened_at < b.config.open_cooldown_s
        }

    def forget(self, live_ids: "set[int] | None" = None) -> None:
        """Drop breakers (and their gauge series, via on_forget) for
        instances that no longer exist (lease expiry/deregistration) so
        neither the board nor /metrics grows unbounded."""
        gone = [
            iid for iid in self._breakers
            if live_ids is None or iid not in live_ids
        ]
        for iid in gone:
            del self._breakers[iid]
            if self.on_forget is not None:
                self.on_forget(iid)
