"""Lean persistent-connection /pick transport ("pickline").

The cluster sim measured the aiohttp /pick p50 at ~4-6 ms over a
sub-millisecond routing decision — request parsing, header machinery,
and per-request connection bookkeeping dominating the data plane
(ROADMAP #7c). This module is the displacement: a raw-asyncio
newline-JSON protocol over long-lived TCP connections, one line per
pick, ids echoed so clients can pipeline::

    -> {"id": 1, "token_ids": [...], "request_id": "r1"}\n
    <- {"id": 1, "status": 200, "worker_id": ..., "endpoint": "h:p",
        "overlap_blocks": N}\n

Request bodies take the SAME fields as ``POST /pick`` (token_ids or
model+prompt); responses carry the /pick payload plus ``status`` (the
HTTP status the aiohttp route would have answered). The server is a
thin shell over ``EndpointPicker.pick_decision`` — one decision path,
two transports — and responses on a connection are written in request
order (pipelining overlaps the network RTT, not the decision).
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import time
from typing import Any

log = logging.getLogger("dynamo.gateway.pickline")

_MAX_LINE = 4 * 1024 * 1024  # generous: 128k-token prompts fit


class PickLineServer:
    """Serve pick decisions over newline-JSON on a persistent socket."""

    def __init__(self, picker, host: str = "127.0.0.1", port: int = 0):
        self.picker = picker
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None
        # live peer writers: close() must actively close them — on
        # py3.12.1+ Server.wait_closed() waits for every connection
        # handler, and pickline connections are long-lived BY DESIGN,
        # so a close() that only stops the listener would hang shutdown
        # until clients disconnect (the repo-wide Server.wait_closed
        # gotcha)
        self._conns: set[asyncio.StreamWriter] = set()

    async def start(self) -> "PickLineServer":
        self._server = await asyncio.start_server(
            self._serve_conn, self.host, self.port, limit=_MAX_LINE
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("pickline listening on %s:%d", self.host, self.port)
        return self

    async def _serve_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._conns.add(writer)
        try:
            while True:
                try:
                    line = await reader.readline()
                except (ValueError, ConnectionError, OSError):
                    break  # oversized line or dead peer: drop the conn
                if not line:
                    break
                resp = await self._handle_line(line)
                writer.write(json.dumps(resp).encode() + b"\n")
                await writer.drain()
        except (ConnectionError, OSError):
            pass  # peer vanished mid-write: nothing to answer
        finally:
            self._conns.discard(writer)
            writer.close()

    async def _handle_line(self, line: bytes) -> dict[str, Any]:
        try:
            body = json.loads(line)
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except ValueError as e:
            # malformed input answers in-band (the connection survives:
            # one bad line must not kill a pipelined neighbor's pick)
            return {"id": None, "status": 400, "error": f"bad line: {e}"}
        t0 = time.monotonic()
        try:
            status, payload, _hdrs = await self.picker.pick_decision(body)
        # answered in-band as a 500 (like the aiohttp route's
        # per-request error handling): an unexpected decision failure
        # must not tear down the connection and fail every pipelined
        # neighbor's pick
        except Exception as e:  # noqa: BLE001
            log.warning("pickline decision failed: %s", e, exc_info=True)
            status, payload = 500, {"error": f"pick failed: {e}"}
        self.picker.observe_pick(time.monotonic() - t0)
        return {"id": body.get("id"), "status": status, **payload}

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            for w in list(self._conns):
                w.close()  # wait_closed would block on live peers
            await self._server.wait_closed()


class PickLineClient:
    """Persistent pipelined pick client (one connection, in-order
    responses matched back to callers by request order)."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._ids = itertools.count(1)
        self._pending: "asyncio.Queue[asyncio.Future]" = asyncio.Queue()
        self._rx_task: asyncio.Task | None = None
        self._wlock = asyncio.Lock()
        # set the moment the rx loop exits (EOF, error, or cancel): a
        # pick() enqueued after that point would have nothing left to
        # resolve or fail its future — it must raise instead of hanging
        self._closed = False

    async def connect(self) -> "PickLineClient":
        from dynamo_tpu.runtime.context import spawn

        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, limit=_MAX_LINE
        )
        self._rx_task = spawn(self._rx_loop(), name="pickline-rx")
        return self

    async def _rx_loop(self) -> None:
        assert self._reader is not None
        try:
            while True:
                line = await self._reader.readline()
                if not line:
                    break
                fut = await self._pending.get()
                if not fut.done():
                    try:
                        fut.set_result(json.loads(line))
                    except ValueError as e:
                        fut.set_exception(
                            ConnectionError(f"bad pickline frame: {e}")
                        )
        except (ConnectionError, OSError) as e:
            log.warning("pickline rx loop died: %s", e)
        finally:
            # connection gone OR task cancelled (close()): fail whatever
            # is still waiting — a drain outside finally would be
            # skipped on cancellation and strand concurrent pick()ers.
            # _closed flips FIRST so a pick() racing this drain can
            # never enqueue a future nothing will ever resolve.
            self._closed = True
            while not self._pending.empty():
                fut = self._pending.get_nowait()
                if not fut.done():
                    fut.set_exception(ConnectionError("pickline closed"))

    async def pick(self, body: dict[str, Any]) -> dict[str, Any]:
        """One pick round-trip; concurrent callers pipeline on the one
        connection (responses are in request order by protocol)."""
        if self._writer is None:
            raise ConnectionError("pickline client not connected")
        if self._closed:
            # server hung up: the rx loop already drained its pending
            # queue; enqueueing now would block this caller forever
            raise ConnectionError("pickline connection lost")
        body = dict(body)
        body.setdefault("id", next(self._ids))
        # serialize BEFORE enqueueing the future: a dumps failure after
        # the put would leave an orphan future eating the next response
        # and desync every later pick on the connection
        frame = json.dumps(body).encode() + b"\n"
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        # dynalint: disable=DL009 -- write serialization point: the
        # (enqueue future, write frame) pair must be atomic per request
        # or a neighbor's interleaved write would desync the in-order
        # response matching; the guarded await is a socket drain, never
        # a wire-tainted call that could re-enter this lock
        async with self._wlock:
            if self._closed:  # rx loop died while we awaited the lock
                raise ConnectionError("pickline connection lost")
            await self._pending.put(fut)
            self._writer.write(frame)
            await self._writer.drain()
        return await fut

    async def close(self) -> None:
        if self._rx_task is not None:
            self._rx_task.cancel()
        if self._writer is not None:
            self._writer.close()
