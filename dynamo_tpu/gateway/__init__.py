"""Inference-gateway integration: the endpoint-picker (EPP) role of the
Kubernetes Gateway API inference extension (ref
deploy/inference-gateway/ — the reference patches the upstream EPP with
a ``dyn-kv`` plugin that calls the dynamo router; here the picker IS the
router, exposed over the HTTP contract gateways consume)."""

from dynamo_tpu.gateway.epp import EndpointPicker

__all__ = ["EndpointPicker"]
