from dynamo_tpu.gateway.epp import main

if __name__ == "__main__":
    raise SystemExit(main())
