"""Token-block hashing primitives.

Every KV-cache feature in the framework (router radix index, engine prefix
cache, KVBM block reuse, disagg KV handoff) keys off the same content hash of
token blocks, so workers and routers agree on block identity without
communicating.

Scheme (behavioral parity with reference lib/tokens/src/lib.rs and
lib/llm/src/tokens.rs: xxh3-chained block/sequence hashes with a salt):

- ``block_hash(tokens)``: xxh3_64 over the little-endian u32 token ids of one
  block. Position-independent (content identity).
- ``sequence_hash``: chained prefix identity -
  ``xxh3_64(parent_sequence_hash_u64le || block_hash_u64le, seed=salt)`` with
  the first block chaining from the salt hash. Two sequences share a
  sequence_hash iff they share the whole token prefix (and salt: model +
  lora + tenant separation).

``TokenBlockSequence`` incrementally maintains the block decomposition of a
growing/shrinking token stream (append, extend, truncate, unwind) so per-token
decode loops pay O(1) amortized hashing cost.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Sequence

import xxhash

__all__ = [
    "block_hash",
    "salt_hash",
    "chain_hash",
    "compute_block_hashes",
    "compute_sequence_hashes",
    "TokenBlock",
    "TokenBlockSequence",
]

_U64 = struct.Struct("<Q")
_NULL_SALT = 0


def _tokens_bytes(tokens: Sequence[int]) -> bytes:
    try:
        # one C-level pack of the whole block — ~40x the per-token
        # pack/join loop, byte-identical for in-range ids
        return struct.pack(f"<{len(tokens)}I", *tokens)
    except struct.error:
        # out-of-range id (negative / >u32): mask per token like before
        return b"".join(struct.pack("<I", t & 0xFFFFFFFF) for t in tokens)


def block_hash(tokens: Sequence[int], seed: int = 0) -> int:
    """Content hash of one block of token ids (order-sensitive)."""
    return xxhash.xxh3_64_intdigest(_tokens_bytes(tokens), seed=seed)


def salt_hash(salt: str | bytes | None) -> int:
    """Hash of the cache-partitioning salt (model id / lora id / tenant)."""
    if salt is None:
        return _NULL_SALT
    if isinstance(salt, str):
        salt = salt.encode("utf-8")
    return xxhash.xxh3_64_intdigest(salt)


def chain_hash(parent: int, child_block_hash: int) -> int:
    """Extend a sequence hash chain by one block."""
    return xxhash.xxh3_64_intdigest(
        _U64.pack(parent & 0xFFFFFFFFFFFFFFFF)
        + _U64.pack(child_block_hash & 0xFFFFFFFFFFFFFFFF)
    )


def compute_block_hashes(
    tokens: Sequence[int], block_size: int
) -> list[int]:
    """Block-content hashes of every *complete* block of ``tokens``."""
    n = len(tokens) // block_size
    return [
        block_hash(tokens[i * block_size : (i + 1) * block_size])
        for i in range(n)
    ]


def compute_sequence_hashes(
    tokens: Sequence[int], block_size: int, salt: str | bytes | None = None
) -> list[int]:
    """Chained prefix hashes of every complete block of ``tokens``."""
    parent = salt_hash(salt)
    out = []
    for bh in compute_block_hashes(tokens, block_size):
        parent = chain_hash(parent, bh)
        out.append(parent)
    return out


@dataclass(frozen=True)
class TokenBlock:
    """One complete, immutable block of tokens with its identity hashes."""

    tokens: tuple[int, ...]
    block_hash: int
    sequence_hash: int
    parent_sequence_hash: int
    block_index: int

    def __len__(self) -> int:
        return len(self.tokens)


@dataclass
class TokenBlockSequence:
    """Incremental block decomposition of a token sequence.

    Maintains complete blocks (hashed) plus a partial tail. Mirrors the
    extend/append/truncate/unwind surface of reference
    lib/llm/src/tokens.rs:479 ``TokenBlockSequence``.
    """

    block_size: int
    salt: str | bytes | None = None
    blocks: list[TokenBlock] = field(default_factory=list)
    partial: list[int] = field(default_factory=list)

    def __post_init__(self) -> None:
        if self.block_size <= 0:
            raise ValueError("block_size must be positive")
        self._salt_hash = salt_hash(self.salt)

    # -- observers ---------------------------------------------------------

    def __len__(self) -> int:
        return len(self.blocks) * self.block_size + len(self.partial)

    @property
    def num_complete_blocks(self) -> int:
        return len(self.blocks)

    @property
    def last_sequence_hash(self) -> int:
        return self.blocks[-1].sequence_hash if self.blocks else self._salt_hash

    def tokens(self) -> list[int]:
        out: list[int] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self.partial)
        return out

    def block_hashes(self) -> list[int]:
        return [b.block_hash for b in self.blocks]

    def sequence_hashes(self) -> list[int]:
        return [b.sequence_hash for b in self.blocks]

    def __iter__(self) -> Iterator[TokenBlock]:
        return iter(self.blocks)

    # -- mutators ----------------------------------------------------------

    def append(self, token: int) -> TokenBlock | None:
        """Append one token; returns the block if one was completed."""
        self.partial.append(token)
        if len(self.partial) == self.block_size:
            return self._seal()
        return None

    def extend(self, tokens: Iterable[int]) -> list[TokenBlock]:
        """Append many tokens; returns all blocks completed along the way."""
        sealed = []
        for t in tokens:
            b = self.append(t)
            if b is not None:
                sealed.append(b)
        return sealed

    def _seal(self) -> TokenBlock:
        bh = block_hash(self.partial)
        parent = self.last_sequence_hash
        blk = TokenBlock(
            tokens=tuple(self.partial),
            block_hash=bh,
            sequence_hash=chain_hash(parent, bh),
            parent_sequence_hash=parent,
            block_index=len(self.blocks),
        )
        self.blocks.append(blk)
        self.partial.clear()
        return blk

    def truncate(self, length: int) -> None:
        """Shrink to the first ``length`` tokens (unwinds sealed blocks)."""
        if length < 0 or length > len(self):
            raise ValueError(f"cannot truncate to {length} (len={len(self)})")
        keep_blocks, rem = divmod(length, self.block_size)
        if keep_blocks < len(self.blocks):
            reopened = list(self.blocks[keep_blocks].tokens[:rem])
            del self.blocks[keep_blocks:]
            self.partial = reopened
        else:
            del self.partial[rem:]

    def unwind(self, n: int = 1) -> None:
        """Remove the last ``n`` tokens."""
        self.truncate(len(self) - n)

    @classmethod
    def from_tokens(
        cls,
        tokens: Sequence[int],
        block_size: int,
        salt: str | bytes | None = None,
    ) -> "TokenBlockSequence":
        seq = cls(block_size=block_size, salt=salt)
        seq.extend(tokens)
        return seq
