"""The closed loop: telemetry -> predictor -> plan -> actuation.

``AutoscaleController.tick()`` is one full pass and the unit the sim and
tests drive directly; ``start()`` runs it on a wall-clock cadence for live
deployments. Each tick:

  1. snapshot the fleet's DemandSignal from :class:`FleetTelemetry`;
  2. feed the demand predictor and (when ``predict_ahead_ticks > 0``)
     plan for ``max(live, forecast)`` — the forecast may pre-scale a ramp
     but can never starve live load;
  3. run the PlanEngine control law; on a new revision, actuate through
     the backend and start convergence accounting (ticks until
     ``backend.observed()`` matches the plan).

The controller also scores its own forecasts: each tick the forecast made
``predict_ahead_ticks`` ago matures against the demand that actually
arrived, feeding the ``dynamo_autoscaler_predictor_error`` gauge — a
predictor that hurts is visible before it pages anyone.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque

from dynamo_tpu.autoscaler.metrics import (
    ACTUATION_SECONDS,
    CONVERGENCE_TICKS,
    PLAN_REVISIONS,
    PREDICTOR_ERROR,
    REPLICAS_ACTUAL,
    REPLICAS_DESIRED,
)
from dynamo_tpu.autoscaler.plan import (
    AutoscalerConfig,
    DemandSignal,
    PlanEngine,
    ScalePlan,
)
from dynamo_tpu.planner.predictor import make_predictor

log = logging.getLogger("dynamo.autoscaler")

__all__ = ["AutoscaleController"]

_DIMS = ("workers", "prefill", "router_shards")


class AutoscaleController:
    def __init__(
        self,
        cfg: AutoscalerConfig,
        telemetry,
        backend,
        *,
        initial_workers: int = 1,
        clock=time.monotonic,
    ):
        self.cfg = cfg
        self.telemetry = telemetry
        self.backend = backend
        self.clock = clock
        self.engine = PlanEngine(cfg, initial_workers=initial_workers)
        kwargs = {}
        if cfg.seasonal_period > 0:
            kind = "seasonal"
            kwargs["period"] = cfg.seasonal_period
        else:
            kind = cfg.predictor
        self.predictor = make_predictor(
            kind, window_size=cfg.predictor_window, **kwargs
        )
        self.plans: list[ScalePlan] = []
        self.converge_ticks: list[int] = []  # per converged plan
        self._converging: ScalePlan | None = None
        self._converge_age = 0
        self._pending_forecasts: deque[float] = deque()
        self.forecast_errors: list[float] = []
        self._task: asyncio.Task | None = None

    # -- one pass ----------------------------------------------------------

    async def tick(self) -> ScalePlan | None:
        sig = self.telemetry.signal()
        demand = sig.demand
        self.predictor.observe(demand)

        # score the forecast that was made predict_ahead_ticks ago and
        # has now matured against the observed demand
        if self._pending_forecasts and self.cfg.predict_ahead_ticks > 0:
            if len(self._pending_forecasts) > self.cfg.predict_ahead_ticks:
                matured = self._pending_forecasts.popleft()
                err = matured - demand
                self.forecast_errors.append(err)
                PREDICTOR_ERROR.set(err)

        planning_demand = demand
        if self.cfg.predict_ahead_ticks > 0:
            forecast = self.predictor.predict_ahead(
                self.cfg.predict_ahead_ticks
            )
            self._pending_forecasts.append(forecast)
            planning_demand = max(demand, forecast)

        plan_sig = DemandSignal(
            demand=planning_demand,
            prefill_queue_tokens=sig.prefill_queue_tokens,
            workers_observed=sig.workers_observed,
            prefill_observed=sig.prefill_observed,
            live_workers_reporting=sig.live_workers_reporting,
            quarantined_workers=sig.quarantined_workers,
        )
        plan = self.engine.plan(plan_sig, self.clock())
        if plan is not None:
            await self._actuate(plan)
        await self._track_convergence()
        return plan

    async def _actuate(self, plan: ScalePlan) -> None:
        self.plans.append(plan)
        PLAN_REVISIONS.inc()
        for dim, val in zip(_DIMS, plan.counts()):
            REPLICAS_DESIRED.labels(dim).set(val)
        t0 = time.perf_counter()
        await self.backend.apply(plan)
        ACTUATION_SECONDS.observe(time.perf_counter() - t0)
        log.info("plan r%d actuated: %s", plan.revision, plan.reason)
        self._converging = plan
        self._converge_age = 0

    async def _track_convergence(self) -> None:
        obs = await self.backend.observed()
        for dim, val in zip(_DIMS, obs):
            REPLICAS_ACTUAL.labels(dim).set(val)
        if self._converging is None:
            return
        self._converge_age += 1
        if obs == self._converging.counts():
            self.converge_ticks.append(self._converge_age)
            CONVERGENCE_TICKS.set(self._converge_age)
            self._converging = None

    # -- live loop ---------------------------------------------------------

    async def run(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.tick_interval_s)
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                log.exception("autoscaler tick failed")

    def start(self) -> "AutoscaleController":
        self._task = asyncio.get_running_loop().create_task(self.run())
        return self

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    # -- reporting ---------------------------------------------------------

    def report(self) -> dict:
        """Artifact-shaped summary of what the loop did."""
        errs = self.forecast_errors
        return {
            "plans": len(self.plans),
            "final": dict(zip(_DIMS, self.engine.current())),
            "converge_ticks_max": max(self.converge_ticks, default=0),
            "unconverged": self._converging is not None,
            "forecast_mae": (
                round(sum(abs(e) for e in errs) / len(errs), 3)
                if errs else None
            ),
            "revisions": [
                {"rev": p.revision, "workers": p.workers,
                 "prefill": p.prefill, "shards": p.router_shards,
                 "reason": p.reason}
                for p in self.plans
            ],
        }
