"""Actuation backends: where ScalePlans become real replicas.

Both backends speak the same two-method protocol so the controller (and
its convergence accounting) is backend-blind:

  ``apply(plan)``  — start converging the fleet toward the plan.
  ``observed()``   — current actual (workers, prefill, router_shards).

:class:`SimBackend` drives the chaos sim's MockFleet directly — scale-up
spawns mock workers on the live DistributedRuntime, scale-down drains them
through the withdraw-grace contract (key first, handler later), which is
what lets the diurnal scenario assert zero client-visible errors while
replicas fall.

:class:`K8sBackend` actuates through the EXISTING operator instead of
talking to kubelets itself: worker/prefill counts go to the planner's
desired-replicas hub key (the operator's reconciler already overrides
prefill/decode-role service replicas from it), and router shard count
patches the DGD's router-role service replicas directly. Scale-down
therefore rides the operator's SIGTERM -> drain path end to end.
"""

from __future__ import annotations

import logging
from typing import Protocol

from dynamo_tpu.autoscaler.plan import ScalePlan
from dynamo_tpu.planner.connector import DesiredReplicas, VirtualConnector

log = logging.getLogger("dynamo.autoscaler.backends")

__all__ = ["K8sBackend", "ScaleBackend", "SimBackend"]


class ScaleBackend(Protocol):
    async def apply(self, plan: ScalePlan) -> None: ...

    async def observed(self) -> tuple[int, int, int]:
        """(workers, prefill, router_shards) actually running."""
        ...


class SimBackend:
    """Actuate against a sim MockFleet (dynamo_tpu/sim/harness.py).

    Scale-up: ``fleet.launch_worker()`` per missing replica. Scale-down:
    drain the most recently launched workers (LIFO keeps the fleet's
    radix-warm veterans serving). Prefill/router dimensions have no sim
    twin yet; they are tracked as virtual counts so plans exercise the
    full law."""

    def __init__(self, fleet):
        self.fleet = fleet
        self.virtual_prefill = 0
        self.virtual_shards = 1
        self.drained = 0
        self.spawned = 0

    async def apply(self, plan: ScalePlan) -> None:
        alive = self.fleet.alive_workers()
        want = plan.workers
        if len(alive) < want:
            for _ in range(want - len(alive)):
                await self.fleet.launch_worker()
                self.spawned += 1
        elif len(alive) > want:
            for w in reversed(alive[-(len(alive) - want):]):
                await w.drain()
                self.drained += 1
        self.virtual_prefill = plan.prefill
        self.virtual_shards = plan.router_shards

    async def observed(self) -> tuple[int, int, int]:
        return (
            len(self.fleet.alive_workers()),
            self.virtual_prefill,
            self.virtual_shards,
        )


class K8sBackend:
    """Actuate through the operator: planner desired-replicas key for the
    prefill/decode pools, DGD patch for router-role service replicas."""

    ROUTER_ROLE = "router"

    def __init__(self, hub, namespace: str, dgd_name: str | None = None,
                 model: str | None = None):
        self.hub = hub
        self.namespace = namespace
        self.dgd_name = dgd_name
        self.connector = VirtualConnector(hub, namespace, model=model)

    async def apply(self, plan: ScalePlan) -> None:
        await self.connector.set_replicas(
            DesiredReplicas(prefill=plan.prefill, decode=plan.workers)
        )
        if self.dgd_name:
            await self._patch_router_shards(plan.router_shards)

    async def _patch_router_shards(self, shards: int) -> None:
        from dynamo_tpu.operator.graph import DynamoGraphDeployment

        dgd = await DynamoGraphDeployment.get(self.hub, self.dgd_name)
        if dgd is None:
            log.warning("DGD %s not found; router shards not actuated",
                        self.dgd_name)
            return
        changed = False
        for svc in dgd.services:
            if svc.role == self.ROUTER_ROLE and svc.replicas != shards:
                svc.replicas = shards
                changed = True
        if changed:
            await dgd.apply(self.hub)
            log.info("DGD %s router replicas -> %d", self.dgd_name, shards)

    async def observed(self) -> tuple[int, int, int]:
        """Actuals from the operator's status write-back (service roles
        come from the DGD spec); falls back to the desired key (converged
        assumption) when no status exists."""
        from dynamo_tpu.operator.graph import (
            DGD_STATUS_KEY,
            DynamoGraphDeployment,
        )

        workers = prefill = shards = 0
        status = (
            await self.hub.get(DGD_STATUS_KEY.format(name=self.dgd_name))
            if self.dgd_name else None
        )
        if status:
            dgd = await DynamoGraphDeployment.get(self.hub, self.dgd_name)
            roles = {s.name: s.role for s in dgd.services} if dgd else {}
            for name, st in (status.get("services") or {}).items():
                role = roles.get(name, "")
                ready = int(st.get("ready", 0))
                if role == "decode":
                    workers += ready
                elif role == "prefill":
                    prefill += ready
                elif role == self.ROUTER_ROLE:
                    shards += ready
            return (workers, prefill, max(shards, 1))
        desired = await self.hub.get(self.connector.key)
        if desired:
            return (
                int(desired.get("decode", 0)),
                int(desired.get("prefill", 0)),
                1,
            )
        return (0, 0, 1)
