"""Closed-loop SLA autoscaler.

Consumes live fleet telemetry (the same ForwardPassMetrics stream the KV
router schedules from, plus optional frontend scrape aggregates), turns it
into versioned :class:`~dynamo_tpu.autoscaler.plan.ScalePlan` documents
through a hysteresis/cooldown/bounded-step control law with optional
predictive pre-scaling, and actuates plans through a pluggable backend —
the chaos sim's :class:`~dynamo_tpu.autoscaler.backends.SimBackend` or the
operator-riding :class:`~dynamo_tpu.autoscaler.backends.K8sBackend`.

Scale-down always rides the drain contract: the instance key is withdrawn
(and the watch-propagation grace served) before any worker dies, so a
converging fleet never produces a client-visible error.
"""

from dynamo_tpu.autoscaler.backends import (
    K8sBackend,
    ScaleBackend,
    SimBackend,
)
from dynamo_tpu.autoscaler.controller import AutoscaleController
from dynamo_tpu.autoscaler.plan import (
    AutoscalerConfig,
    DemandSignal,
    PlanEngine,
    ScalePlan,
)
from dynamo_tpu.autoscaler.telemetry import FleetTelemetry

__all__ = [
    "AutoscaleController",
    "AutoscalerConfig",
    "DemandSignal",
    "FleetTelemetry",
    "K8sBackend",
    "PlanEngine",
    "ScaleBackend",
    "ScalePlan",
    "SimBackend",
]
