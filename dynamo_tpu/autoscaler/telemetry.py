"""Live fleet telemetry for the autoscaler.

:class:`FleetTelemetry` rides the SAME hub pub/sub stream the KV router
schedules from (``kv_metrics.{component}`` carrying per-worker
ForwardPassMetrics) — the autoscaler sees exactly the load signal the data
plane acts on, with no second scrape path to drift. Snapshots age out
workers whose metrics went quiet (crashed or drained), so demand never
counts a corpse's last report.
"""

from __future__ import annotations

import asyncio
import logging
import time

from dynamo_tpu.autoscaler.plan import DemandSignal
from dynamo_tpu.kv_router.protocols import (
    KV_METRICS_SUBJECT,
    ForwardPassMetrics,
)

log = logging.getLogger("dynamo.autoscaler.telemetry")

__all__ = ["FleetTelemetry"]


class FleetTelemetry:
    """Latest-per-worker ForwardPassMetrics view with staleness expiry."""

    def __init__(
        self,
        hub,
        component_path: str,
        *,
        stale_after_s: float = 2.0,
        clock=time.monotonic,
    ):
        self.hub = hub
        self.subject = KV_METRICS_SUBJECT.format(component=component_path)
        self.stale_after_s = stale_after_s
        self.clock = clock
        self._latest: dict[int, tuple[float, ForwardPassMetrics]] = {}
        self._task: asyncio.Task | None = None
        # soft-withdrawn workers (gray-failure quarantine): alive and
        # possibly still reporting metrics, but zero routable capacity.
        # Fed from instance-card state (runtime/health.py quarantine
        # metadata) by whoever watches the cards — the controller plans
        # a replacement per entry.
        self._quarantined: set[int] = set()

    def start(self) -> "FleetTelemetry":
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._consume()
            )
        return self

    async def _consume(self) -> None:
        try:
            async for _subj, payload in self.hub.subscribe(self.subject):
                try:
                    m = ForwardPassMetrics.from_dict(payload)
                except (KeyError, ValueError, TypeError):
                    log.warning("dropping malformed metrics: %r", payload)
                    continue
                self._latest[m.worker_id] = (self.clock(), m)
        except asyncio.CancelledError:
            pass
        except ConnectionError:
            log.warning("autoscaler metrics subscription lost")

    def ingest(self, m: ForwardPassMetrics) -> None:
        """Direct feed for tests/dryruns (no hub round-trip)."""
        self._latest[m.worker_id] = (self.clock(), m)

    def set_quarantined(self, worker_ids) -> None:
        """Replace the quarantined-worker set (from instance cards)."""
        self._quarantined = set(worker_ids)

    def quarantined(self) -> set[int]:
        return set(self._quarantined)

    def _fresh(self) -> list[ForwardPassMetrics]:
        cutoff = self.clock() - self.stale_after_s
        dead = [w for w, (ts, _) in self._latest.items() if ts < cutoff]
        for w in dead:
            del self._latest[w]
        return [m for _, m in self._latest.values()]

    def signal(self) -> DemandSignal:
        """Aggregate the fresh per-worker reports into one DemandSignal."""
        fresh = self._fresh()
        return DemandSignal(
            demand=float(
                sum(m.running_requests + m.waiting_requests for m in fresh)
            ),
            prefill_queue_tokens=float(
                sum(m.prefill_tokens_queued for m in fresh)
            ),
            workers_observed=len(fresh),
            live_workers_reporting=len(fresh),
            quarantined_workers=len(self._quarantined),
        )

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
