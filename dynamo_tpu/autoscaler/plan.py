"""Scale plans and the control law that emits them.

The law is deterministic and side-effect free — the controller feeds it a
:class:`DemandSignal` plus a clock reading and gets back either a new
versioned :class:`ScalePlan` or None (hold). All the stability machinery
lives here, per scaled dimension (decode workers, prefill workers, router
shards):

  hysteresis    — scaling up needs utilization >= ``scale_up_at``; scaling
                  down needs utilization <= ``scale_down_at``. The dead
                  band between them absorbs noise so the fleet doesn't
                  flap around a steady load.
  cooldowns     — per-direction refractory periods after the last move in
                  that dimension; downscale cooldowns default much longer
                  than upscale (adding capacity late costs latency,
                  removing it late costs only dollars).
  bounded steps — one plan moves a dimension at most ``max_step_up`` /
                  ``max_step_down`` replicas, so a telemetry glitch can't
                  order a fleet-halving in one tick.

Sizing itself is occupancy-targeted: desired = ceil(demand / (capacity per
replica × ``target_occupancy``)). Demand is concurrent work (running +
waiting requests for decode, queued prefill tokens for prefill); feeding a
k-step-ahead forecast instead of the live reading is what makes the loop
predictive — the law doesn't care where the number came from.
"""

from __future__ import annotations

import math
from dataclasses import asdict, dataclass, field

__all__ = ["AutoscalerConfig", "DemandSignal", "PlanEngine", "ScalePlan"]

PLAN_SCHEMA = "dynamo-scaleplan/v1"


@dataclass
class AutoscalerConfig:
    """Control-law knobs. Defaults are production-shaped (seconds-scale
    cooldowns); the sim dilates them via ``scaled(time_scale)``."""

    # capacity model
    slots_per_worker: int = 8  # decode slots (engine max_batch_size)
    target_occupancy: float = 0.75  # size for this fraction of slots busy
    prefill_tokens_per_worker: float = 8192.0  # queued tokens one prefill
    # worker is expected to absorb within a tick
    workers_per_router_shard: int = 64  # fleet size one /pick shard serves

    # bounds
    min_workers: int = 1
    max_workers: int = 64
    min_prefill: int = 0
    max_prefill: int = 16
    min_router_shards: int = 1
    max_router_shards: int = 8

    # hysteresis band (utilization = demand / (replicas * capacity))
    scale_up_at: float = 0.85
    scale_down_at: float = 0.5

    # per-direction cooldowns (seconds on the controller's clock)
    up_cooldown_s: float = 15.0
    down_cooldown_s: float = 120.0

    # bounded step sizes (replicas per plan, per dimension)
    max_step_up: int = 4
    max_step_down: int = 2

    # predictive pre-scaling: forecast demand this many ticks ahead and
    # plan for max(live, forecast). 0 = purely reactive.
    predict_ahead_ticks: int = 0
    predictor: str = "holt"
    predictor_window: int = 128
    seasonal_period: int = 0  # >0 selects the seasonal predictor

    # controller cadence (used by AutoscaleController.run, not the law)
    tick_interval_s: float = 5.0

    def scaled(self, time_scale: float) -> "AutoscalerConfig":
        """A copy with every time constant divided by ``time_scale`` — the
        sim runs the same law under time dilation."""
        out = AutoscalerConfig(**asdict(self))
        out.up_cooldown_s /= time_scale
        out.down_cooldown_s /= time_scale
        out.tick_interval_s /= time_scale
        return out


@dataclass
class DemandSignal:
    """One tick's aggregated fleet observation (possibly forecast)."""

    demand: float = 0.0  # concurrent decode work: running + waiting reqs
    prefill_queue_tokens: float = 0.0
    workers_observed: int = 0
    prefill_observed: int = 0
    live_workers_reporting: int = 0  # telemetry coverage, for the plan note
    # soft-withdrawn (quarantined) workers: alive but excluded from
    # routing — zero effective capacity, so the law holds replacements
    # on top of its load-based target (gray-failure immunity)
    quarantined_workers: int = 0


@dataclass
class ScalePlan:
    """One versioned scaling decision, self-describing enough to audit."""

    revision: int
    workers: int
    prefill: int
    router_shards: int
    reason: str = ""
    created_at: float = 0.0
    schema: str = PLAN_SCHEMA
    signal: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return asdict(self)

    def counts(self) -> tuple[int, int, int]:
        return (self.workers, self.prefill, self.router_shards)


@dataclass
class _DimState:
    """Per-dimension controller memory: current target + move timestamps."""

    current: int
    last_up: float = float("-inf")
    last_down: float = float("-inf")


class PlanEngine:
    """The pure control law. ``step()`` per dimension, ``plan()`` overall."""

    def __init__(self, cfg: AutoscalerConfig, *, initial_workers: int = 1,
                 initial_prefill: int = 0, initial_shards: int = 1):
        self.cfg = cfg
        self.revision = 0
        self._dims = {
            "workers": _DimState(initial_workers),
            "prefill": _DimState(initial_prefill),
            "shards": _DimState(initial_shards),
        }
        # quarantine replacement overlay: replicas held ON TOP of the
        # load-based workers target, one per quarantined worker. Kept
        # outside _DimState on purpose — replacing withdrawn capacity is
        # not load-driven scaling, so it bypasses the hysteresis band
        # and both cooldowns, and unwinds instantly on re-admission
        # without burning the downscale cooldown.
        self._quarantine_overlay = 0

    # -- single-dimension law ---------------------------------------------

    def _step(
        self,
        dim: str,
        demand: float,
        per_replica: float,
        lo: int,
        hi: int,
        now: float,
    ) -> tuple[int, str | None]:
        """Next target for one dimension; (value, reason|None if holding)."""
        cfg = self.cfg
        st = self._dims[dim]
        cap = max(per_replica, 1e-9)
        want = max(lo, min(hi, math.ceil(demand / (cap * cfg.target_occupancy))))
        cur = st.current
        if want == cur:
            return cur, None
        util = demand / (cap * max(cur, 1))
        if want > cur:
            if util < cfg.scale_up_at:
                return cur, None  # inside the dead band
            if now - st.last_up < cfg.up_cooldown_s:
                return cur, None
            nxt = min(want, cur + cfg.max_step_up, hi)
            if nxt == cur:
                return cur, None
            st.current, st.last_up = nxt, now
            return nxt, (
                f"{dim} {cur}->{nxt} (util {util:.2f} >= {cfg.scale_up_at})"
            )
        # scale down
        if util > cfg.scale_down_at:
            return cur, None
        if now - st.last_down < cfg.down_cooldown_s:
            return cur, None
        # an upscale also resets the downscale clock: never remove capacity
        # while the up-cooldown from a recent burst is still running
        if now - st.last_up < cfg.down_cooldown_s:
            return cur, None
        nxt = max(want, cur - cfg.max_step_down, lo)
        if nxt == cur:
            return cur, None
        st.current, st.last_down = nxt, now
        return nxt, (
            f"{dim} {cur}->{nxt} (util {util:.2f} <= {cfg.scale_down_at})"
        )

    # -- full plan ---------------------------------------------------------

    def plan(self, sig: DemandSignal, now: float) -> ScalePlan | None:
        """Run the law over every dimension; a new revision only when at
        least one dimension moved."""
        cfg = self.cfg
        reasons: list[str] = []
        workers, r = self._step(
            "workers", sig.demand, float(cfg.slots_per_worker),
            cfg.min_workers, cfg.max_workers, now,
        )
        if r:
            reasons.append(r)
        overlay = min(
            max(int(sig.quarantined_workers), 0),
            cfg.max_workers - workers,
        )
        if overlay != self._quarantine_overlay:
            reasons.append(
                f"workers quarantine overlay "
                f"{self._quarantine_overlay}->{overlay} "
                f"({sig.quarantined_workers} quarantined)"
            )
            self._quarantine_overlay = overlay
        workers += self._quarantine_overlay
        prefill, r = self._step(
            "prefill", sig.prefill_queue_tokens,
            cfg.prefill_tokens_per_worker,
            cfg.min_prefill, cfg.max_prefill, now,
        )
        if r:
            reasons.append(r)
        # router shards track fleet size, not load: demand = planned
        # workers, capacity = workers_per_router_shard
        shards, r = self._step(
            "shards", float(workers), float(cfg.workers_per_router_shard),
            cfg.min_router_shards, cfg.max_router_shards, now,
        )
        if r:
            reasons.append(r)
        if not reasons:
            return None
        self.revision += 1
        return ScalePlan(
            revision=self.revision,
            workers=workers,
            prefill=prefill,
            router_shards=shards,
            reason="; ".join(reasons),
            created_at=now,
            signal={
                "demand": round(sig.demand, 2),
                "prefill_queue_tokens": round(sig.prefill_queue_tokens, 1),
                "workers_observed": sig.workers_observed,
                "reporting": sig.live_workers_reporting,
                "quarantined": sig.quarantined_workers,
            },
        )

    def current(self) -> tuple[int, int, int]:
        return (
            self._dims["workers"].current + self._quarantine_overlay,
            self._dims["prefill"].current,
            self._dims["shards"].current,
        )
