"""Autoscaler observability (module registry, every /metrics surface)."""

from __future__ import annotations

from dynamo_tpu.runtime.metrics import MetricsRegistry, register_registry

_REG = MetricsRegistry()

PLAN_REVISIONS = _REG.counter(
    "autoscaler_plan_revisions_total",
    "scale plans emitted by the control law",
)
ACTUATION_SECONDS = _REG.histogram(
    "autoscaler_actuation_seconds",
    "wall time for the backend to apply one scale plan",
)
REPLICAS_DESIRED = _REG.gauge(
    "autoscaler_replicas_desired",
    "latest plan's target replicas by dimension",
    ["dimension"],
)
REPLICAS_ACTUAL = _REG.gauge(
    "autoscaler_replicas_actual",
    "backend-observed replicas by dimension",
    ["dimension"],
)
PREDICTOR_ERROR = _REG.gauge(
    "autoscaler_predictor_error",
    "forecast minus realized demand for the last matured forecast",
)
CONVERGENCE_TICKS = _REG.gauge(
    "autoscaler_convergence_ticks",
    "controller ticks the last plan took to converge observed to desired",
)

register_registry("autoscaler", _REG)
